"""Device-path circuit breaker: closed -> open -> half-open -> closed.

The device solver is one shared dependency (the chip, its runtime, the
tunnel to it) sitting under every allocate/preempt/reclaim dispatch. When
that dependency is sick, each cycle paying a dispatch-and-fail (XLA
runtime error, OOM, garbage readback) before falling back to the host
oracle turns a degraded chip into a degraded *scheduler*. The breaker
makes the fallback sticky: N consecutive device failures open it, the
session goes straight to the host oracle for a cool-down window, then ONE
half-open probe re-tries the device path — success closes the breaker,
failure re-opens it for another window. This is the standard breaker
state machine (the reference survives API-server flaps with the same
shape of containment: client-go backs off and re-lists instead of
hammering a failing dependency every cycle).

State transitions and fallback cycles are exported both as metrics
(``volcano_breaker_*``) and through ``Scheduler.last_cycle_timing``
(``breaker_state`` / ``breaker_fallback_cycles``), so "the scheduler is
running on the host oracle" is a first-class observable, not an
inference from latency.

Thread-safe; the clock is injectable so tests drive the cool-down
deterministically.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Tuple

log = logging.getLogger(__name__)

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

#: numeric encoding for gauges / last_cycle_timing
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}

#: bounded transition history (enough for any soak's open/close trace)
MAX_TRANSITIONS = 256


class CircuitBreaker:
    def __init__(self, name: str = "device-solver",
                 failure_threshold: int = 3, cooldown_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        self.name = name
        self.failure_threshold = max(1, int(failure_threshold))
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        #: (timestamp, from_state, to_state), bounded
        self.transitions: List[Tuple[float, str, str]] = []
        #: cycles served by the fallback path while not closed
        self.fallback_cycles = 0
        self._export_state()

    # -- state machine ----------------------------------------------------

    def _transition(self, to: str) -> None:
        """Caller holds the lock."""
        if self._state == to:
            return
        frm, self._state = self._state, to
        if len(self.transitions) < MAX_TRANSITIONS:
            self.transitions.append((self.clock(), frm, to))
        log.warning("circuit breaker %r: %s -> %s", self.name, frm, to)
        self._export_state()
        try:
            from ..metrics import metrics
            metrics.breaker_transitions_total.inc(
                labels={"breaker": self.name, "to": to})
        except Exception:  # noqa: BLE001 — metrics must not break the breaker
            pass

    def _export_state(self) -> None:
        try:
            from ..metrics import metrics
            metrics.breaker_state.set(STATE_CODES[self._state],
                                      labels={"breaker": self.name})
        except Exception:  # noqa: BLE001
            pass

    def allow(self) -> bool:
        """May the protected path be attempted right now? OPEN flips to
        HALF_OPEN (and allows the probe) once the cool-down elapsed."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self.clock() - self._opened_at >= self.cooldown_s:
                    self._transition(HALF_OPEN)
                    return True
                return False
            return True  # HALF_OPEN: the probe is in flight this cycle

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state != CLOSED:
                self._transition(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                # failed probe: straight back to a fresh cool-down
                self._opened_at = self.clock()
                self._transition(OPEN)
                return
            self._consecutive_failures += 1
            if self._state == CLOSED \
                    and self._consecutive_failures >= self.failure_threshold:
                self._opened_at = self.clock()
                self._transition(OPEN)

    # -- observability ----------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def state_code(self) -> int:
        return STATE_CODES[self.state]

    def count_fallback(self) -> None:
        """One scheduling cycle degraded to the fallback path."""
        with self._lock:
            self.fallback_cycles += 1
        try:
            from ..metrics import metrics
            metrics.breaker_fallback_cycles_total.inc(
                labels={"breaker": self.name})
        except Exception:  # noqa: BLE001
            pass

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return (f"CircuitBreaker({self.name!r}, state={self.state}, "
                f"failures={self._consecutive_failures}, "
                f"fallback_cycles={self.fallback_cycles})")
