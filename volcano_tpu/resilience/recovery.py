"""Crash-safe bind recovery: the write-ahead intent journal + the
takeover reconciliation pass.

The gang transaction boundary (framework/statement.py) decides a wave of
binds in session memory, then applies them through the cache effectors.
A crash between the decision and the last store write loses or
half-applies the wave: the reference survives this because the API
server holds pod truth and the next scheduler instance re-lists, but a
half-bound GANG is still wrong — some members run, the rest re-queue,
and nothing records what the dead leader had decided.

``BindIntentJournal`` closes that window Omega-style (PAPERS.md): before
any bind effect dispatches, the whole decided task->node map is
persisted as ONE ``bindintents`` store object carrying the writer's
lease fencing token. ``reconcile_bind_intents`` runs at leadership
acquisition (scheduler.run_with_leader_election): every surviving intent
is settled against pod truth — bindings the store already shows are
adopted, bindings the crash swallowed are re-driven with the NEW
leader's fencing token (completing the gang exactly as the dead leader
decided, so the recovered bind set is byte-identical to an
uninterrupted run), and the intent is deleted. Zero duplicates (only
unbound pods are re-driven) and zero lost gang members (every decided
binding either landed or is re-driven).

In steady state intents are garbage-collected by ``sweep()`` — called
once per scheduling cycle by the leader — which deletes an intent once
every binding is visible in the store (async effectors may lag a cycle)
or after two sweeps, whichever comes first. The journal is leader-only
(``SchedulerCache.bind_journal`` is None outside
run_with_leader_election), so non-HA embeddings pay nothing.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import List, Optional

from ..client.store import FencedError, NotFoundError
from ..models import BindIntent

log = logging.getLogger(__name__)

#: sweeps an intent survives with unbound pods before it is presumed
#: failed (its statement unwound session-side) and dropped — two, not
#: one, because pipelined async effectors may land a cycle late
SWEEP_GENERATIONS = 2


class BindIntentJournal:
    """Write-ahead journal of decided binds (see module docstring).

    ``cluster`` should be the writer's FENCED store handle so a deposed
    leader cannot journal new intents; reads pass through unfenced.
    """

    def __init__(self, cluster, identity: str = "",
                 clock=time.time):
        self.cluster = cluster
        self.identity = identity
        self.clock = clock
        self._seq = 0
        self._gen = 0
        #: intents THIS process wrote and has not yet confirmed:
        #: (name, gen, bindings)
        self._pending: List[tuple] = []

    def record(self, tasks) -> Optional[BindIntent]:
        """Persist one intent for a decided wave of allocate tasks
        (task.node_name already set). Returns the stored intent, or None
        for an empty wave. A FencedError propagates: a deposed leader
        must not journal, let alone bind."""
        bindings = [[t.namespace, t.name, t.node_name]
                    for t in tasks if t.node_name]
        if not bindings:
            return None
        fencing = None
        token_provider = getattr(self.cluster, "_token_provider", None)
        if token_provider is not None:
            fencing = token_provider()
        self._seq += 1
        intent = BindIntent(
            name=f"bi-{uuid.uuid4().hex[:8]}-{self._seq}",
            job=tasks[0].job,
            bindings=bindings,
            holder=(fencing or {}).get("holder", self.identity),
            epoch=int((fencing or {}).get("epoch", 0)),
            created=self.clock(),
        )
        self.cluster.create("bindintents", intent)
        self._pending.append((intent.name, self._gen, bindings))
        try:
            from ..metrics import metrics
            metrics.bind_intents_total.inc(labels={"event": "recorded"})
        except Exception:  # noqa: BLE001
            pass
        return intent

    def _settled(self, bindings) -> bool:
        for ns, name, _node in bindings:
            pod = self.cluster.try_get("pods", name, ns)
            if pod is not None and not pod.node_name:
                return False  # bind effect still in flight (or failed)
        return True

    def sweep(self) -> int:
        """Confirm-and-delete intents whose bindings are all visible in
        the store (the pod's own bound state IS the confirmation — no
        extra ack write races the async effectors), plus intents old
        enough that their effects must have either landed or unwound.
        Only touches intents THIS process recorded; a dead leader's
        intents are the recovery pass's job. Returns how many cleared."""
        self._gen += 1
        keep, cleared = [], 0
        for name, gen, bindings in self._pending:
            try:
                settled = self._settled(bindings)
            except Exception:  # noqa: BLE001 — store away: retry next cycle
                log.exception("bind-intent sweep could not read pod truth")
                keep.append((name, gen, bindings))
                continue
            if self._gen - gen < SWEEP_GENERATIONS and not settled:
                keep.append((name, gen, bindings))
                continue
            try:
                self.cluster.delete("bindintents", name)
            except NotFoundError:
                pass
            except FencedError:
                # deposed mid-sweep: stop writing; recovery cleans up
                keep.append((name, gen, bindings))
                break
            except Exception:  # noqa: BLE001 — retry next cycle
                log.exception("bind-intent sweep failed for %s", name)
                keep.append((name, gen, bindings))
                continue
            cleared += 1
        self._pending = keep
        if cleared:
            try:
                from ..metrics import metrics
                metrics.bind_intents_total.inc(
                    cleared, labels={"event": "confirmed"})
            except Exception:  # noqa: BLE001
                pass
        return cleared


def reconcile_bind_intents(cluster, fencing_token=None) -> dict:
    """The takeover reconciliation pass (run at leadership acquisition,
    BEFORE the first scheduling cycle).

    For every surviving intent, settle each decided binding against pod
    truth:

    - pod already bound to the intended node -> **adopted** (the crash
      happened post-collect; the watch stream folds it into the mirror);
    - pod exists, unbound -> **redriven**: the bind is applied now with
      the NEW leader's fencing token, completing the gang exactly as
      decided (zero lost members, and identical to the uninterrupted
      run's bind set);
    - pod bound elsewhere -> **conflict** (left alone — pod truth wins);
    - pod gone -> **lost** (retired/evicted between decision and
      recovery; nothing to do).

    The intent is deleted afterwards in every case. ``fencing_token`` is
    a dict or a provider callable; re-driven writes carry it so this
    pass is itself fenced out if leadership is lost mid-recovery.
    """
    token = fencing_token() if callable(fencing_token) else fencing_token
    summary = {"intents": 0, "adopted": 0, "redriven": 0,
               "conflicts": 0, "lost": 0}
    try:
        intents = cluster.list("bindintents")
    except Exception:  # noqa: BLE001 — store down: retry next acquisition
        log.exception("bind-intent recovery could not list intents")
        raise
    intents.sort(key=lambda i: (i.created, i.name))
    from ..metrics import metrics
    for intent in intents:
        summary["intents"] += 1
        for ns, name, node in intent.bindings:
            pod = cluster.try_get("pods", name, ns)
            if pod is None:
                outcome = "lost"
            elif pod.node_name == node:
                outcome = "adopted"
            elif pod.node_name:
                outcome = "conflict"
                log.warning(
                    "bind intent %s: pod %s/%s bound to %r, intent said "
                    "%r — pod truth wins", intent.name, ns, name,
                    pod.node_name, node)
            else:
                # the decided bind never reached the store: drive it now,
                # exactly as the dead leader's binder would have
                pod.node_name = node
                pod.phase = "Running"
                cluster.update("pods", pod, fencing=token)
                outcome = "redriven"
            key = "conflicts" if outcome == "conflict" else outcome
            summary[key] += 1
            metrics.recovery_intents_total.inc(
                labels={"outcome": outcome})
        try:
            cluster.delete("bindintents", intent.name, fencing=token)
        except NotFoundError:
            pass
    if summary["intents"]:
        log.warning("bind-intent recovery: %s", summary)
    return summary
