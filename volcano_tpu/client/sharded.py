"""Sharded cluster store: the partitioned front door.

Everything upstream of the solver used to funnel through ONE
single-process ClusterStore behind ONE TCP socket with ONE global
EventJournal — million-pod churn serialized before the solver ever ran
(ROADMAP item 3; BENCH_r03 measured ~1.2 s of burst ingest ahead of the
10k-pod solve). The reference system scales exactly this layer with
sharded controller workers and a 16-worker fan-out (SURVEY §2/§5). This
module is that partition:

``ShardedClusterStore`` splits the object space across N member stores
by deterministic ``(kind, namespace/name)`` hash routing (crc32 — the
same object lands on the same shard across restarts, which is what lets
each shard own its own durable lineage). Each shard owns its own lock,
its own resource_version sequence, its own watch-resume journal window
(served per shard by the router), and — when a data dir is set — its
own ``DurableClusterStore`` WAL + snapshot lineage in
``data_dir/shard-NNN/``, recovered independently: a shard replays only
its own WAL.

Concurrency model: a single top-level mutation mutex (``locked()``)
serializes commits end-to-end, exactly like the plain store's one lock —
in-process consumers (scheduler cache, controllers) keep the
delivered-under-the-lock, never-concurrent listener contract, and
fencing checks stay atomic with the writes they guard. The sharding
win is everything AROUND that mutex: reads take only the owning shard's
lock (a list of nodes doesn't wait out a pod wave's fsync), bulk waves
fsync every touched shard's WAL in PARALLEL (fsync releases the GIL —
N shards cost one fsync's wall time), the wire layer decodes/encodes
outside it, and watch delivery batches per frame (``bulk_watch``).

``ShardRouter`` serves a ShardedClusterStore over the EXISTING wire
protocol on one endpoint — ``RemoteClusterStore`` callers are
unchanged. Events carry a ``shard`` tag and the shard's own rv; resume
high-water marks generalize from ``{kind: rv}`` to
``{kind: {shard: rv}}`` (the PR 3/PR 9 ``since:`` machinery, per
shard). The ``bulk_watch`` op subscribes many kinds on one stream and
coalesces events into batched frames.

Fencing: the ``leases`` kind is PINNED to shard 0, and every member
shard delegates fence validation there (``_fence_arbiter``,
client/store.py) — lease arbitration stays a single-writer concern
while the fenced objects themselves spread across shards.

Fault points: ``shard_request`` fires per routed wire request in the
router (armed, it kills that connection the way a dropped shard link
would — the client's retry rules engage); ``shard_crash`` fires at the
sharded store's commit seam, once per mutation / per touched shard in a
bulk wave (arm ``exc:exit`` in a store subprocess to SIGKILL it with
some shards' sub-batches durable and others not — recovery must heal
every lineage). For in-process chaos, ``crash_shard(i)`` /
``recover_shard(i)`` kill exactly one shard: its ops raise
``ShardUnavailableError`` (in a bulk wave, only that shard's items fail)
while the other shards keep serving; recovery replays the shard's own
WAL and re-attaches every watcher.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import queue
import threading
import zlib
from typing import Any, Callable, Dict, List, Optional

from ..resilience.faultinject import faults
from .codec import encode
from .durable import DurableClusterStore
from .server import (
    WATCH_BATCH_MAX, WATCH_QUEUE_MAX, WATCH_SEND_TIMEOUT_S, DeltaEncoder,
    EventJournal, StoreServer, _Handler, pump_watch, send_frame,
)
from .store import (
    KINDS, ClusterStore, ShardUnavailableError, _key,
)

log = logging.getLogger(__name__)

#: kinds routed to shard 0 regardless of name: the lease bucket is the
#: fencing arbiter (every shard validates tokens against it), so it must
#: live in exactly one place
PINNED_KINDS = frozenset({"leases"})


def shard_for(kind: str, key: str, n_shards: int) -> int:
    """Deterministic routing: crc32 of ``kind/key`` mod N. Stable across
    processes and restarts (unlike ``hash()``, which is salted) — the
    property that lets each shard own a durable WAL lineage."""
    if n_shards <= 1 or kind in PINNED_KINDS:
        return 0
    return zlib.crc32(f"{kind}/{key}".encode()) % n_shards


class ShardedClusterStore:
    """See module docstring. Presents the full ClusterStore surface
    (create/update/apply/delete/get/try_get/list/watch/bulk_apply/
    locked/add_interceptor), so FencedStore, the webhook chain, the
    scheduler cache, the controllers and the wire dispatch all work
    against it unchanged."""

    def __init__(self, n_shards: int, data_dir: Optional[str] = None,
                 fsync: str = "every", fsync_interval_s: float = 0.05,
                 snapshot_every: int = 4096, keep_snapshots: int = 2):
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.n_shards = int(n_shards)
        self.data_dir = data_dir
        self.fsync_policy = fsync
        self.fsync_interval_s = fsync_interval_s
        self.snapshot_every = snapshot_every
        self.keep_snapshots = keep_snapshots
        # top-level mutation mutex: commits (route -> shard commit ->
        # listener delivery) serialize here, preserving the plain store's
        # atomic-write / serial-listener contract; locked() hands it to
        # consumers needing a frozen multi-read view
        self._mu = threading.RLock()
        self._interceptors: List[Callable] = []
        #: consumer/watch registry, so crash_shard/recover_shard can
        #: re-attach every subscription to a rebuilt shard:
        #: {"kind", "fn", "sharded", "wrapped": {shard_idx: wrapped_fn}}
        self._watchers: List[dict] = []
        self.shards: List[ClusterStore] = [
            self._make_shard(i) for i in range(self.n_shards)]
        self._down = [False] * self.n_shards
        self._rewire_arbiters()
        #: set by the ShardRouter: called (idx, new_shard) after a shard
        #: recovery so the router rebuilds that shard's resume journal
        self.on_shard_recovered: Optional[Callable[[int, Any], None]] = None

    # -- construction -------------------------------------------------------

    def _make_shard(self, i: int) -> ClusterStore:
        if self.data_dir:
            return DurableClusterStore(
                os.path.join(self.data_dir, f"shard-{i:03d}"),
                fsync=self.fsync_policy,
                fsync_interval_s=self.fsync_interval_s,
                snapshot_every=self.snapshot_every,
                keep_snapshots=self.keep_snapshots,
                shard=str(i))
        return ClusterStore()

    def _rewire_arbiters(self) -> None:
        for i, s in enumerate(self.shards):
            s._fence_arbiter = self.shards[0] if i != 0 else None

    # -- routing ------------------------------------------------------------

    def shard_of(self, kind: str, key: str) -> int:
        return shard_for(kind, key, self.n_shards)

    def _shard(self, idx: int) -> ClusterStore:
        if self._down[idx]:
            raise ShardUnavailableError(
                f"store shard {idx} is down (crashed, not yet recovered)")
        return self.shards[idx]

    def _route(self, kind: str, key: str) -> ClusterStore:
        return self._shard(self.shard_of(kind, key))

    # -- locking / clock ----------------------------------------------------

    def locked(self):
        """The top-level mutation mutex: holding it guarantees no write
        commits anywhere (any shard) — the consistent multi-read seam
        the scheduler cache's snapshot needs."""
        return self._mu

    @property
    def clock(self):
        return self.shards[0].clock

    @clock.setter
    def clock(self, fn) -> None:
        # fencing arbitration clock (HA tests drive lease expiry): the
        # arbiter is shard 0, but keep every shard consistent
        for s in self.shards:
            s.clock = fn

    def last_event_rv(self, kind: str) -> int:
        return max(s.last_event_rv(kind) for s in self.shards)

    # -- admission ----------------------------------------------------------

    def add_interceptor(self, fn) -> None:
        with self._mu:
            self._interceptors.append(fn)
            for s in self.shards:
                s.add_interceptor(fn)

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str, listener, replay: bool = True) -> None:
        """Subscribe to a kind on EVERY shard (replay in shard order,
        deterministic). Delivery runs under the mutation mutex, so a
        consumer listener is never invoked concurrently — the in-memory
        store's contract, preserved."""
        with self._mu:
            entry = {"kind": kind, "fn": listener, "sharded": False,
                     "wrapped": {}}
            self._watchers.append(entry)
            for i, s in enumerate(self.shards):
                if self._down[i]:
                    continue  # re-attached by recover_shard
                entry["wrapped"][i] = listener
                s.watch(kind, listener, replay=replay)

    def watch_sharded(self, kind: str, fn, replay: bool = True) -> None:
        """Shard-aware subscription (the router's seam): ``fn(shard_idx,
        rv, event, obj, old)`` with ``rv`` the owning shard's commit
        resource_version."""
        with self._mu:
            entry = {"kind": kind, "fn": fn, "sharded": True,
                     "wrapped": {}}
            self._watchers.append(entry)
            for i in range(self.n_shards):
                if self._down[i]:
                    continue
                wrapped = self._wrap_sharded(i, fn)
                entry["wrapped"][i] = wrapped
                self.shards[i].watch(kind, wrapped, replay=replay)

    def _wrap_sharded(self, idx: int, fn):
        shard = self.shards[idx]

        def wrapped(event, obj, old, _i=idx, _s=shard, _fn=fn):
            # runs under the shard lock: _rv is this event's commit rv
            _fn(_i, _s._rv, event, obj, old)
        return wrapped

    def _unwatch(self, kind: str, fn) -> None:
        with self._mu:
            for entry in list(self._watchers):
                if entry["kind"] == kind and entry["fn"] is fn:
                    for i, wrapped in entry["wrapped"].items():
                        self.shards[i].unwatch(kind, wrapped)
                    self._watchers.remove(entry)
                    return

    def unwatch(self, kind: str, listener) -> None:
        self._unwatch(kind, listener)

    def unwatch_sharded(self, kind: str, fn) -> None:
        self._unwatch(kind, fn)

    # -- CRUD ---------------------------------------------------------------

    def create(self, kind: str, obj, fencing: Optional[dict] = None):
        shard = self.shard_of(kind, _key(obj))
        with self._mu:
            faults.fire("shard_crash")
            return self._shard(shard).create(kind, obj, fencing=fencing)

    def update(self, kind: str, obj, fencing: Optional[dict] = None):
        shard = self.shard_of(kind, _key(obj))
        with self._mu:
            faults.fire("shard_crash")
            return self._shard(shard).update(kind, obj, fencing=fencing)

    def apply(self, kind: str, obj, fencing: Optional[dict] = None):
        shard = self.shard_of(kind, _key(obj))
        with self._mu:
            faults.fire("shard_crash")
            return self._shard(shard).apply(kind, obj, fencing=fencing)

    def delete(self, kind: str, name: str, namespace: Optional[str] = None,
               fencing: Optional[dict] = None):
        key = f"{namespace}/{name}" if namespace is not None else name
        shard = self.shard_of(kind, key)
        with self._mu:
            faults.fire("shard_crash")
            return self._shard(shard).delete(kind, name, namespace,
                                             fencing=fencing)

    def get(self, kind: str, name: str, namespace: Optional[str] = None):
        key = f"{namespace}/{name}" if namespace is not None else name
        return self._route(kind, key).get(kind, name, namespace)

    def try_get(self, kind: str, name: str, namespace: Optional[str] = None):
        from .store import NotFoundError
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None,
             name_glob: Optional[str] = None) -> List[Any]:
        out: List[Any] = []
        for i in range(self.n_shards):
            # a partial list during a shard outage would silently hide
            # that shard's objects from the scheduler — fail honestly
            out.extend(self._shard(i).list(kind, namespace, label_selector,
                                           name_glob))
        return out

    def bulk_apply(self, items, fencing: Optional[dict] = None) -> List[Any]:
        """Partitioned batch: items group per owning shard, each shard
        commits its sub-batch as ONE journal batch under the mutation
        mutex, and every touched durable WAL fsyncs in PARALLEL at the
        end (one fsync's wall time for N shards). Per-item containment
        is preserved — and extends to availability: a DOWN shard's items
        carry ShardUnavailableError while the other shards' items
        commit. Results reassemble in submission order."""
        items = list(items)
        results: List[Any] = [None] * len(items)
        by_shard: Dict[int, List] = collections.defaultdict(list)
        for idx, item in enumerate(items):
            try:
                by_shard[self.shard_of(item[0], _key(item[1]))].append(
                    (idx, item))
            except Exception as e:  # noqa: BLE001 — per-item containment
                results[idx] = e
        with self._mu:
            touched = []
            for shard_idx in sorted(by_shard):
                sub = by_shard[shard_idx]
                try:
                    shard = self._shard(shard_idx)
                    faults.fire("shard_crash")
                except Exception as e:  # noqa: BLE001 — shard down: its
                    for idx, _ in sub:   # items fail, the wave survives
                        results[idx] = e
                    continue
                res = shard.bulk_apply([it for _, it in sub],
                                       fencing=fencing, _sync=False)
                for (idx, _), r in zip(sub, res):
                    results[idx] = r
                touched.append(shard)
            self._sync_shards(touched)
        return results

    def _sync_shards(self, shards: List[ClusterStore]) -> None:
        """fsync every touched shard's WAL, in parallel when there is
        more than one (os.fsync releases the GIL, so N WALs on N files
        cost roughly one fsync of wall time)."""
        walled = [s for s in shards if getattr(s, "wal", None) is not None]
        if not walled:
            return
        if len(walled) == 1:
            walled[0].wal.maybe_sync()
            return
        errors: List[BaseException] = []

        def sync_one(s):
            try:
                s.wal.maybe_sync()
            except BaseException as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=sync_one, args=(s,),
                                    name=f"shard-fsync-{i}")
                   for i, s in enumerate(walled)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0]

    # -- durability ---------------------------------------------------------

    @property
    def recovered_records(self) -> int:
        return sum(getattr(s, "recovered_records", 0) for s in self.shards)

    @property
    def recovery_ms(self) -> float:
        return sum(getattr(s, "recovery_ms", 0.0) for s in self.shards)

    @property
    def _rv(self) -> int:
        # informational only (READY banners, introspection): the shards
        # own their real rv sequences
        return max(s._rv for s in self.shards)

    def snapshot(self) -> List[str]:
        with self._mu:
            return [s.snapshot() for s in self.shards
                    if hasattr(s, "snapshot")]

    def close(self) -> None:
        with self._mu:
            for i, s in enumerate(self.shards):
                if self._down[i]:
                    continue
                close = getattr(s, "close", None)
                if close is not None:
                    close()

    # -- single-shard chaos -------------------------------------------------

    def crash_shard(self, idx: int) -> None:
        """Kill one shard the way SIGKILL would: drop its in-memory
        state, abandon its WAL fd without fsync (appends were flushed to
        the OS per record, so process-kill durability semantics hold),
        and refuse its ops until recover_shard. The other shards keep
        serving."""
        with self._mu:
            if self._down[idx]:
                return
            shard = self.shards[idx]
            wal = getattr(shard, "wal", None)
            if wal is not None:
                try:
                    wal._f.close()  # raw close: no clean-shutdown fsync
                except OSError:
                    pass
                shard._wal = None
            self._down[idx] = True
            log.warning("store shard %d crashed (simulated)", idx)

    def recover_shard(self, idx: int) -> ClusterStore:
        """Rebuild a crashed shard: construction IS recovery (its own
        snapshot + WAL tail replay; in-memory shards recover empty),
        interceptors and every registered watcher re-attach, the fence
        arbiter re-wires, and the router (if any) is told to rebuild the
        shard's resume journal from the recovered tail."""
        with self._mu:
            if not self._down[idx]:
                return self.shards[idx]
            new = self._make_shard(idx)
            for fn in self._interceptors:
                new.add_interceptor(fn)
            self.shards[idx] = new
            self._rewire_arbiters()
            for entry in self._watchers:
                wrapped = (self._wrap_sharded(idx, entry["fn"])
                           if entry["sharded"] else entry["fn"])
                entry["wrapped"][idx] = wrapped
                # replay=False: everything recovered was observed before
                # the crash, and nothing committed while the shard was
                # down (its ops refused)
                new.watch(entry["kind"], wrapped, replay=False)
            self._down[idx] = False
            if self.on_shard_recovered is not None:
                self.on_shard_recovered(idx, new)
            log.info("store shard %d recovered (%d records replayed)",
                     idx, getattr(new, "recovered_records", 0))
            return new


# -- per-shard observability -------------------------------------------------


class _MeteredJournal(EventJournal):
    """EventJournal that accounts its shard's committed events and
    resume-window span (volcano_store_shard_* family)."""

    def __init__(self, store: ClusterStore, shard_label: str):
        self._labels = {"shard": shard_label}
        self._n_events = 0
        super().__init__(store)

    def _make_listener(self, kind: str):
        inner = super()._make_listener(kind)

        def listener(event, obj, old):
            inner(event, obj, old)
            self._n_events += 1
            try:
                from ..metrics import metrics
                metrics.store_shard_events_total.inc(labels=self._labels)
                if self._n_events % 64 == 0:
                    with self._lock:
                        span = sum(len(q) for q in self._events.values())
                    metrics.store_shard_journal_window.set(
                        span, labels=self._labels)
            except Exception:  # noqa: BLE001 — accounting only
                pass
        return listener


class _ShardJournals:
    """One resume journal per shard (each seeded from ITS shard's
    recovered WAL tail), plus per-shard watch-queue accounting shared by
    every stream the router serves."""

    def __init__(self, store: ShardedClusterStore):
        self.store = store
        self.journals = [_MeteredJournal(s, str(i))
                         for i, s in enumerate(store.shards)]
        self._lock = threading.Lock()
        self._pending = [0] * store.n_shards

    def since(self, shard_idx: int, kind: str, rv: int):
        return self.journals[shard_idx].since(kind, rv)

    def rebuild(self, idx: int, new_shard: ClusterStore) -> None:
        self.journals[idx].close()
        self.journals[idx] = _MeteredJournal(new_shard, str(idx))

    def close(self) -> None:
        for j in self.journals:
            j.close()

    # pending watch-queue depth, per shard, across all live streams.
    # The int bookkeeping is exact (drop accounting depends on it); the
    # GAUGE is sampled every 64th enqueue — label-key formatting per
    # event was measurable at tens of thousands of events/sec

    def _set_depth(self, idx: int) -> None:
        try:
            from ..metrics import metrics
            metrics.store_shard_watch_queue_depth.set(
                self._pending[idx], labels={"shard": str(idx)})
        except Exception:  # noqa: BLE001
            pass

    def enqueued(self, idx: int) -> None:
        with self._lock:
            self._pending[idx] += 1
            sample = self._pending[idx] % 64 == 0
        if sample:
            self._set_depth(idx)

    def sent(self, shard_idxs) -> None:
        counts = collections.Counter(shard_idxs)
        with self._lock:
            for idx, n in counts.items():
                self._pending[idx] = max(0, self._pending[idx] - n)
        for idx in counts:
            self._set_depth(idx)

    def dropped(self, counts: Dict[int, int]) -> None:
        try:
            from ..metrics import metrics
            for idx, n in counts.items():
                metrics.store_shard_dropped_total.inc(
                    n, labels={"shard": str(idx)})
        except Exception:  # noqa: BLE001
            pass
        self.sent(idx for idx, n in counts.items() for _ in range(n))


# -- the router --------------------------------------------------------------


class _WatchHub:
    """Encode once, fan out to every stream. A committed event used to
    be encoded per watch stream; with a scheduler cache, a controller
    manager and operator mirrors attached, that multiplied the commit
    path's encode cost by the watcher count. The hub subscribes ONE
    shard-aware listener per kind, encodes the event exactly once, and
    hands the same payload dict to every subscribed stream queue — the
    commit path is O(1) encodes + one queue append per stream, and zero
    encodes when nobody watches the kind."""

    def __init__(self, store: ShardedClusterStore):
        self.store = store
        #: per kind: [(enqueue, delta), ...] — one row per watch stream
        self._subs: Dict[str, List] = {k: [] for k in KINDS}
        self._attached: set = set()
        # one delta encoder per member shard, created eagerly: each owns
        # that shard's interning table + per-kind frame counters, mutated
        # only under the shard's commit notify (so no extra lock), and a
        # delta stream's synced snapshot covers every shard even before
        # the first event flows
        self.delta_encs = [DeltaEncoder() for _ in range(store.n_shards)]

    def subscribe(self, kind: str, enqueue, delta: bool = False) -> None:
        # caller holds store.locked(): the subscription is atomic with
        # the replay it just enqueued
        if kind not in self._attached:
            self._attached.add(kind)
            self.store.watch_sharded(kind, self._fan(kind), replay=False)
        self._subs[kind].append((enqueue, delta))

    def unsubscribe(self, kind: str, enqueue) -> None:
        self._subs[kind] = [s for s in self._subs[kind]
                            if s[0] is not enqueue]

    def synced_fields(self, kinds) -> dict:
        """The delta half of a stream's ``synced`` frame: per-kind,
        per-shard table snapshots + per-kind/per-shard ks baselines.
        Caller holds ``store.locked()`` so they are atomic with the
        subscription."""
        vtab: Dict[str, dict] = {}
        ks: Dict[str, Dict[str, int]] = {k: {} for k in kinds}
        for idx, enc in enumerate(self.delta_encs):
            for k in kinds:
                it = enc.interners.get(k)
                if it is not None:
                    vtab.setdefault(k, {})[str(idx)] = it.snapshot()
                ks[k][str(idx)] = enc.ks.get(k, 0)
        return {"delta": True, "vtab": vtab, "ks": ks}

    def _fan(self, kind: str):
        def fn(shard, rv, event, obj, old):
            subs = self._subs[kind]
            if not subs:
                return  # zero watchers: zero encodes
            obj_subs = [s[0] for s in subs if not s[1]]
            delta_subs = [s[0] for s in subs if s[1]]
            if obj_subs:
                payload = {"stream": "event", "kind": kind, "shard": shard,
                           "rv": rv, "event": event, "obj": encode(obj),
                           "old": encode(old) if old is not None else None}
                # serialize ONCE: every stream ships these same bytes
                # (pump_watch), so an extra watcher costs a queue append
                # and a socket write, not another encode+dumps
                payload["_raw"] = json.dumps(payload,
                                             separators=(",", ":"))
                for enq in obj_subs:
                    enq(payload)
            if delta_subs:
                dp = self.delta_encs[shard].payload(
                    kind, shard, rv, event, obj, old)
                try:
                    faults.fire("delta_frame")
                except Exception:  # noqa: BLE001 — injected drop
                    # the frame's ks was consumed but it never ships:
                    # every delta stream sees the gap and falls back
                    return
                for enq in delta_subs:
                    enq(dp)
                try:
                    faults.fire("delta_frame_dup")
                except Exception:  # noqa: BLE001 — injected dup
                    for enq in delta_subs:
                        enq(dp)  # same ks twice: typed refusal
        return fn


class _RouterHandler(_Handler):
    """The StoreServer wire protocol over a ShardedClusterStore: CRUD
    dispatch is inherited unchanged (the sharded store presents the same
    surface); watch serving is shard-aware — events carry a ``shard``
    tag and the owning shard's rv, resumes take ``{kind: {shard: rv}}``
    maps against the per-shard journals, and ``bulk_watch`` batches
    events per frame."""

    def _dispatch(self, store, op: str, req: dict) -> dict:
        # armed shard_request faults are ConnectionError-shaped: they
        # propagate out of handle()'s request loop and kill this
        # connection the way a dropped shard link would, so the client's
        # transport-retry rules (not its error handling) engage
        faults.fire("shard_request")
        return _Handler._dispatch(self, store, op, req)

    def _serve_watch(self, sock, store: ShardedClusterStore,
                     req: dict) -> None:
        kinds = req.get("kinds") or [req.get("kind")]
        bad = [k for k in kinds if k not in KINDS]
        if bad:
            send_frame(sock, {"ok": False, "error": "RuntimeError",
                              "message": f"unknown watch kinds {bad}"})
            return
        replay = bool(req.get("replay", True))
        since = req.get("since") or None
        batch_max = WATCH_BATCH_MAX if req.get("op") == "bulk_watch" else 1
        journals: _ShardJournals = self.server.journal  # type: ignore
        events: "queue.Queue" = queue.Queue(maxsize=WATCH_QUEUE_MAX)
        overflowed = threading.Event()
        sock.settimeout(WATCH_SEND_TIMEOUT_S)

        def enqueue(payload) -> None:
            if overflowed.is_set():
                return
            try:
                events.put_nowait(payload)
            except queue.Full:
                overflowed.set()
                return
            shard = payload.get("shard")
            if shard is not None:
                journals.enqueued(shard)

        def on_sent(batch) -> None:
            journals.sent(p["shard"] for p in batch)

        def drop_pending() -> None:
            # the stream is condemned: whatever is still queued will
            # never reach the watcher — account it per shard
            counts: Dict[int, int] = collections.Counter()
            while True:
                try:
                    p = events.get_nowait()
                except queue.Empty:
                    break
                if p.get("shard") is not None:
                    counts[p["shard"]] += 1
            journals.dropped(counts)

        hub: _WatchHub = self.server.hub  # type: ignore[attr-defined]
        # delta negotiation (fail-safe: object frames unless asked).
        # Replay adds below bypass the hub and stay object frames; only
        # live hub events ship delta-form with ks stamps
        delta = bool(req.get("delta"))
        hooked = []
        try:
            gap = None  # (kind, message)
            with store.locked():
                if since is not None:
                    for kind in kinds:
                        smap = since.get(kind)
                        if not isinstance(smap, dict):
                            # a scalar mark names one rv sequence; only
                            # a 1-shard store has exactly one
                            if store.n_shards == 1:
                                smap = {"0": smap}
                            else:
                                gap = (kind, "scalar resume mark against "
                                             f"{store.n_shards} shards")
                                break
                        for idx in range(store.n_shards):
                            rv = smap.get(str(idx))
                            rv = int(rv) if rv is not None else -1
                            missed = journals.since(idx, kind, rv)
                            if missed is None:
                                gap = (kind, f"shard {idx} window no "
                                             f"longer covers rv {rv}")
                                break
                            for erv, event, obj, old in missed:
                                enqueue({"stream": "event", "kind": kind,
                                         "shard": idx, "rv": erv,
                                         "event": event,
                                         "obj": encode(obj),
                                         "old": encode(old)
                                         if old is not None else None})
                        if gap is not None:
                            break
                if gap is None:
                    for kind in kinds:
                        if replay and since is None:
                            # list-then-watch: current objects as adds,
                            # shard by shard (the same order the
                            # in-process replay delivers)
                            for idx in range(store.n_shards):
                                if store._down[idx]:
                                    continue
                                sh = store.shards[idx]
                                rv = sh._rv
                                for obj in list(
                                        sh._buckets[kind].values()):
                                    enqueue({"stream": "event",
                                             "kind": kind, "shard": idx,
                                             "rv": rv, "event": "add",
                                             "obj": encode(obj),
                                             "old": None})
                        hub.subscribe(kind, enqueue, delta=delta)
                        hooked.append(kind)
                    sync_payload = {"stream": "synced", "rv": {
                        k: {str(i): store.shards[i].last_event_rv(k)
                            for i in range(store.n_shards)}
                        for k in kinds}}
                    if delta:
                        sync_payload.update(hub.synced_fields(kinds))
                    enqueue(sync_payload)
            if gap is not None:
                send_frame(sock, {
                    "ok": False, "error": "ResumeGapError",
                    "message": f"resume window for {gap[0]!r}: {gap[1]}"})
                return
            pump_watch(sock, events, overflowed, batch_max=batch_max,
                       on_sent=on_sent)
            log.warning("sharded watch stream overflowed %d events; "
                        "dropping the slow watcher", WATCH_QUEUE_MAX)
            self._count_drop()
            drop_pending()
        except OSError as e:
            import socket as _socket
            if isinstance(e, _socket.timeout):
                log.warning("sharded watch send stalled > %.0fs; dropping "
                            "the slow watcher", WATCH_SEND_TIMEOUT_S)
                self._count_drop()
            drop_pending()
        except ValueError:
            drop_pending()
        finally:
            for kind in hooked:
                hub.unsubscribe(kind, enqueue)

    @staticmethod
    def _count_drop() -> None:
        try:
            from ..metrics import metrics
            metrics.store_watch_dropped_total.inc()
        except Exception:  # noqa: BLE001 — accounting only
            pass


class ShardRouter(StoreServer):
    """A StoreServer whose backend is a ShardedClusterStore: one
    endpoint, the existing wire protocol, N shards behind it. Watchers
    get per-shard resume journals (each seeded from its shard's
    recovered WAL tail after a restart); a recovered shard's journal is
    rebuilt in place so live streams keep resuming."""

    handler_class = _RouterHandler

    def __init__(self, store: ShardedClusterStore, host: str = "127.0.0.1",
                 port: int = 0, token: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 tls_client_ca: Optional[str] = None, gate=None):
        super().__init__(store, host=host, port=port, token=token,
                         tls_cert=tls_cert, tls_key=tls_key,
                         tls_client_ca=tls_client_ca, gate=gate)
        # encode-once event fan-out shared by every watch stream
        self.hub = _WatchHub(store)
        self._server.hub = self.hub  # type: ignore[attr-defined]
        store.on_shard_recovered = self._on_shard_recovered

    def _make_journal(self, store):
        return _ShardJournals(store)

    def _on_shard_recovered(self, idx: int, new_shard) -> None:
        self.journal.rebuild(idx, new_shard)
