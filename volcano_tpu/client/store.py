"""In-memory cluster store: the API-server/informer seam.

The reference's "distributed communication backend" is the Kubernetes API
server plus client-go informer watch streams (SURVEY.md §2.9 item 8). The TPU
build replaces that with this process-local object store: typed buckets with
create/update/delete plus synchronous watch listeners. The scheduler cache,
controllers, webhooks and CLI all talk to a ClusterStore — in production the
same interface is backed by the gRPC sidecar to a real control plane; in
tests it is this in-memory implementation (the reference's fake-clientset
pattern, pkg/client/clientset/versioned/fake).

Admission plugs in as a create/update interceptor chain, mirroring the
webhook-manager's mutate/validate path.
"""

from __future__ import annotations

import fnmatch
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

Listener = Callable[[str, Any, Optional[Any]], None]  # (event, obj, old) event in {add, update, delete}
Interceptor = Callable[[str, str, Any], Any]  # (verb, kind, obj) -> obj (may raise AdmissionError)

KINDS = (
    "pods", "nodes", "podgroups", "queues", "priorityclasses",
    "resourcequotas", "jobs", "commands", "services", "configmaps",
    "secrets", "pvcs", "leases", "networkpolicies", "bindintents",
    "migrationintents",
)


class AdmissionError(Exception):
    """Raised by an admission interceptor to deny a write."""


class NotFoundError(KeyError):
    pass


class ConflictError(Exception):
    """Stale-object write (resource_version mismatch)."""


class FencedError(ConflictError):
    """A mutating write carried a stale lease fencing token: the writer is
    no longer (or never was) the lease holder the store knows, so the
    write is refused before touching any state. Subclasses ConflictError
    so untyped callers degrade to conflict handling (a fence IS an
    optimistic-concurrency rejection — of the writer's leadership rather
    than one object's version)."""


class ResumeGapError(Exception):
    """A watch resume asked for events the server can no longer replay
    (the journal's window moved past the client's high-water mark); the
    client falls back to its crash-only resync path."""


class ShardUnavailableError(Exception):
    """The store shard owning the requested object is down (crashed and
    not yet recovered). Per-item containment applies: in a bulk wave the
    down shard's items carry this error while the other shards' items
    commit — a dead shard costs its objects, not the wave."""


class ReplicaReadOnlyError(Exception):
    """A mutating op (create/update/apply/delete/bulk_apply) reached a
    read replica. Replicas serve list/get/watch with explicit staleness;
    every write — and with it fencing, leases and conditional-update
    arbitration — belongs to the primary, so the op fails CLOSED with
    this typed error instead of forking the object's history."""


class ReplicaLagError(Exception):
    """An rv-bounded read (``min_rv=`` on list) timed out before the
    replica applied that resource_version: the caller asked for
    read-your-writes freshness the replica cannot yet prove. The caller
    retries, raises its bound, or falls back to the primary."""


def _key(obj) -> str:
    ns = getattr(obj, "namespace", None)
    return f"{ns}/{obj.name}" if ns is not None else obj.name


class ClusterStore:
    """Typed object buckets + watch listeners. Writes serialize under one
    reentrant lock: the normal control flow is single-threaded (ordering
    deterministic, informer-delta semantics testable), but the job-updater
    fan-out and async effectors may write concurrently — each write
    (admission + mutation + listener delivery) is atomic under the lock,
    like one API-server request."""

    def __init__(self):
        import threading
        self._buckets: Dict[str, Dict[str, Any]] = {k: {} for k in KINDS}
        self._listeners: Dict[str, List[Listener]] = {k: [] for k in KINDS}
        self._interceptors: List[Interceptor] = []
        self._lock = threading.RLock()
        self._rv = 0
        # fencing arbitration clock (injectable so HA tests drive lease
        # expiry deterministically); only consulted for fenced writes
        self.clock: Callable[[], float] = time.time
        # global rv of the LAST event committed per kind — the watch-resume
        # seam (server.EventJournal) needs "has anything happened to this
        # kind since rv X" answerable without scanning a journal
        self._kind_rv: Dict[str, int] = {k: 0 for k in KINDS}

    def locked(self):
        """The store's write lock, for callers that need a consistent
        multi-read view against concurrent writers (e.g. the scheduler
        cache's snapshot — the reference's SchedulerCache.Mutex)."""
        return self._lock

    # -- admission ----------------------------------------------------------

    def add_interceptor(self, fn: Interceptor) -> None:
        self._interceptors.append(fn)

    def _admit(self, verb: str, kind: str, obj):
        for fn in self._interceptors:
            obj = fn(verb, kind, obj)
        return obj

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str, listener: Listener, replay: bool = True) -> None:
        """Subscribe to a bucket; replay=True delivers existing objects as
        adds first (informer list-then-watch semantics)."""
        with self._lock:
            self._listeners[kind].append(listener)
            if replay:
                for obj in list(self._buckets[kind].values()):
                    listener("add", obj, None)

    def unwatch(self, kind: str, listener: Listener) -> None:
        """Drop a subscription (a disconnected remote watcher must not keep
        receiving — and leaking — events; the in-process consumers never
        unsubscribe)."""
        with self._lock:
            try:
                self._listeners[kind].remove(listener)
            except ValueError:
                pass

    def _notify(self, kind: str, event: str, obj, old=None) -> None:
        self._kind_rv[kind] = self._rv
        for fn in list(self._listeners[kind]):
            fn(event, obj, old)

    def last_event_rv(self, kind: str) -> int:
        """Global resource_version at which this kind last committed an
        event (0 = never). Deletes count: they bump the global rv too."""
        with self._lock:
            return self._kind_rv[kind]

    # -- lease fencing ------------------------------------------------------

    def _check_fence(self, fencing: Optional[dict]) -> None:
        """Refuse a mutating write whose lease fencing token is stale.

        The token names the Lease the writer holds ({lock, holder, epoch});
        the STORE's current lease record arbitrates — a deposed leader's
        view of its own leadership is exactly what cannot be trusted. The
        write is fenced out when the lease is gone, held by someone else,
        re-acquired since (epoch = lease_transitions at acquisition), or
        expired by the store's own clock (split-brain where no standby has
        taken over yet must still not commit). Unfenced writes (no token)
        pass untouched: fencing is opt-in per writer via FencedStore.

        A sharded member store delegates to its fence arbiter (the shard
        holding the "leases" bucket, client/sharded.py): a pod write on
        shard 3 is arbitrated by the lease record on shard 0 — the
        sharded store's top-level mutation mutex makes the check atomic
        with the write, exactly like this store's own lock does."""
        if not fencing:
            return
        arbiter = getattr(self, "_fence_arbiter", None)
        if arbiter is not None:
            arbiter._check_fence(fencing)
            return
        name = fencing.get("lock", "")
        lease = self._buckets["leases"].get(name)
        holder = fencing.get("holder")
        epoch = fencing.get("epoch", -1)
        reason = None
        if lease is None:
            reason = f"lease {name!r} does not exist"
        elif lease.holder_identity != holder:
            reason = (f"lease {name!r} is held by "
                      f"{lease.holder_identity!r}, not {holder!r}")
        elif int(epoch) != int(lease.lease_transitions):
            reason = (f"lease {name!r} was re-acquired (epoch "
                      f"{lease.lease_transitions} != token epoch {epoch})")
        elif self.clock() - lease.renew_time > lease.lease_duration_seconds:
            reason = (f"lease {name!r} expired "
                      f"{self.clock() - lease.renew_time:.1f}s ago")
        if reason is not None:
            try:
                from ..metrics import metrics
                metrics.fenced_writes_total.inc(
                    labels={"holder": str(holder)})
            except Exception:  # noqa: BLE001 — accounting never masks the fence
                pass
            raise FencedError(f"write fenced: {reason}")

    # -- CRUD ---------------------------------------------------------------

    def create(self, kind: str, obj, fencing: Optional[dict] = None):
        with self._lock:
            self._check_fence(fencing)
            obj = self._admit("create", kind, obj)
            key = _key(obj)
            bucket = self._buckets[kind]
            if key in bucket:
                raise ConflictError(f"{kind} {key} already exists")
            self._rv += 1
            if hasattr(obj, "resource_version"):
                obj.resource_version = self._rv
            bucket[key] = obj
            self._notify(kind, "add", obj)
            return obj

    def update(self, kind: str, obj, fencing: Optional[dict] = None):
        with self._lock:
            self._check_fence(fencing)
            obj = self._admit("update", kind, obj)
            key = _key(obj)
            bucket = self._buckets[kind]
            old = bucket.get(key)
            if old is None:
                raise NotFoundError(f"{kind} {key} not found")
            # Optimistic concurrency: a writer presenting a stale copy
            # loses (k8s resourceVersion precondition). Only enforced when
            # the caller hands in a *different* object carrying a version —
            # in-place updates of the stored object (the informer-cache
            # pattern) and fresh objects with version 0 carry no
            # precondition.
            if (obj is not old
                    and getattr(obj, "resource_version", 0)
                    and getattr(old, "resource_version", 0)
                    and obj.resource_version != old.resource_version):
                raise ConflictError(
                    f"{kind} {key}: stale resource_version "
                    f"{obj.resource_version} != {old.resource_version}")
            self._rv += 1
            if hasattr(obj, "resource_version"):
                obj.resource_version = self._rv
            bucket[key] = obj
            self._notify(kind, "update", obj, old)
            return obj

    def apply(self, kind: str, obj, fencing: Optional[dict] = None):
        """Create-or-update."""
        with self._lock:
            key = _key(obj)
            if key in self._buckets[kind]:
                return self.update(kind, obj, fencing=fencing)
            return self.create(kind, obj, fencing=fencing)

    def delete(self, kind: str, name: str, namespace: Optional[str] = None,
               fencing: Optional[dict] = None):
        with self._lock:
            self._check_fence(fencing)
            key = f"{namespace}/{name}" if namespace is not None else name
            bucket = self._buckets[kind]
            obj = bucket.pop(key, None)
            if obj is None:
                raise NotFoundError(f"{kind} {key} not found")
            self._admit("delete", kind, obj)
            # deletes advance the global rv like every other event, so a
            # resuming watcher's high-water mark orders them correctly
            self._rv += 1
            self._notify(kind, "delete", obj)
            return obj

    def bulk_apply(self, items, fencing: Optional[dict] = None,
                   _sync: bool = True) -> List[Any]:
        """Batch mutation: many objects under ONE lock hold (and, on the
        durable store, one journal batch — a single fsync covers the
        whole wave). ``items`` is an iterable of ``(kind, obj)`` or
        ``(kind, obj, verb)`` with verb in {"apply", "create",
        "update"}; default "apply".

        Per-item containment, not a transaction: each object commits (or
        fails) independently, in order, and the result list carries the
        applied object OR the exception instance at that item's position
        — a rejected pod in a 500-pod ingest wave costs that pod, not
        the wave. The wire op (StoreServer ``bulk_apply``) carries the
        same contract in one frame each way.

        ``_sync=False`` defers the batch-end fsync to the caller (the
        sharded store runs one batch per touched shard and then fsyncs
        every touched WAL in parallel — N shards cost one fsync's wall
        time, not N)."""
        results: List[Any] = []
        with self._lock:
            self._batch_begin()
            try:
                for item in items:
                    kind, obj = item[0], item[1]
                    verb = item[2] if len(item) > 2 else "apply"
                    try:
                        if verb == "create":
                            results.append(self.create(kind, obj,
                                                       fencing=fencing))
                        elif verb == "update":
                            results.append(self.update(kind, obj,
                                                       fencing=fencing))
                        elif verb == "apply":
                            results.append(self.apply(kind, obj,
                                                      fencing=fencing))
                        else:
                            raise ValueError(
                                f"bulk_apply verb {verb!r} not in "
                                "('apply', 'create', 'update')")
                    except Exception as e:  # noqa: BLE001 — per-item result
                        results.append(e)
            finally:
                self._batch_end(sync=_sync)
        return results

    def _batch_begin(self) -> None:
        """Journal-batch seam (no-op in memory; the durable store defers
        fsync until _batch_end so a bulk write costs one sync)."""

    def _batch_end(self, sync: bool = True) -> None:
        pass

    def get(self, kind: str, name: str, namespace: Optional[str] = None):
        with self._lock:
            key = f"{namespace}/{name}" if namespace is not None else name
            obj = self._buckets[kind].get(key)
            if obj is None:
                raise NotFoundError(f"{kind} {key} not found")
            return obj

    def try_get(self, kind: str, name: str, namespace: Optional[str] = None):
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None,
             name_glob: Optional[str] = None) -> List[Any]:
        out = []
        with self._lock:
            objs = list(self._buckets[kind].values())
        for obj in objs:
            if namespace is not None and getattr(obj, "namespace", None) != namespace:
                continue
            if label_selector:
                labels = getattr(obj, "labels", {}) or {}
                if any(labels.get(k) != v for k, v in label_selector.items()):
                    continue
            if name_glob is not None and not fnmatch.fnmatch(obj.name, name_glob):
                continue
            out.append(obj)
        return out


class FencedStore:
    """Store proxy attaching the writer's lease fencing token to every
    mutating op (create/update/apply/delete); reads and watch pass
    through untouched. ``token_provider`` returns the current token
    ({lock, holder, epoch}) or None when the writer holds no lease — in
    which case mutations FAIL CLOSED with FencedError locally: a deposed
    leader whose elector already observed the loss must not fall back to
    writing unfenced. Wraps both the in-memory ClusterStore (which
    validates under its own lock) and RemoteClusterStore (which carries
    the token on the wire for the StoreServer to validate)."""

    def __init__(self, store, token_provider: Callable[[], Optional[dict]]):
        self._store = store
        self._token_provider = token_provider

    def _token(self) -> dict:
        token = self._token_provider()
        if token is None:
            raise FencedError(
                "write fenced: this writer holds no lease")
        return token

    def create(self, kind: str, obj):
        return self._store.create(kind, obj, fencing=self._token())

    def update(self, kind: str, obj):
        return self._store.update(kind, obj, fencing=self._token())

    def apply(self, kind: str, obj):
        return self._store.apply(kind, obj, fencing=self._token())

    def delete(self, kind: str, name: str, namespace: Optional[str] = None):
        return self._store.delete(kind, name, namespace,
                                  fencing=self._token())

    def bulk_apply(self, items):
        return self._store.bulk_apply(items, fencing=self._token())

    def __getattr__(self, name):
        # reads (get/try_get/list/watch/locked/...) forward unfenced
        return getattr(self._store, name)
