"""ReadTierStore: split one store surface into a fenced write path and
a replica read path (ROADMAP item 1, the controllers-off-the-primary
half of fan-out trees).

Controllers, standby mirrors and dashboards are steady-state READERS:
their list/watch/bulk_watch volume dwarfs their mutations, and PR 12
measured what happens when all of it lands on the writer quorum. This
wrapper sends every mutation to ``write_store`` (the primary — fencing,
leases and conditional-write arbitration untouched) and every read to
``read_store`` (a replica, in-process mirror or remote endpoint), with
the staleness contract made explicit instead of hoped for:

- **read-your-writes via min_rv**: each acked mutation's ``applied_rv``
  stamp advances a high-water mark, and every subsequent read demands
  it (``min_rv=``) — the replica blocks until it has applied that rv or
  fails typed, so a controller can never act on a view that predates
  its own last sync. The stamp is read from the write client's
  ``applied_hwm()`` when it keeps one (RemoteClusterStore), else from
  the in-process store's rv under its lock.
- **primary kinds**: coordination state that arbitrates LIVENESS —
  leases and the takeover-recovery intents — is always read from the
  primary. min_rv only bounds this wrapper's OWN writes; a lease
  renewed by another process must be seen fresh, not eventually.
- **typed fallback**: a lagging (ReplicaLagError) or unreachable read
  replica degrades reads to the primary, counted, never silently
  stale. Other typed errors (NotFoundError, ...) are real answers and
  propagate.

``FencedStore`` composes on top (it wraps mutations with the fencing
token and forwards reads via ``__getattr__``), so the HA controller
manager stacks FencedStore(ReadTierStore(primary, replica)) without
either wrapper knowing about the other.
"""

from __future__ import annotations

import inspect
import logging
import threading
from typing import Optional

from .server import applied_rv_of
from .store import ReplicaLagError

log = logging.getLogger(__name__)

#: kinds whose reads always go to the primary: they arbitrate liveness
#: (leases) or takeover recovery (intents), where another writer's
#: update must be seen fresh — a min_rv bound only covers OUR writes
PRIMARY_KINDS = ("leases", "bindintents", "migrationintents")

#: default block budget a read demands from the replica before the
#: typed fallback to the primary engages
DEFAULT_READ_WAIT_S = 5.0


def _accepts_min_rv(fn) -> bool:
    try:
        return "min_rv" in inspect.signature(fn).parameters
    except (TypeError, ValueError):  # builtins/mocks: assume not
        return False


class ReadTierStore:
    """See module docstring. ``write_store`` is the primary surface
    (in-process store or RemoteClusterStore to it); ``read_store`` is
    the replica surface (a ReplicaStore's ``.store`` mirror, or a
    RemoteClusterStore to any replica in the tree)."""

    def __init__(self, write_store, read_store,
                 primary_kinds=PRIMARY_KINDS,
                 wait_s: float = DEFAULT_READ_WAIT_S):
        self.write_store = write_store
        self.read_store = read_store
        self.primary_kinds = tuple(primary_kinds)
        self.wait_s = float(wait_s)
        self._hwm_lock = threading.Lock()
        self._hwm = None
        self._read_min_rv = _accepts_min_rv(read_store.list)
        self.reads_replica = 0    # reads the replica answered
        self.read_fallbacks = 0   # reads that degraded to the primary

    # -- the read-your-writes bound ------------------------------------------

    def _note_write(self) -> None:
        """Advance the hwm to at least this mutation's applied rv."""
        hwm_fn = getattr(self.write_store, "applied_hwm", None)
        if hwm_fn is not None:
            rv = hwm_fn()
        else:
            with self.write_store.locked():
                rv = applied_rv_of(self.write_store)
        if rv is None:
            return
        with self._hwm_lock:
            self._hwm = self._merge_hwm(self._hwm, rv)

    @staticmethod
    def _merge_hwm(cur, new):
        if cur is None:
            return new
        if isinstance(new, dict) or isinstance(cur, dict):
            cur = cur if isinstance(cur, dict) else {"0": int(cur)}
            new = new if isinstance(new, dict) else {"0": int(new)}
            out = dict(cur)
            for sh, rv in new.items():
                out[sh] = max(int(rv), int(out.get(sh, 0)))
            return out
        return max(int(cur), int(new))

    def applied_hwm(self):
        with self._hwm_lock:
            return self._hwm

    # -- mutations: the fenced write path ------------------------------------

    def create(self, kind, obj, fencing=None):
        out = self.write_store.create(kind, obj, fencing=fencing)
        self._note_write()
        return out

    def update(self, kind, obj, fencing=None):
        out = self.write_store.update(kind, obj, fencing=fencing)
        self._note_write()
        return out

    def apply(self, kind, obj, fencing=None):
        out = self.write_store.apply(kind, obj, fencing=fencing)
        self._note_write()
        return out

    def delete(self, kind, name, namespace=None, fencing=None):
        out = self.write_store.delete(kind, name, namespace,
                                      fencing=fencing)
        self._note_write()
        return out

    def bulk_apply(self, items, fencing=None, **kw):
        out = self.write_store.bulk_apply(items, fencing=fencing, **kw)
        self._note_write()
        return out

    # -- reads: the replica path ---------------------------------------------

    def _read(self, kind: str, op, primary_op):
        """One read: the replica with min_rv=hwm, the primary for
        primary kinds or after a typed/unreachable replica failure."""
        if kind in self.primary_kinds:
            return primary_op()
        try:
            if self._read_min_rv:
                resp = op(min_rv=self.applied_hwm())
            else:
                resp = op()
        except (ReplicaLagError, ConnectionError, OSError) as e:
            self.read_fallbacks += 1
            log.warning("read-tier %s read failed (%s: %s); falling "
                        "back to the primary", kind, type(e).__name__, e)
            return primary_op()
        self.reads_replica += 1
        return resp

    def get(self, kind, name, namespace=None):
        def replica_get(min_rv=None):
            if min_rv is not None:
                return self.read_store.get(kind, name, namespace,
                                           min_rv=min_rv,
                                           wait_s=self.wait_s)
            return self.read_store.get(kind, name, namespace)

        return self._read(
            kind, replica_get,
            lambda: self.write_store.get(kind, name, namespace))

    def try_get(self, kind, name, namespace=None):
        from .store import NotFoundError
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(self, kind, namespace=None, label_selector=None,
             name_glob=None):
        def replica_list(min_rv=None):
            if min_rv is not None:
                return self.read_store.list(kind, namespace,
                                            label_selector, name_glob,
                                            min_rv=min_rv,
                                            wait_s=self.wait_s)
            return self.read_store.list(kind, namespace, label_selector,
                                        name_glob)

        return self._read(
            kind, replica_list,
            lambda: self.write_store.list(kind, namespace,
                                          label_selector, name_glob))

    # -- streams + locks: the replica's mirror is the subscription -----------

    def watch(self, kind, listener, replay: bool = True):
        return self.read_store.watch(kind, listener, replay=replay)

    def unwatch(self, kind, listener):
        return self.read_store.unwatch(kind, listener)

    def bulk_watch(self, subs, **kw):
        return self.read_store.bulk_watch(subs, **kw)

    def locked(self):
        return self.read_store.locked()

    def last_event_rv(self, kind: str) -> int:
        return self.read_store.last_event_rv(kind)

    def __getattr__(self, name):
        # everything else (interceptors, fencing internals, clock, the
        # lease arbitration surface) belongs to the primary
        return getattr(self.write_store, name)

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return (f"ReadTierStore(write={self.write_store!r}, "
                f"read={self.read_store!r})")


__all__ = ["ReadTierStore", "PRIMARY_KINDS", "DEFAULT_READ_WAIT_S"]
