"""StoreServer: the ClusterStore served over TCP.

The reference's control-plane components are separate processes meeting at
the Kubernetes API server (cmd/cli/vcctl.go:44-49 CRUDs from anywhere;
pkg/scheduler/cache/cache.go:319-402 watches ten informer streams). This
module is the TPU build's API-server seam as an actual server: a
length-prefixed JSON protocol exposing create/update/apply/delete/get/
list/watch on one authoritative in-process ClusterStore, so `vcctl
--server`, remote scheduler caches and HA standbys can drive a deployed
control plane over the wire.

Protocol: 4-byte magic "VCS1", then frames of <u32 length><JSON bytes>.
Request ops mirror the ClusterStore surface; errors return their class
name and re-raise as the same class client-side. A `watch` request turns
the connection into an event stream: replayed adds, then {"stream":
"synced", "rv": {...}}, then live events (each carrying the global
resource_version it committed at) as they commit. A watch request with
"since": {kind: rv} instead resumes from that high-water mark: the
per-kind EventJournal replays exactly the missed events (client-go's
reflector re-watch at a ResourceVersion), or refuses with ResumeGapError
when its bounded window no longer covers them — the client then falls
back to its crash-only path. Frame size is capped so a corrupt or
hostile peer cannot drive unbounded allocation (same rule as the solver
sidecar, parallel/sidecar.py:35-53).
"""

from __future__ import annotations

import collections
import json
import logging
import queue
import socket
import socketserver
import struct
import threading
from typing import Dict, Optional

import hmac

from ..resilience.faultinject import faults
from ..resilience.overload import AdmissionGate, OverloadedError
from .codec import (
    Interner, decode, delta_diff, delta_value, encode, object_key,
)
from .store import (
    KINDS, AdmissionError, ClusterStore, ConflictError, FencedError,
    NotFoundError, ReplicaLagError, ReplicaReadOnlyError, ResumeGapError,
    ShardUnavailableError,
)

log = logging.getLogger(__name__)

MAGIC = b"VCS1"
MAX_FRAME_BYTES = 64 << 20  # a 10k-pod wave of Jobs is ~10 MB of JSON
WATCH_QUEUE_MAX = 65536     # pending events before a slow watcher drops
WATCH_SEND_TIMEOUT_S = 30.0
TLS_HANDSHAKE_TIMEOUT_S = 10.0
JOURNAL_CAPACITY = 4096     # per-kind resume window (events)
WATCH_BATCH_MAX = 256       # events coalesced per bulk_watch frame
SHIP_BATCH_MAX = 256        # WAL records coalesced per ship frame

_ERRORS = {
    "ConflictError": ConflictError,
    "NotFoundError": NotFoundError,
    "AdmissionError": AdmissionError,
    "ResumeGapError": ResumeGapError,
    "FencedError": FencedError,
    "ShardUnavailableError": ShardUnavailableError,
    "ReplicaReadOnlyError": ReplicaReadOnlyError,
    "ReplicaLagError": ReplicaLagError,
    "OverloadedError": OverloadedError,
}


def applied_rv_of(store) -> object:
    """The store's committed resource_version(s) for response stamping:
    the global rv scalar, or — sharded — the ``{shard: rv}`` map (each
    shard owns its own sequence). Call under ``store.locked()`` so the
    stamp is consistent with the reads it rides alongside."""
    shards = getattr(store, "shards", None)
    if shards is not None:
        return {str(i): s._rv for i, s in enumerate(shards)}
    return store._rv


def _ship_source(store, shard) -> "ClusterStore":
    """Resolve a ship/bootstrap request to the store that owns the WAL
    lineage: the store itself, or — behind a ShardRouter — the requested
    member shard. Any ``ship_capable`` store qualifies: the durable
    primary (disk segments + live tail) or a replica's mirror shard
    (bounded re-ship ring + live tail) — fan-out trees hang replicas
    off replicas through exactly this seam. A plain in-memory store has
    no lineage to ship and refuses."""
    shards = getattr(store, "shards", None)
    idx = int(shard or 0)
    if shards is None:
        if idx != 0:
            raise RuntimeError(f"unsharded store has no shard {idx}")
        target = store
    else:
        if not 0 <= idx < len(shards):
            raise RuntimeError(
                f"shard {idx} out of range (store has {len(shards)})")
        target = store._shard(idx)  # ShardUnavailableError when down
    if (getattr(target, "data_dir", None) is None
            and not getattr(target, "ship_capable", False)):
        raise RuntimeError(
            "replica bootstrap/ship requires a durable primary "
            "(--store-data-dir): an in-memory store has no WAL to ship")
    return target


class EventJournal:
    """Per-kind ring of recent committed events keyed by the store's
    global resource_version, so a reconnecting watcher resumes from its
    high-water mark instead of tearing its mirror down. Bounded: once a
    kind's ring has dropped an event (or the event predates this
    journal), resumes from before that point refuse (ResumeGapError).

    Entries hold the live store objects and encode lazily at resume time
    — the common case (no broken watchers) pays one deque append per
    write, no JSON. With the store's in-place-update idiom a replayed
    event can therefore carry a slightly newer object state than it
    committed with; the mirror still converges (level-triggered, and the
    cache's handlers are resync-safe).

    A DurableClusterStore that just recovered exposes the WAL-tail
    events it replayed (``recovery_tail``/``recovery_floors``,
    client/durable.py); they seed this journal's window, so a watcher
    that was mid-stream when the store crashed resumes through the same
    ``since:`` path over the restart — the events it missed while the
    store was down are replayed from disk instead of forcing the
    crash-only full resync."""

    def __init__(self, store: ClusterStore, capacity: int = JOURNAL_CAPACITY):
        self.store = store
        self.capacity = capacity
        self._lock = threading.Lock()
        self._events: Dict[str, collections.deque] = {}
        #: per kind: events at or below this rv are NOT replayable
        self._floor: Dict[str, int] = {}
        self._listeners = []
        seed = getattr(store, "recovery_tail", None) or {}
        floors = getattr(store, "recovery_floors", None) or {}
        with store.locked():
            for kind in KINDS:
                self._events[kind] = collections.deque()
                self._floor[kind] = store.last_event_rv(kind)
                tail = seed.get(kind)
                # trust the recovered tail only when it reaches the
                # store's PRESENT rv for this kind: a journal built some
                # time after recovery (events committed in between) has
                # a hole the tail cannot cover, and resuming across it
                # would silently skip those events — keep the floor at
                # the current rv instead (resumes from before it refuse)
                if tail and tail[-1][0] >= store.last_event_rv(kind):
                    self._floor[kind] = int(floors.get(kind, 0))
                    q = self._events[kind]
                    for entry in tail:
                        if len(q) >= self.capacity:
                            self._floor[kind] = q.popleft()[0]
                        q.append(entry)
                listener = self._make_listener(kind)
                self._listeners.append((kind, listener))
                store.watch(kind, listener, replay=False)

    def _make_listener(self, kind: str):
        def listener(event, obj, old):
            # runs under the store lock: _rv is the rv this event
            # committed at (store._notify stamps _kind_rv from it too)
            rv = self.store._rv
            with self._lock:
                q = self._events[kind]
                if len(q) >= self.capacity:
                    self._floor[kind] = q.popleft()[0]
                q.append((rv, event, obj, old))
        return listener

    def since(self, kind: str, rv: int):
        """[(rv, event, obj, old)] committed after ``rv``, or None when
        the window no longer covers that point."""
        with self._lock:
            if rv < self._floor[kind]:
                return None
            return [e for e in self._events[kind] if e[0] > rv]

    def close(self) -> None:
        """Unsubscribe (a stopped server must not keep journaling into a
        store that outlives it — the restart case builds a fresh one)."""
        for kind, listener in self._listeners:
            self.store.unwatch(kind, listener)
        self._listeners = []


def send_frame(sock: socket.socket, payload: dict) -> None:
    raw = json.dumps(payload).encode()
    if len(raw) > MAX_FRAME_BYTES:
        raise ValueError(f"frame of {len(raw)} bytes exceeds cap")
    sock.sendall(struct.pack("<I", len(raw)) + raw)


def send_frame_raw(sock: socket.socket, raw: bytes) -> None:
    """Send an already-serialized frame (the watch hub serializes each
    event once; every stream then ships the same bytes)."""
    if len(raw) > MAX_FRAME_BYTES:
        raise ValueError(f"frame of {len(raw)} bytes exceeds cap")
    sock.sendall(struct.pack("<I", len(raw)) + raw)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("store connection closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame_sized(sock: socket.socket) -> tuple:
    """(frame, wire byte length) — the watch client's per-stream byte
    accounting (volcano_delta_stream_bytes_total) without re-encoding."""
    raw = recv_frame_raw(sock)
    return json.loads(raw), len(raw)


def recv_frame(sock: socket.socket) -> dict:
    (length,) = struct.unpack("<I", recv_exact(sock, 4))
    if length > MAX_FRAME_BYTES:
        raise ConnectionError(f"frame length {length} exceeds cap")
    return json.loads(recv_exact(sock, length))


def recv_frame_raw(sock: socket.socket) -> bytes:
    """One frame's payload bytes, unparsed — the multi-process shard
    router relays worker watch/ship frames verbatim (the workers already
    stamp shard tags), so the relay never pays a loads/dumps per event."""
    (length,) = struct.unpack("<I", recv_exact(sock, 4))
    if length > MAX_FRAME_BYTES:
        raise ConnectionError(f"frame length {length} exceeds cap")
    return recv_exact(sock, length)


def remote_error(resp: dict) -> Exception:
    """Rebuild a {"ok": false} response (or a bulk_apply per-item error
    entry) as its original exception class, without raising."""
    cls = _ERRORS.get(resp.get("error"), RuntimeError)
    if cls is OverloadedError:
        # the shed response's retry-after hint (and lane/reason) ride
        # the frame as typed fields, not prose — rebuild them so the
        # client's retry discipline can honor the hint
        return OverloadedError(
            resp.get("message", "request shed at the admission gate"),
            retry_after_ms=resp.get("retry_after_ms"),
            lane=resp.get("lane"), reason=resp.get("reason"))
    return cls(resp.get("message", "remote store error"))


def overloaded_response(e: OverloadedError) -> dict:
    """The wire form of a shed: typed error + retry-after hint."""
    resp = {"ok": False, "error": "OverloadedError", "message": str(e)}
    if e.retry_after_ms is not None:
        resp["retry_after_ms"] = e.retry_after_ms
    if e.lane is not None:
        resp["lane"] = e.lane
    if e.reason is not None:
        resp["reason"] = e.reason
    return resp


def raise_remote(resp: dict) -> None:
    """Re-raise a {"ok": false} response as its original error class."""
    raise remote_error(resp)


def since_rv(val, shard: Optional[int] = None) -> int:
    """A resume high-water mark out of a ``since:`` request: the legacy
    scalar, or the per-shard map ({shard: rv}) a shard-aware client
    sends — the unsharded server IS shard "0" (or, for a shard-worker
    process serving one member lineage, its own ``shard`` index), so it
    resumes from that entry and ignores the rest (there are none to
    ignore unless the client migrated from a sharded endpoint, in which
    case an absent entry refuses conservatively)."""
    if isinstance(val, dict):
        val = val.get(str(shard if shard is not None else 0), -1)
    return int(val if val is not None else -1)


def pump_watch(sock: socket.socket, events: "queue.Queue",
               overflowed: threading.Event, batch_max: int = 1,
               on_sent=None) -> None:
    """Drain a watch queue onto the socket until the watcher is
    condemned (overflow) or the peer goes away (raises). With
    ``batch_max`` > 1 consecutive event payloads coalesce into one
    ``{"stream": "events", "batch": [...]}`` frame — the bulk_watch
    contract: at tens of thousands of events per second, per-event
    frames spend more wall time in framing + syscalls than in the
    events themselves. Control frames (synced/heartbeat) always flush
    the pending batch first, so ordering is preserved.

    An event payload may carry ``_raw`` — its own frame bytes,
    serialized ONCE by the producer (the shard router's watch hub) —
    in which case this pump ships/concatenates those bytes instead of
    re-serializing per stream."""
    def event_bytes(p) -> str:
        raw = p.get("_raw")
        return raw if raw is not None else json.dumps(p)

    while not overflowed.is_set():
        try:
            payload = events.get(timeout=10.0)
        except queue.Empty:
            # heartbeat: an idle cluster would otherwise never touch
            # the socket, so a dead peer's listener would stay
            # subscribed forever
            payload = {"stream": "heartbeat"}
        if batch_max > 1 and payload.get("stream") == "event":
            batch = [payload]
            tail = None
            while len(batch) < batch_max:
                try:
                    nxt = events.get_nowait()
                except queue.Empty:
                    break
                if nxt.get("stream") == "event":
                    batch.append(nxt)
                else:
                    tail = nxt
                    break
            send_frame_raw(sock, (
                '{"stream":"events","batch":['
                + ",".join(event_bytes(p) for p in batch)
                + "]}").encode())
            if on_sent is not None:
                on_sent(batch)
            if tail is not None:
                send_frame(sock, tail)
            continue
        if payload.get("stream") == "event":
            send_frame_raw(sock, event_bytes(payload).encode())
            if on_sent is not None:
                on_sent([payload])
        else:
            send_frame(sock, payload)


class DeltaEncoder:
    """Shared per-serving-store builder of delta-form watch payloads
    (the ``delta: true`` negotiation — see codec.py's dialect notes).

    One instance per store lineage (a StoreServer / shard worker, or one
    per shard inside the router's watch hub); every call happens under
    that store's commit lock, so the per-kind frame-sequence counters
    (``ks``) and the interning table mutate without a lock of their own,
    and the last-event payload cache lets N delta streams share one
    diff+dumps exactly like the object path's ``_raw``.

    ``ks`` stamps EVERY live delta-stream frame (patch or object form)
    densely per kind: the client refuses a gap or repeat BEFORE applying
    anything, which is what makes the drop/dup fault ladder
    (``delta_frame``/``delta_frame_dup``) recover with zero lost or
    duplicated events — the resume replay (object form, journal-fed)
    starts from a high-water mark the bad frame never advanced."""

    def __init__(self):
        # one interning table PER KIND: a table addition must ride a
        # frame of the kind that grew it, and a stream only receives
        # the kinds it subscribed — a shared table would skew streams
        # watching a subset of kinds (their copy misses the additions
        # other kinds' frames carried)
        self.interners: Dict[str, Interner] = {}
        self.ks: Dict[str, int] = {}
        self._last_key: Optional[tuple] = None
        self._last_payload: Optional[dict] = None

    def payload(self, kind: str, shard, rv: int, event: str,
                obj, old) -> dict:
        cache_key = (kind, rv, event, id(obj))
        if cache_key == self._last_key:
            return self._last_payload  # type: ignore[return-value]
        n = self.ks.get(kind, 0) + 1
        self.ks[kind] = n
        payload: dict = {"stream": "event", "kind": kind, "rv": rv,
                         "event": event, "ks": n}
        if shard is not None:
            payload["shard"] = shard
        it = self.interners.get(kind)
        if it is None:
            it = self.interners[kind] = Interner()
        t0 = len(it.entries)
        patched = False
        if event == "update" and old is not None:
            enc_new, enc_old = encode(obj), encode(old)
            d = delta_diff(enc_new, enc_old)
            if d is not None:
                changed, cleared = d
                dk = it.intern(object_key(obj))
                if dk is not None:
                    df, dv, dx = [], [], []
                    ok = True
                    for fname, enc in changed.items():
                        fid = it.intern(fname)
                        if fid is None:
                            ok = False  # table at cap: object form
                            break
                        df.append(fid)
                        dv.append(delta_value(enc, it))
                    if ok:
                        for fname in cleared:
                            fid = it.intern(fname)
                            if fid is None:
                                ok = False
                                break
                            dx.append(fid)
                    if ok:
                        payload["dk"] = dk
                        payload["df"] = df
                        payload["dv"] = dv
                        if dx:
                            payload["dx"] = dx
                        patched = True
        if not patched:
            payload["obj"] = encode(obj)
            payload["old"] = encode(old) if old is not None else None
        added = it.entries[t0:]
        if added:
            # the table entries THIS event created ride this frame, in
            # id order — every subscribed stream needs exactly these
            # (its synced snapshot covered everything earlier)
            payload["tb"] = [t0, added]
        payload["_raw"] = json.dumps(payload, separators=(",", ":"))
        self._last_key = cache_key
        self._last_payload = payload
        return payload

    def synced_fields(self, kinds, shard) -> dict:
        """The delta half of a stream's ``synced`` frame — per-kind
        table snapshots plus per-kind ks baselines, read under the
        store lock so they are atomic with the subscription. Only the
        subscribed kinds ship: their frames are all this stream will
        see, so their tables are all it can keep aligned."""
        sh = str(shard if shard is not None else 0)
        return {"delta": True,
                "vtab": {k: {sh: self.interners[k].snapshot()}
                         for k in kinds if k in self.interners},
                "ks": {k: {sh: self.ks.get(k, 0)} for k in kinds}}


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:  # noqa: D102 — socketserver contract
        sock = self.request
        store: ClusterStore = self.server.store  # type: ignore[attr-defined]
        token = self.server.token  # type: ignore[attr-defined]
        ssl_ctx = self.server.ssl_ctx  # type: ignore[attr-defined]
        # register the RAW socket first so stop() can always unblock this
        # thread, and bound the handshake: a peer that connects and goes
        # silent must not pin a handler thread forever
        self.server.active.add(sock)  # type: ignore[attr-defined]
        if ssl_ctx is not None:
            # per-connection handshake in THIS handler thread, so a slow
            # (or hostile) handshaker never blocks the accept loop
            raw = sock
            try:
                sock.settimeout(TLS_HANDSHAKE_TIMEOUT_S)
                sock = ssl_ctx.wrap_socket(sock, server_side=True)
                sock.settimeout(None)
            except (OSError, ValueError) as e:
                log.warning("store TLS handshake failed: %s", e)
                self.server.active.discard(raw)
                return
            self.request = sock
            self.server.active.discard(raw)
            self.server.active.add(sock)  # type: ignore[attr-defined]
        try:
            if recv_exact(sock, 4) != MAGIC:
                return
            if token:
                # first frame must authenticate; anything else is refused
                # before it can touch the store
                req = recv_frame(sock)
                presented = req.get("token") or ""
                # compare digests of BYTES: compare_digest on str rejects
                # non-ASCII tokens with a TypeError
                if req.get("op") != "auth" or not hmac.compare_digest(
                        str(presented).encode(), token.encode()):
                    send_frame(sock, {"ok": False, "error": "RuntimeError",
                                      "message": "store auth failed"})
                    return
                send_frame(sock, {"ok": True})
            # every request-serving surface consults the admission gate
            # before dispatch: per-lane bounded concurrency + bounded
            # queues, typed sheds with a retry-after hint (see
            # resilience/overload.py). gate=None only when explicitly
            # disabled — the old-ungated-server behavior, byte for byte.
            gate: Optional[AdmissionGate] = \
                getattr(self.server, "gate", None)
            while True:
                req = recv_frame(sock)
                op = req.get("op")
                # per-op request counters (store_info "requests"): the
                # ground truth for "the primary served zero read-lane
                # traffic while the tree absorbed the storm"
                counts = getattr(self.server, "op_counts", None)
                if counts is not None and op:
                    counts[op] += 1
                if op in ("watch", "bulk_watch", "ship"):
                    # stream setup admits through the gate too: a storm
                    # of new watchers queues/sheds at its lane instead
                    # of spawning unbounded fan-out; the stream ticket
                    # is held for the STREAM's lifetime so lanes with a
                    # max_streams bound cap live fan-out, not just setup
                    ticket = None
                    if gate is not None:
                        try:
                            ticket = gate.admit(
                                op, req, client=self._gate_client(req),
                                stream=True)
                        except OverloadedError as e:
                            send_frame(sock, overloaded_response(e))
                            continue
                    try:
                        if op == "ship":
                            # WAL shipping (read replicas): the
                            # connection becomes a one-way record
                            # stream, like watch
                            self._serve_ship(sock, store, req)
                        else:
                            self._serve_watch(sock, store, req)
                    finally:
                        if gate is not None:
                            gate.release(ticket)
                    return  # streams never go back to req/resp
                ticket = None
                try:
                    if gate is not None:
                        ticket = gate.admit(op, req,
                                            client=self._gate_client(req))
                    try:
                        resp = self._dispatch(store, op, req)
                    finally:
                        if gate is not None:
                            gate.release(ticket)
                except OverloadedError as e:
                    resp = overloaded_response(e)
                except (ConflictError, NotFoundError, AdmissionError,
                        ShardUnavailableError, ReplicaReadOnlyError,
                        ReplicaLagError) as e:
                    resp = {"ok": False, "error": type(e).__name__,
                            "message": str(e)}
                except ConnectionError:
                    # transport-shaped failure inside dispatch (the
                    # shard_request/shard_crash fault points inject
                    # these): die like the link did, so the client's
                    # retry rules engage instead of its error handling
                    raise
                except Exception as e:  # noqa: BLE001 — report, keep serving
                    log.exception("store op %s failed", op)
                    resp = {"ok": False, "error": "RuntimeError",
                            "message": str(e)}
                try:
                    send_frame(sock, resp)
                except ValueError as e:
                    # oversize response (giant list): the size check fires
                    # before any bytes hit the socket, so the connection
                    # is still clean — report instead of dying silently
                    send_frame(sock, {"ok": False, "error": "RuntimeError",
                                      "message": str(e)})
        except (ConnectionError, OSError):
            pass  # client went away
        finally:
            self.server.active.discard(sock)  # type: ignore[attr-defined]

    def _gate_client(self, req: dict) -> str:
        """Flow identity for per-client fairness inside a lane: the
        client's self-assigned id header when present (one per
        RemoteClusterStore instance, stable across its pooled
        connections), else the peer address — old clients still get a
        flow of their own."""
        client = req.get("client")
        if client:
            return str(client)
        try:
            return str(self.client_address[0])
        except Exception:  # noqa: BLE001 — fairness only
            return ""

    def _admission_info(self) -> dict:
        """Per-lane admission table (vcctl status): inflight/streams/
        queued/sheds/deadline-expirations per lane, plus the configured
        bounds. An ungated server reports enabled=False with no lanes."""
        gate: Optional[AdmissionGate] = getattr(self.server, "gate", None)
        if gate is None or not gate.enabled:
            return {"ok": True, "enabled": False, "lanes": {}}
        return {"ok": True, "enabled": True, "lanes": gate.stats()}

    def _dispatch(self, store: ClusterStore, op: str, req: dict) -> dict:
        kind = req.get("kind")
        if op == "admission_info":
            return self._admission_info()
        # fencing tokens ride the frame; the authoritative store validates
        # them against ITS lease record (the deposed writer's view of its
        # own leadership is exactly what cannot be trusted client-side)
        fencing = req.get("fencing") or None
        if op in ("create", "update", "apply"):
            obj = getattr(store, op)(kind, decode(req["obj"]),
                                     fencing=fencing)
            return {"ok": True, "obj": encode(obj),
                    "applied_rv": self._applied_stamp(store)}
        if op == "delete":
            obj = store.delete(kind, req["name"], req.get("namespace"),
                               fencing=fencing)
            return {"ok": True, "obj": encode(obj),
                    "applied_rv": self._applied_stamp(store)}
        if op == "bulk_apply":
            # one frame, many objects, one journal batch (the durable
            # store fsyncs once for the wave); per-item results so one
            # rejected object costs that object, not the wave
            items = [(it["kind"], decode(it["obj"]),
                      it.get("verb", "apply")) for it in req["items"]]
            results = store.bulk_apply(items, fencing=fencing)
            if req.get("ack"):
                # ingest-wave mode: the caller doesn't want the applied
                # objects back — respond with counts + sparse errors, so
                # a 10k-pod wave costs no result encode/decode at all
                errors = {str(i): {"error": type(r).__name__,
                                   "message": str(r)}
                          for i, r in enumerate(results)
                          if isinstance(r, Exception)}
                return {"ok": True, "n": len(results), "errors": errors,
                        "applied_rv": self._applied_stamp(store)}
            out = []
            for res in results:
                if isinstance(res, Exception):
                    out.append({"error": type(res).__name__,
                                "message": str(res)})
                else:
                    out.append({"obj": encode(res)})
            return {"ok": True, "results": out,
                    "applied_rv": self._applied_stamp(store)}
        if op == "get":
            with store.locked():
                rv = applied_rv_of(store)
                obj = store.get(kind, req["name"], req.get("namespace"))
            return {"ok": True, "obj": encode(obj), "applied_rv": rv}
        if op == "list":
            # rv stamped under the SAME lock hold as the read, so the
            # response names the exact store version it reflects — a
            # mirror can order a (possibly retried) list against the rv
            # high-water mark of its concurrent watch stream. min_rv on
            # the authoritative store is trivially satisfied: every rv
            # a client can legally hold was minted here. (A replica's
            # handler overrides this with real rv-bounded blocking.)
            with store.locked():
                rv = applied_rv_of(store)
                objs = store.list(kind, req.get("namespace"),
                                  req.get("label_selector"),
                                  req.get("name_glob"))
            return {"ok": True, "objs": [encode(o) for o in objs],
                    "applied_rv": rv}
        if op == "store_info":
            # replica handshake: shape + current rv(s) + whether a WAL
            # lineage exists to ship. recovered/pid ride along for the
            # shard-worker supervisor's liveness polls and vcctl status
            import os as _os
            shards = getattr(store, "shards", None)
            with store.locked():
                rv = applied_rv_of(store)
            counts = getattr(self.server, "op_counts", None)
            return {"ok": True, "rv": rv,
                    "shards": len(shards) if shards is not None else 1,
                    "durable": getattr(store, "data_dir", None)
                    is not None,
                    "ship_capable": getattr(store, "data_dir", None)
                    is not None or bool(getattr(store, "ship_capable",
                                                False)),
                    "requests": dict(counts) if counts is not None else {},
                    "recovered": getattr(store, "recovered_records", 0),
                    "pid": _os.getpid()}
        if op == "bootstrap":
            # newest valid on-disk snapshot (replica seed); the WAL
            # records past its rv arrive over the ship stream
            src = _ship_source(store, req.get("shard"))
            rv, state = src.newest_snapshot_state()
            return {"ok": True, "rv": rv, "state": state}
        if op == "fence_check":
            # the shard-worker fencing RPC: a worker owning a non-lease
            # shard validates a write's fencing token against the
            # arbiter worker's lease record (the ``leases`` bucket is
            # pinned to shard 0). FencedError re-raises typed
            # client-side, exactly like a fenced write would.
            store._check_fence(req.get("fencing") or None)
            return {"ok": True}
        if op == "topology":
            return self._topology(store)
        if op == "announce_read_endpoint":
            # a replica (possibly deep in a tree) registers itself so
            # topology can hand read traffic to the read tier; advisory
            # — clients that never ask keep reading here
            table = getattr(self.server, "read_endpoints", None)
            if table is not None and req.get("endpoint"):
                table[str(req["endpoint"])] = {
                    "depth": int(req.get("depth", 1)),
                    "shards": int(req.get("shards", 1)),
                }
            return {"ok": True}
        if op == "ping":
            return {"ok": True}
        if op == "replica_info":
            # a quiet typed refusal: vcctl probes every hop of an
            # upstream chain with this op to find where the tree ends,
            # and hitting the primary is the expected terminal case
            return {"ok": False, "error": "RuntimeError",
                    "message": "not a replica endpoint"}
        if op == "auth":
            return {"ok": True}  # token-less server: auth is a no-op
        raise RuntimeError(f"unknown op {op!r}")

    def _applied_stamp(self, store) -> object:
        """rv(s) as of (at least) this mutation's commit, stamped on the
        response so the writer can demand read-your-writes from a
        replica via ``min_rv`` on its next read. A shard WORKER stamps a
        ``{shard: rv}`` map keyed by its shard tag — the proc router
        relays worker responses verbatim, and a bare scalar would be
        ambiguous once it crosses that hop."""
        with store.locked():
            rv = applied_rv_of(store)
        tag = getattr(self.server, "shard_tag", None)
        if tag is not None and not isinstance(rv, dict):
            return {str(tag): rv}
        return rv

    def _topology(self, store: ClusterStore) -> dict:
        """The shard map a direct-routing client asks for once: shard
        count plus per-shard endpoints it may connect to directly. A
        single-store server (and the in-process ShardRouter, whose
        shards share its one process) advertises NO direct endpoints —
        the client then keeps router-only routing. The multi-process
        router (client/shardproc.py) overrides with real worker
        endpoints."""
        shards = getattr(store, "n_shards", 1)
        table = getattr(self.server, "read_endpoints", {}) or {}
        return {"ok": True, "n_shards": int(shards), "endpoints": [],
                "read_endpoints": [
                    {"endpoint": ep, "depth": meta.get("depth", 1),
                     "shards": meta.get("shards", 1)}
                    for ep, meta in table.items()]}

    def _serve_watch(self, sock: socket.socket, store: ClusterStore,
                     req: dict) -> None:
        """Stream events for the requested kinds until the peer leaves.

        The listener enqueues under the store lock and a writer loop
        drains, so a slow or stuck watcher never blocks store writes
        (client-go's watch buffers give the reference the same
        isolation)."""
        kinds = req.get("kinds") or [req.get("kind")]
        bad = [k for k in kinds if k not in KINDS]
        if bad:
            # refuse BEFORE subscribing anything: a partially-subscribed
            # failed request would leak listeners that enqueue forever
            send_frame(sock, {"ok": False, "error": "RuntimeError",
                              "message": f"unknown watch kinds {bad}"})
            return
        replay = bool(req.get("replay", True))
        since = req.get("since") or None  # {kind: rv} = resume request
        # bulk_watch: same subscription semantics, but events coalesce
        # into batched frames (pump_watch) — the high-churn ingest path
        batch_max = WATCH_BATCH_MAX if req.get("op") == "bulk_watch" else 1
        # a shard-worker process serving ONE member lineage stamps its
        # shard index into every event/synced frame, so the multi-process
        # router can relay frames verbatim and a direct-routed client's
        # per-shard resume marks attribute events without re-tagging
        shard = getattr(self.server, "shard_tag", None)
        journal: Optional[EventJournal] = getattr(self.server, "journal",
                                                  None)
        # delta negotiation: additive and fail-safe — the client must ask
        # (delta: true) AND this server must carry an encoder; otherwise
        # the stream is plain object frames exactly as before
        enc: Optional[DeltaEncoder] = getattr(self.server, "delta_enc",
                                              None)
        delta = bool(req.get("delta")) and enc is not None
        # replay adds (store.watch replay / journal resume) are delivered
        # synchronously under the subscribe hold, BEFORE this flips: they
        # stay object frames without ks, because the shared encoder's
        # counters must only move for live events every delta stream sees
        sync_done = [False]
        # bounded queue + send timeout: a peer that stalls without closing
        # (TCP zero window) otherwise blocks the writer in sendall forever
        # while the listeners keep enqueueing — unbounded memory per stuck
        # watcher. On overflow the watcher is dropped (client-go's watch
        # buffers terminate slow watchers the same way); the client sees
        # the close and treats it as a broken stream (resume-then-resync).
        events: "queue.Queue" = queue.Queue(maxsize=WATCH_QUEUE_MAX)
        overflowed = threading.Event()
        sock.settimeout(WATCH_SEND_TIMEOUT_S)

        def enqueue(payload) -> None:
            if overflowed.is_set():
                return  # watcher already condemned: stop buffering
            try:
                events.put_nowait(payload)
            except queue.Full:
                overflowed.set()

        def listener_for(kind):
            def listener(event, obj, old):
                # under the store lock: store._rv is this event's rv
                if delta and sync_done[0]:
                    payload = enc.payload(kind, shard, store._rv,
                                          event, obj, old)
                    try:
                        faults.fire("delta_frame")
                    except Exception:  # noqa: BLE001 — injected drop
                        # frame lost AFTER its ks was consumed: the
                        # client sees the gap on the next frame and
                        # falls back typed (delta_gap)
                        return
                    enqueue(payload)
                    try:
                        faults.fire("delta_frame_dup")
                    except Exception:  # noqa: BLE001 — injected dup
                        enqueue(payload)  # same ks twice: typed refusal
                    return
                payload = {"stream": "event", "kind": kind,
                           "rv": store._rv, "event": event,
                           "obj": encode(obj),
                           "old": encode(old) if old is not None else None}
                if shard is not None:
                    payload["shard"] = shard
                enqueue(payload)
            return listener

        listeners = []
        try:
            # subscribe (and, on resume, read the journal) under ONE hold
            # of the store lock: no event can fall between the replayed
            # window and the live stream, and the synced rv map is exact.
            # put_nowait throughout: a replay bigger than the whole queue
            # has already condemned this watcher, and a blocking put would
            # deadlock (nothing drains yet).
            gap_kind = None
            with store.locked():
                if since is not None:
                    for kind in kinds:
                        missed = journal.since(
                            kind, since_rv(since.get(kind), shard)) \
                            if journal is not None else None
                        if missed is None:
                            gap_kind = kind
                            break
                        for rv, event, obj, old in missed:
                            payload = {"stream": "event", "kind": kind,
                                       "rv": rv, "event": event,
                                       "obj": encode(obj),
                                       "old": encode(old)
                                       if old is not None else None}
                            if shard is not None:
                                payload["shard"] = shard
                            enqueue(payload)
                if gap_kind is None:
                    for kind in kinds:
                        listener = listener_for(kind)
                        listeners.append((kind, listener))
                        store.watch(kind, listener,
                                    replay=replay and since is None)
                    sync_done[0] = True
                    sync_payload = {
                        "stream": "synced",
                        "rv": {k: ({str(shard): store.last_event_rv(k)}
                                   if shard is not None
                                   else store.last_event_rv(k))
                               for k in kinds}}
                    if delta:
                        # table snapshot + per-kind ks baselines, atomic
                        # with the subscription under this same hold
                        sync_payload.update(enc.synced_fields(kinds, shard))
                    enqueue(sync_payload)
            if gap_kind is not None:
                send_frame(sock, {
                    "ok": False, "error": "ResumeGapError",
                    "message": f"resume window for {gap_kind!r} no longer "
                               f"covers rv {since.get(gap_kind)}"})
                return
            pump_watch(sock, events, overflowed, batch_max=batch_max)
            log.warning("watch stream overflowed %d events; dropping the "
                        "slow watcher", WATCH_QUEUE_MAX)
            try:
                from ..metrics import metrics
                metrics.store_watch_dropped_total.inc()
            except Exception:  # noqa: BLE001 — accounting only
                pass
        except socket.timeout:
            # the other slow-watcher shape: a peer that stalls without
            # closing (TCP zero window) blocks sendall past the timeout
            log.warning("watch send stalled > %.0fs; dropping the slow "
                        "watcher", WATCH_SEND_TIMEOUT_S)
            try:
                from ..metrics import metrics
                metrics.store_watch_dropped_total.inc()
            except Exception:  # noqa: BLE001 — accounting only
                pass
        except (ConnectionError, OSError, ValueError):
            pass  # peer went away
        finally:
            for kind, listener in listeners:
                store.unwatch(kind, listener)

    def _serve_ship(self, sock: socket.socket, store: ClusterStore,
                    req: dict) -> None:
        """Stream WAL records committed after ``since_rv`` to a replica:
        sealed segments + the already-durable tail replayed off disk
        (``read_frames``' CRC/torn-tail discipline — a torn record and
        everything after it never ships), then live records as they
        commit, coalesced into batched frames. Refuses with
        ResumeGapError when ``since_rv`` predates the retained-segment
        window — the replica must close that hole with a fresh snapshot
        bootstrap, never by skipping. The ``wal_ship`` fault point fires
        at every frame send (arm ``exc:`` to drop the link mid-segment,
        ``exc:exit`` to SIGKILL the primary there); the replica's
        record-continuity check is the backstop for anything this stream
        could lose.

        A REPLICA serving this op (fan-out trees) replays from its
        mirror shard's re-ship ring instead of disk segments, fires the
        ``ship_relay`` fault point instead of ``wal_ship``, and counts
        the absorbed traffic in its ``ship_served`` ledger — same
        protocol, same lock-hold no-gap guarantee, different source."""
        from .durable import _segment_paths, read_frames
        try:
            src = _ship_source(store, req.get("shard"))
        except Exception as e:  # noqa: BLE001 — refuse, keep the conn clean
            name = type(e).__name__
            send_frame(sock, {"ok": False,
                              "error": name if name in _ERRORS
                              else "RuntimeError", "message": str(e)})
            return
        fault_point = getattr(self.server, "ship_fault_point", "wal_ship")
        replica = getattr(self.server, "replica", None)

        def account(n: int) -> None:
            if replica is None:
                return
            replica.ship_served["records"] += n
            try:
                from ..metrics import metrics as _m
                _m.replica_ship_served_records_total.inc(n)
            except Exception:  # noqa: BLE001 — accounting only
                pass

        since_rv = int(req.get("since_rv", 0))
        events: "queue.Queue" = queue.Queue(maxsize=WATCH_QUEUE_MAX)
        overflowed = threading.Event()
        sock.settimeout(WATCH_SEND_TIMEOUT_S)

        def on_record(rec) -> None:
            if overflowed.is_set():
                return
            try:
                events.put_nowait(rec)
            except queue.Full:
                overflowed.set()

        with src._lock:
            floor = src.ship_floor()
            if since_rv < floor:
                send_frame(sock, {
                    "ok": False, "error": "ResumeGapError",
                    "message": f"retained WAL window starts after rv "
                               f"{floor}; cannot resume from {since_rv}"})
                return
            # registration + segment listing + rv capture under ONE lock
            # hold: every record <= live_from is fully flushed to these
            # segments, every record > live_from arrives via the hook —
            # no record can fall between disk replay and live tail
            live_from = src._rv
            if getattr(src, "data_dir", None) is not None:
                segments = _segment_paths(src.data_dir)
                pending: Optional[list] = None
            else:
                # mirror ship source: the bounded re-ship ring stands in
                # for disk segments, captured under the SAME lock hold
                segments = []
                pending = src.ship_records(since_rv, live_from)
            src.add_ship_listener(on_record)
        if replica is not None:
            replica.ship_served["streams"] += 1
            replica._ship_stream_delta(1)
        try:
            send_frame(sock, {"ok": True, "rv": live_from})
            batch: list = []

            def flush() -> None:
                if batch:
                    faults.fire(fault_point)
                    send_frame(sock, {"stream": "wal", "recs": batch,
                                      "prv": live_from})
                    account(len(batch))
                    del batch[:]

            for path in segments:
                records, _, _torn = read_frames(path)
                for rec in records:
                    if since_rv < int(rec["rv"]) <= live_from:
                        batch.append(rec)
                        if len(batch) >= SHIP_BATCH_MAX:
                            flush()
            for rec in pending or ():
                batch.append(rec)
                if len(batch) >= SHIP_BATCH_MAX:
                    flush()
            flush()
            send_frame(sock, {"stream": "ship_synced", "rv": live_from})
            while not overflowed.is_set():
                try:
                    rec = events.get(timeout=10.0)
                except queue.Empty:
                    # heartbeat carries the primary's current rv so an
                    # idle replica can report zero lag (and a lagging
                    # one honest lag) without any commit traffic
                    send_frame(sock, {"stream": "heartbeat",
                                      "prv": src._rv})
                    continue
                recs = [rec]
                while len(recs) < SHIP_BATCH_MAX:
                    try:
                        recs.append(events.get_nowait())
                    except queue.Empty:
                        break
                faults.fire(fault_point)
                send_frame(sock, {"stream": "wal", "recs": recs,
                                  "prv": src._rv})
                account(len(recs))
            log.warning("ship stream overflowed %d records; dropping the "
                        "slow replica (it resumes at its applied rv)",
                        WATCH_QUEUE_MAX)
        except socket.timeout:
            log.warning("ship send stalled > %.0fs; dropping the slow "
                        "replica", WATCH_SEND_TIMEOUT_S)
        except (ConnectionError, OSError, ValueError):
            pass  # replica went away; it resumes from its applied rv
        finally:
            src.remove_ship_listener(on_record)
            if replica is not None:
                replica._ship_stream_delta(-1)


class StoreServer:
    """Serve a ClusterStore on host:port (TCP, daemon threads).

    ``token``: shared-secret auth — every connection must open with an
    auth frame carrying it (the analog of the API server's bearer-token
    check). REQUIRED for non-loopback binds: the store holds Secrets and
    the leader-election lease; standalone refuses to expose it
    unauthenticated.

    ``tls_cert``/``tls_key``: serve TLS — the reference's equivalent seam
    (the k8s API server) is always TLS, and without it the token and
    every payload (ssh-keypair Secrets, the HA lease) cross the network
    in clear. ``tls_client_ca`` additionally requires client
    certificates (mTLS). Non-loopback deployments should set these (or
    run inside a network layer that encrypts, e.g. a service mesh);
    webhooks.server.generate_self_signed_cert bootstraps a dev pair.

    ``gate``: the overload-admission gate every request consults before
    dispatch (resilience/overload.py). Defaults to a gate with the
    fail-safe generous lane limits — an unloaded deployment is
    protocol-indistinguishable from an ungated one, an overloaded one
    sheds ``read`` first and ``system`` never. Pass an
    ``AdmissionGate(enabled=False)`` to run ungated (the pre-gate
    behavior, for wire-compat tests against "old" servers)."""

    #: request handler; the shard router (client/sharded.py) subclasses
    #: with shard-aware watch serving over the same wire protocol
    handler_class = _Handler

    def __init__(self, store: ClusterStore, host: str = "127.0.0.1",
                 port: int = 0, token: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 tls_client_ca: Optional[str] = None,
                 gate: Optional[AdmissionGate] = None):
        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        ssl_ctx = None
        if tls_cert and tls_key:
            import ssl

            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_ctx.load_cert_chain(tls_cert, tls_key)
            if tls_client_ca:
                ssl_ctx.verify_mode = ssl.CERT_REQUIRED
                ssl_ctx.load_verify_locations(tls_client_ca)
        elif tls_cert or tls_key or tls_client_ca:
            # a half-configured pair must not silently serve plaintext
            raise ValueError(
                "store TLS needs BOTH tls_cert and tls_key "
                "(tls_client_ca additionally needs them)")

        self._server = _Server((host, port), self.handler_class)
        self._server.store = store  # type: ignore[attr-defined]
        self._server.token = token or ""  # type: ignore[attr-defined]
        self._server.ssl_ctx = ssl_ctx  # type: ignore[attr-defined]
        # overload-admission gate, on by default (generous limits); an
        # enabled=False gate serves ungated and the handler skips it
        self.gate = gate if gate is not None else AdmissionGate()
        self._server.gate = (  # type: ignore[attr-defined]
            self.gate if self.gate.enabled else None)
        # resume window for reconnecting watchers (see EventJournal;
        # the shard router builds one journal per shard instead)
        self.journal = self._make_journal(store)
        self._server.journal = self.journal  # type: ignore[attr-defined]
        # delta-watch encoder for this store lineage: one interning table
        # + per-kind frame counters shared by every delta: true stream
        # (the shard ROUTER serves watches through its hub's per-shard
        # encoders instead — _RouterHandler overrides _serve_watch)
        self._server.delta_enc = DeltaEncoder()  # type: ignore[attr-defined]
        # per-op request counters (store_info "requests") and the
        # announced read-tier endpoints (topology "read_endpoints")
        self._server.op_counts = (  # type: ignore[attr-defined]
            collections.Counter())
        self._server.read_endpoints = {}  # type: ignore[attr-defined]
        # live connection sockets, so stop() drops watch streams too
        # (daemon handler threads outlive server_close otherwise and
        # clients would never learn the server is gone)
        self._server.active = set()  # type: ignore[attr-defined]
        self.host, self.port = self._server.server_address
        self._thread: Optional[threading.Thread] = None

    def _make_journal(self, store):
        return EventJournal(store)

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def start(self) -> "StoreServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="store-server")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self.journal.close()
        for sock in list(self._server.active):  # type: ignore[attr-defined]
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=5)
