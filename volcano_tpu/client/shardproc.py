"""Multi-process store shards: one OS process per shard, a thin router.

The sharded front door (client/sharded.py) partitioned the object space,
but every shard still lived in ONE Python process — commits, watch
fan-out and wire encode all contended on one GIL, and the
``store_shard_scale`` bench recorded the 50k events/sec sustained-ingest
floor as core-bound (``ok=false``, honestly). This module takes the
partition to real cores, the reference repo's sharded-worker fan-out
(SURVEY §2/§5) as actual OS processes:

**Shard worker** (``python -m volcano_tpu.client.shardproc``): one
process owning exactly one shard — its lock, its resource_version
sequence, its watch-resume journal window, and its
``data-dir/shard-NNN`` WAL+snapshot lineage (the SAME layout and format
the in-process sharded store writes: the two deployments are
interchangeable over one data dir). The worker is a plain
``StoreServer`` over a ``DurableClusterStore`` speaking the UNCHANGED
wire protocol, with two twists: it stamps its shard index into every
watch event/synced frame (``shard_tag``), so routers relay frames
verbatim and direct clients attribute events without re-tagging; and a
non-arbiter worker validates fencing tokens through a **fencing RPC**
(``fence_check``) to the shard-0 worker, which owns the pinned
``leases`` bucket — lease arbitration stays a single-writer concern.
Admission interceptors (the webhook chain) run IN the worker, at the
authoritative store, exactly like ``standalone`` runs them at its
in-process store.

**Supervisor** (``ShardProcSupervisor``): spawns the workers, monitors
them, and restarts a dead worker on the SAME port and data dir with
capped exponential backoff — construction is recovery, so the restarted
worker's journal window re-seeds from its recovered WAL tail and
mid-stream watchers resume through the normal ``since:`` path. While a
worker is down its ops are contained with ``ShardUnavailableError``.
Liveness, pid, restart count, uptime and per-shard ingest events/sec
export as ``volcano_store_shard_worker_*`` metrics and surface in
``vcctl status``.

**Router** (``ProcShardRouter``): one endpoint, the existing wire
protocol, N worker processes behind it. It became what a router should
be — a proxy, not a store: single-key CRUD forwards the client's frame
verbatim to the owning worker (routing keys are extracted from the
sparse-encoded object without decoding it); ``bulk_apply`` waves split
per shard and dispatch to the workers IN PARALLEL (each worker fsyncs
its own sub-batch — N shards cost one fsync's wall time and none of the
router's); ``list``/``store_info`` fan out and merge with per-shard
``applied_rv`` stamps; watch/bulk_watch streams relay the workers'
already-shard-tagged frames byte-for-byte (one merged ``synced`` frame,
per-shard resume marks split back to each worker's own journal); and
``ship``/``bootstrap`` relay to the owning worker so replicas can ride
the router — or skip it entirely and tail a worker directly.

**Direct routing**: ``crc32(kind/ns/name) % N`` is deterministic and
client-visible (client/sharded.py ``shard_for``), so clients don't need
the router at all for single-key work. The router serves a ``topology``
op (``{n_shards, endpoints}``); ``RemoteClusterStore`` fetches it once
and opens per-shard connections (client/remote.py), sending single-key
CRUD/get — and, opted in, watch streams — straight to the owning
worker. The router hop survives only for cross-shard ops. Old servers
(no ``topology``) and failed direct connections degrade gracefully to
router-only routing.

Fault points: ``shard_proc_crash`` fires in the worker's request
dispatch (arm ``exc:exit`` via the worker's ``--faults`` to SIGKILL the
worker at the Nth op — the supervisor must restart it and every client
must ride through); ``shard_request``/``shard_crash`` fire at the
router's dispatch/commit seams exactly like the in-process router's.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import queue
import socket
import struct
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..resilience.faultinject import faults
from .codec import _REGISTRY, decode, encode
from .server import (
    MAGIC, WATCH_QUEUE_MAX, WATCH_SEND_TIMEOUT_S, _Handler, StoreServer,
    raise_remote, recv_frame, recv_frame_raw, remote_error, send_frame,
    send_frame_raw,
)
from .sharded import shard_for
from .store import (
    KINDS, ClusterStore, FencedError, ShardUnavailableError, _key,
)

log = logging.getLogger(__name__)

#: idle raw request sockets the supervisor keeps per worker
_WORKER_POOL_MAX = 8
#: sentinel pushed into a relay queue when an upstream dies
_EOF = object()


# -- routing keys off the wire ------------------------------------------------

#: class tag -> (default name, default namespace or None): what an
#: absent field decodes to, so a router can compute the SAME routing key
#: ``_key(decode(obj))`` would, without decoding the object
_KEY_DEFAULTS: Dict[str, tuple] = {}


def _key_defaults(tag: str) -> tuple:
    got = _KEY_DEFAULTS.get(tag)
    if got is None:
        name_default: Any = ""
        ns_default: Any = None
        cls = _REGISTRY.get(tag)
        if cls is not None and dataclasses.is_dataclass(cls):
            for fld in dataclasses.fields(cls):
                if fld.name == "name" \
                        and fld.default is not dataclasses.MISSING:
                    name_default = fld.default
                elif fld.name == "namespace" \
                        and fld.default is not dataclasses.MISSING:
                    ns_default = fld.default
        got = _KEY_DEFAULTS[tag] = (name_default, ns_default)
    return got


def encoded_key(enc: dict) -> str:
    """The ``_key()`` of a sparse-encoded object, without decoding it:
    fields absent from the wire regain their class defaults (the codec's
    contract), so name/namespace resolve identically on both sides."""
    fields = enc.get("f") or {}
    name_default, ns_default = _key_defaults(enc.get("__t", ""))
    name = fields.get("name", name_default)
    ns = fields.get("namespace", ns_default)
    return f"{ns}/{name}" if ns is not None else str(name)


# -- the worker process -------------------------------------------------------


class _RemoteFenceArbiter:
    """Fencing delegation over the wire: a worker owning a non-lease
    shard validates every fenced write against the arbiter worker's
    lease record (shard 0 owns the pinned ``leases`` bucket). FAILS
    CLOSED: an unreachable arbiter refuses the write — a fenced writer
    that cannot prove its leadership must not commit."""

    def __init__(self, address: str, token: Optional[str] = None,
                 connect_timeout: float = 2.0):
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.token = token or ""
        self.connect_timeout = connect_timeout
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.connect_timeout)
        sock.settimeout(self.connect_timeout)
        sock.sendall(MAGIC)
        if self.token:
            send_frame(sock, {"op": "auth", "token": self.token})
            resp = recv_frame(sock)
            if not resp.get("ok"):
                sock.close()
                raise_remote(resp)
        return sock

    def _check_fence(self, fencing: Optional[dict]) -> None:
        if not fencing:
            return
        resp = None
        with self._lock:
            for attempt in (0, 1):
                try:
                    if self._sock is None:
                        self._sock = self._connect()
                    send_frame(self._sock,
                               {"op": "fence_check", "fencing": fencing})
                    resp = recv_frame(self._sock)
                    break
                except (ConnectionError, OSError):
                    if self._sock is not None:
                        try:
                            self._sock.close()
                        except OSError:
                            pass
                        self._sock = None
                    if attempt:
                        raise FencedError(
                            "write fenced: fencing arbiter (shard 0 "
                            "worker) unreachable — failing closed")
        if not resp.get("ok"):
            if resp.get("error") == "FencedError":
                raise FencedError(resp.get("message", "write fenced"))
            raise_remote(resp)


class _PeerReadStore:
    """The worker's admission view of the WHOLE cluster: writes and
    same-shard reads hit the local store; a read whose key routes to
    another shard goes to the owning PEER worker over the wire (the
    jobs webhook checks its queue exists, the pods webhook checks its
    podgroup's phase, the queues webhook lists podgroups — all of which
    may live on other shards). Peers are installed by the supervisor's
    ``set_peers`` broadcast once every worker is up; until then (and on
    an unsharded deployment) every read is local. Peer reads carry a
    short timeout: a cross-shard read under the local store lock must
    degrade to a typed admission failure, never a distributed hang."""

    def __init__(self, local: ClusterStore, shard_idx: int,
                 token: Optional[str] = None, timeout_s: float = 5.0):
        self.local = local
        self.shard_idx = int(shard_idx)
        self.token = token or ""
        self.timeout_s = timeout_s
        self.n_shards = 1
        self._peers: List[tuple] = []
        self._lock = threading.Lock()
        self._socks: Dict[int, socket.socket] = {}

    def set_peers(self, endpoints: List[str], n_shards: int) -> None:
        peers = []
        for addr in endpoints:
            host, _, port = addr.rpartition(":")
            peers.append((host or "127.0.0.1", int(port)))
        with self._lock:
            self._peers = peers
            self.n_shards = int(n_shards)
            for s in self._socks.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._socks.clear()

    def _peer_request(self, idx: int, payload: dict) -> dict:
        with self._lock:
            for attempt in (0, 1):
                sock = self._socks.pop(idx, None)
                fresh = sock is None
                try:
                    if sock is None:
                        host, port = self._peers[idx]
                        sock = socket.create_connection(
                            (host, port), timeout=self.timeout_s)
                        sock.settimeout(self.timeout_s)
                        sock.sendall(MAGIC)
                        if self.token:
                            send_frame(sock, {"op": "auth",
                                              "token": self.token})
                            resp = recv_frame(sock)
                            if not resp.get("ok"):
                                sock.close()
                                raise_remote(resp)
                    send_frame(sock, payload)
                    resp = recv_frame(sock)
                except (ConnectionError, OSError, socket.timeout):
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                    if fresh or attempt:
                        raise
                    continue  # stale cached socket: one fresh retry
                self._socks[idx] = sock
                return resp
        raise ConnectionError("peer read failed")  # unreachable

    def _owner(self, kind: str, key: str) -> int:
        return shard_for(kind, key, self.n_shards)

    def get(self, kind: str, name: str, namespace: Optional[str] = None):
        key = f"{namespace}/{name}" if namespace is not None else name
        idx = self._owner(kind, key)
        if self.n_shards <= 1 or idx == self.shard_idx:
            return self.local.get(kind, name, namespace)
        resp = self._peer_request(idx, {"op": "get", "kind": kind,
                                        "name": name,
                                        "namespace": namespace})
        if not resp.get("ok"):
            raise_remote(resp)  # NotFoundError re-raises typed
        return decode(resp["obj"])

    def try_get(self, kind: str, name: str,
                namespace: Optional[str] = None):
        from .store import NotFoundError
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None,
             name_glob: Optional[str] = None) -> List[Any]:
        out = list(self.local.list(kind, namespace, label_selector,
                                   name_glob))
        for idx in range(self.n_shards):
            if idx == self.shard_idx:
                continue
            resp = self._peer_request(idx, {
                "op": "list", "kind": kind, "namespace": namespace,
                "label_selector": label_selector,
                "name_glob": name_glob})
            if not resp.get("ok"):
                raise_remote(resp)
            out.extend(decode(o) for o in resp["objs"])
        return out

    def __getattr__(self, name):
        # writes, locked(), add_interceptor, watch, ... stay LOCAL: the
        # wrapper exists only to widen admission's read horizon
        return getattr(self.local, name)


class _WorkerHandler(_Handler):
    def _dispatch(self, store, op: str, req: dict) -> dict:
        # shard_proc_crash armed exc:exit kills THIS worker process at
        # the Nth dispatched op — the deterministic worker-death chaos
        # the supervisor's restart path is tested against
        faults.fire("shard_proc_crash")
        if op == "set_peers":
            # supervisor broadcast: the full worker endpoint list, so
            # this worker's admission view can read across shards
            view = getattr(self.server, "peer_view", None)
            if view is not None:
                view.set_peers(req.get("endpoints") or [],
                               int(req.get("n_shards") or 1))
            return {"ok": True}
        return _Handler._dispatch(self, store, op, req)


class ShardWorkerServer(StoreServer):
    """A StoreServer that knows which shard it is: every watch
    event/synced frame carries ``shard`` so routers relay verbatim and
    direct clients keep per-shard resume marks, and resume requests
    read the worker's own key out of a ``{shard: rv}`` map."""

    handler_class = _WorkerHandler

    def __init__(self, store: ClusterStore, shard_idx: int, **kw):
        super().__init__(store, **kw)
        self.shard_idx = int(shard_idx)
        self._server.shard_tag = self.shard_idx  # type: ignore[attr-defined]


def main(argv=None) -> int:
    """Shard-worker entrypoint (grown from tests/store_server_proc.py
    into the real module): ONE shard's store served over TCP, nothing
    else. Imports stay store-only — no jax, no scheduler — so a
    supervisor restart is fast enough for clients' transport-retry
    windows to ride out."""
    ap = argparse.ArgumentParser(prog="volcano-tpu-shard-worker")
    ap.add_argument("--shard", type=int, required=True)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--data-dir", default="",
                    help="this shard's OWN lineage dir (data-dir/"
                         "shard-NNN); empty = in-memory")
    ap.add_argument("--fsync", default="every",
                    choices=["every", "interval", "off"])
    ap.add_argument("--fsync-interval", type=float, default=0.05)
    ap.add_argument("--snapshot-every", type=int, default=4096)
    ap.add_argument("--arbiter", default="",
                    help="HOST:PORT of the shard-0 worker; fenced "
                         "writes on this shard validate there (empty "
                         "for shard 0 itself)")
    ap.add_argument("--token", default="")
    ap.add_argument("--admission", action="store_true",
                    help="run the admission webhook chain in this "
                         "worker (interceptors live at the "
                         "authoritative store)")
    ap.add_argument("--scheduler-name", default="volcano")
    ap.add_argument("--default-queue", default="default")
    ap.add_argument("--faults", default=None)
    ap.add_argument("--admission-lanes", default="",
                    help="per-lane admission bounds for THIS worker's "
                         "gate (lane=inflight[:queue[:streams]],...); "
                         "each worker sheds independently, so one hot "
                         "shard never touches its siblings")
    ap.add_argument("--admission-queue-wait-ms", type=float,
                    default=None,
                    help="max milliseconds a request waits in a full "
                         "lane before it is shed typed")
    ap.add_argument("--parent-pid", type=int, default=0,
                    help="exit when this process is no longer the "
                         "parent (supervisor died; don't leak workers "
                         "holding ports)")
    args = ap.parse_args(argv)

    from ..resilience.faultinject import faults as _faults
    if args.faults:
        _faults.configure(args.faults)

    from .durable import DurableClusterStore
    if args.data_dir:
        store: ClusterStore = DurableClusterStore(
            args.data_dir, fsync=args.fsync,
            fsync_interval_s=args.fsync_interval,
            snapshot_every=args.snapshot_every,
            shard=str(args.shard))
    else:
        store = ClusterStore()
    if args.arbiter:
        store._fence_arbiter = _RemoteFenceArbiter(  # type: ignore[attr-defined]
            args.arbiter, token=args.token or None)
    peer_view = None
    if args.admission:
        # same order as standalone: recovery (constructor, above) runs
        # BEFORE interceptors install — recovered objects were admitted
        # when they first committed — and interceptors install before
        # the port opens, so no early write slips past the chain. The
        # chain's read horizon is the whole cluster via peer reads
        # (set_peers arrives from the supervisor once all workers are
        # up; until then reads are local)
        from ..webhooks import start_webhooks
        peer_view = _PeerReadStore(store, args.shard,
                                   token=args.token or None)
        start_webhooks(peer_view, scheduler_name=args.scheduler_name,
                       default_queue=args.default_queue)
    # each worker owns its own admission gate: one hot shard sheds
    # without touching its siblings (the router's gate fronts the
    # cross-shard ops; single-key traffic meets only this one)
    from ..resilience.overload import AdmissionGate, parse_lane_spec
    gate_kw = {}
    if args.admission_queue_wait_ms is not None:
        gate_kw["queue_wait_ms"] = args.admission_queue_wait_ms
    gate = AdmissionGate(parse_lane_spec(args.admission_lanes or None),
                         **gate_kw)
    server = ShardWorkerServer(store, args.shard, port=args.port,
                               token=args.token or None, gate=gate)
    server._server.peer_view = peer_view  # type: ignore[attr-defined]
    server.start()
    print(f"READY {server.port} shard={args.shard} rv={store._rv} "
          f"recovered={getattr(store, 'recovered_records', 0)} "
          f"pid={os.getpid()}", flush=True)
    try:
        while True:
            if args.parent_pid and os.getppid() != args.parent_pid:
                log.warning("shard worker %d: supervisor (pid %d) is "
                            "gone; exiting", args.shard, args.parent_pid)
                break
            time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    server.stop()
    close = getattr(store, "close", None)
    if close is not None:
        close()
    return 0


# -- the supervisor -----------------------------------------------------------


class _Worker:
    __slots__ = ("idx", "port", "data_dir", "proc", "pid", "alive",
                 "restarts", "started_at", "restarting", "last_rv",
                 "last_poll_t", "events_per_sec", "idle_socks")

    def __init__(self, idx: int, data_dir: Optional[str]):
        self.idx = idx
        self.port = 0
        self.data_dir = data_dir
        self.proc: Optional[subprocess.Popen] = None
        self.pid: Optional[int] = None
        self.alive = False
        self.restarts = 0
        self.started_at = 0.0
        self.restarting = False
        self.last_rv: Optional[int] = None
        self.last_poll_t = 0.0
        self.events_per_sec = 0.0
        self.idle_socks: List[socket.socket] = []


class ShardProcSupervisor:
    """Spawn one worker process per shard, monitor them, restart the
    dead with capped exponential backoff on the SAME port + data dir
    (construction is recovery). See module docstring."""

    def __init__(self, n_shards: int, data_dir: Optional[str] = None,
                 fsync: str = "every", fsync_interval_s: float = 0.05,
                 snapshot_every: int = 4096,
                 token: Optional[str] = None,
                 scheduler_name: str = "volcano",
                 default_queue: str = "default",
                 admission: bool = True,
                 worker_faults=None,
                 admission_lanes: Optional[str] = None,
                 admission_queue_wait_ms: Optional[float] = None,
                 restart_backoff_base_s: float = 0.2,
                 restart_backoff_cap_s: float = 5.0,
                 ready_timeout_s: float = 60.0):
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.n_shards = int(n_shards)
        self.data_dir = data_dir
        self.fsync = fsync
        self.fsync_interval_s = fsync_interval_s
        self.snapshot_every = snapshot_every
        self.token = token or ""
        self.scheduler_name = scheduler_name
        self.default_queue = default_queue
        self.admission = admission
        #: fault spec applied to every worker, or {shard_idx: spec}
        self.worker_faults = worker_faults
        #: per-lane admission bounds handed to every worker's own gate
        self.admission_lanes = admission_lanes
        self.admission_queue_wait_ms = admission_queue_wait_ms
        self.restart_backoff_base_s = restart_backoff_base_s
        self.restart_backoff_cap_s = restart_backoff_cap_s
        self.ready_timeout_s = ready_timeout_s
        #: called (idx) after a dead worker came back READY — the
        #: on_shard_recovered seam (the worker's own journal re-seeded
        #: from its recovered WAL tail during construction)
        self.on_shard_recovered: Optional[Callable[[int], None]] = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._started = False
        self.workers = [
            _Worker(i, os.path.join(data_dir, f"shard-{i:03d}")
                    if data_dir else None)
            for i in range(self.n_shards)]
        self._monitor_thread: Optional[threading.Thread] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "ShardProcSupervisor":
        # shard 0 first: it is the fencing arbiter, and the other
        # workers need its (stable) endpoint at spawn time
        self._spawn(self.workers[0])
        for w in self.workers[1:]:
            self._spawn(w)
        for w in self.workers:
            self._send_peers(w)
        self._started = True
        self._monitor_thread = threading.Thread(
            target=self._monitor, daemon=True, name="shard-supervisor")
        self._monitor_thread.start()
        return self

    def _send_peers(self, w: _Worker) -> None:
        """Hand a worker the full endpoint map so its admission chain
        can read across shards (no-op for admission-less workers)."""
        if not self.admission or self.n_shards <= 1:
            return
        try:
            self.request(w.idx, {"op": "set_peers",
                                 "endpoints": self.endpoints(),
                                 "n_shards": self.n_shards})
        except Exception:  # noqa: BLE001 — reads stay local until retried
            log.exception("set_peers to shard worker %d failed", w.idx)

    def stop(self) -> None:
        self._stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout=5)
        for w in self.workers:
            with self._lock:
                socks, w.idle_socks = w.idle_socks, []
            for s in socks:
                try:
                    s.close()
                except OSError:
                    pass
            if w.proc is not None and w.proc.poll() is None:
                w.proc.terminate()
        for w in self.workers:
            if w.proc is None:
                continue
            try:
                w.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                w.proc.kill()
                try:
                    w.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass

    # -- spawning -----------------------------------------------------------

    def _faults_for(self, idx: int) -> Optional[str]:
        wf = self.worker_faults
        if isinstance(wf, dict):
            return wf.get(idx)
        return wf

    def _spawn(self, w: _Worker) -> None:
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        cmd = [sys.executable, "-m", "volcano_tpu.client.shardproc",
               "--shard", str(w.idx), "--port", str(w.port),
               "--data-dir", w.data_dir or "",
               "--fsync", self.fsync,
               "--fsync-interval", str(self.fsync_interval_s),
               "--snapshot-every", str(self.snapshot_every),
               "--scheduler-name", self.scheduler_name,
               "--default-queue", self.default_queue,
               "--parent-pid", str(os.getpid())]
        if self.token:
            cmd += ["--token", self.token]
        if self.admission:
            cmd += ["--admission"]
        if self.admission_lanes:
            cmd += ["--admission-lanes", self.admission_lanes]
        if self.admission_queue_wait_ms is not None:
            cmd += ["--admission-queue-wait-ms",
                    str(self.admission_queue_wait_ms)]
        if w.idx != 0:
            cmd += ["--arbiter", self.endpoint(0)]
        spec = self._faults_for(w.idx)
        if spec:
            cmd += ["--faults", spec]
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                cwd=repo_root)
        deadline = time.time() + self.ready_timeout_s
        line = ""
        while time.time() < deadline:
            line = proc.stdout.readline()
            if line.startswith("READY"):
                break
            if proc.poll() is not None:
                break
        if not line.startswith("READY"):
            tail = proc.stdout.read() if proc.stdout else ""
            proc.kill()
            raise RuntimeError(
                f"shard worker {w.idx} failed to start "
                f"(rc={proc.poll()}): {line!r} {tail[-500:]!r}")
        w.port = int(line.split()[1])
        w.proc = proc
        w.pid = proc.pid
        w.started_at = time.time()
        w.alive = True
        # drain (and discard) the worker's remaining output so its logs
        # can never fill the pipe and block it mid-serve
        threading.Thread(target=self._drain, args=(proc,), daemon=True,
                         name=f"shard-drain-{w.idx}").start()
        self._export(w)
        log.info("shard worker %d up: pid=%d port=%d", w.idx, w.pid,
                 w.port)

    @staticmethod
    def _drain(proc: subprocess.Popen) -> None:
        try:
            for _ in proc.stdout:  # type: ignore[union-attr]
                pass
        except (OSError, ValueError):
            pass

    # -- monitoring / restart ----------------------------------------------

    def _monitor(self) -> None:
        while not self._stop.is_set():
            for w in self.workers:
                if (w.alive and not w.restarting and w.proc is not None
                        and w.proc.poll() is not None):
                    w.alive = False
                    w.restarting = True
                    self._export(w)
                    log.error("shard worker %d (pid %s) died (rc=%s); "
                              "restarting with backoff", w.idx, w.pid,
                              w.proc.poll())
                    threading.Thread(target=self._restart, args=(w,),
                                     daemon=True,
                                     name=f"shard-restart-{w.idx}").start()
            self._poll_stats()
            self._stop.wait(0.1)

    def _restart(self, w: _Worker) -> None:
        # dead worker: drop its pooled sockets (they point at a corpse)
        with self._lock:
            socks, w.idle_socks = w.idle_socks, []
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        backoff = self.restart_backoff_base_s
        while not self._stop.is_set():
            self._stop.wait(backoff)
            backoff = min(backoff * 2.0, self.restart_backoff_cap_s)
            if self._stop.is_set():
                break
            try:
                # SAME port + data dir: construction IS recovery, the
                # endpoint stays stable for direct-routed clients, and
                # the fresh journal window seeds from the recovered
                # WAL tail
                self._spawn(w)
            except Exception:  # noqa: BLE001 — keep backing off
                log.exception("shard worker %d restart failed; backing "
                              "off %.2fs", w.idx, backoff)
                continue
            w.restarts += 1
            self._send_peers(w)  # the endpoint map survives the restart
            self._export(w)
            if self.on_shard_recovered is not None:
                try:
                    self.on_shard_recovered(w.idx)
                except Exception:  # noqa: BLE001 — seam must not kill us
                    log.exception("on_shard_recovered(%d) failed", w.idx)
            break
        w.restarting = False

    def _poll_stats(self) -> None:
        now = time.time()
        for w in self.workers:
            if not w.alive or now - w.last_poll_t < 2.0:
                continue
            try:
                info = self.request(w.idx, {"op": "store_info"})
            except Exception:  # noqa: BLE001 — stats only
                continue
            rv = info.get("rv")
            if isinstance(rv, int) and w.last_rv is not None \
                    and now > w.last_poll_t:
                # each committed mutation advances the worker's rv by
                # one, so the rv delta IS the shard's ingested events
                w.events_per_sec = round(
                    max(0, rv - w.last_rv) / (now - w.last_poll_t), 1)
            if isinstance(rv, int):
                w.last_rv = rv
            w.last_poll_t = now
            self._export(w)

    def _export(self, w: _Worker) -> None:
        try:
            from ..metrics import metrics
            labels = {"shard": str(w.idx)}
            metrics.store_shard_worker_up.set(
                1.0 if w.alive else 0.0, labels=labels)
            if w.pid is not None:
                metrics.store_shard_worker_pid.set(w.pid, labels=labels)
            metrics.store_shard_worker_uptime_seconds.set(
                round(time.time() - w.started_at, 1) if w.alive else 0.0,
                labels=labels)
            # counter: export the absolute restart count once per change
            delta = w.restarts - metrics.store_shard_worker_restarts_total \
                .get(labels)
            if delta > 0:
                metrics.store_shard_worker_restarts_total.inc(
                    delta, labels=labels)
            metrics.store_shard_ingest_events_per_sec.set(
                w.events_per_sec, labels=labels)
        except Exception:  # noqa: BLE001 — accounting only
            pass

    # -- worker I/O ---------------------------------------------------------

    def endpoint(self, idx: int) -> str:
        return f"127.0.0.1:{self.workers[idx].port}"

    def endpoints(self) -> List[str]:
        return [self.endpoint(i) for i in range(self.n_shards)]

    def alive(self, idx: int) -> bool:
        return self.workers[idx].alive

    def connect(self, idx: int,
                timeout: Optional[float] = 5.0) -> socket.socket:
        """A fresh authed socket to worker ``idx`` (watch/ship relays
        own their streams)."""
        w = self.workers[idx]
        if not w.alive:
            raise ShardUnavailableError(
                f"store shard {idx} worker is down (restarting)")
        sock = socket.create_connection(("127.0.0.1", w.port),
                                        timeout=timeout)
        sock.settimeout(None)
        sock.sendall(MAGIC)
        if self.token:
            send_frame(sock, {"op": "auth", "token": self.token})
            resp = recv_frame(sock)
            if not resp.get("ok"):
                sock.close()
                raise_remote(resp)
        return sock

    def request(self, idx: int, payload: dict) -> dict:
        """One raw request/response against worker ``idx`` over a pooled
        socket. A send that never completed retries once on a fresh
        socket (stale pool entry); a failure AFTER the send propagates
        as the ConnectionError it is — the router's client then applies
        its own retry rules, exactly as if its own link had dropped."""
        w = self.workers[idx]
        for attempt in (0, 1):
            if not w.alive:
                raise ShardUnavailableError(
                    f"store shard {idx} worker is down (restarting)")
            with self._lock:
                sock = w.idle_socks.pop() if w.idle_socks else None
            fresh = sock is None
            sent = False
            try:
                if sock is None:
                    sock = self.connect(idx)
                send_frame(sock, payload)
                sent = True
                resp = recv_frame(sock)
            except (ConnectionError, OSError):
                if sock is not None:
                    try:
                        sock.close()
                    except OSError:
                        pass
                if sent or fresh or attempt:
                    raise
                continue  # stale pooled socket: one fresh-socket retry
            with self._lock:
                pooled = len(w.idle_socks) < _WORKER_POOL_MAX and w.alive
                if pooled:
                    w.idle_socks.append(sock)
            if not pooled:
                try:
                    sock.close()
                except OSError:
                    pass
            return resp
        raise ConnectionError(f"shard {idx} request failed")  # unreachable

    def topology(self) -> dict:
        now = time.time()
        return {
            "ok": True, "n_shards": self.n_shards,
            "endpoints": self.endpoints(),
            "workers": [{
                "shard": w.idx, "endpoint": self.endpoint(w.idx),
                "pid": w.pid, "alive": w.alive,
                "restarts": w.restarts,
                "uptime_s": round(now - w.started_at, 1)
                if w.alive else 0.0,
                "rv": w.last_rv,
                "events_per_sec": w.events_per_sec,
            } for w in self.workers],
        }


# -- the router-side store view ----------------------------------------------


class _WorkerBuckets:
    """Introspection shim: ``view._buckets[kind]`` as a {key: obj} dict
    fetched from the worker (tests and debugging tooling peek at shard
    contents this way on the in-process store)."""

    def __init__(self, sup: ShardProcSupervisor, idx: int):
        self._sup = sup
        self._idx = idx

    def __getitem__(self, kind: str) -> Dict[str, Any]:
        resp = self._sup.request(self._idx, {"op": "list", "kind": kind})
        if not resp.get("ok"):
            raise_remote(resp)
        objs = [decode(o) for o in resp["objs"]]
        return {_key(o): o for o in objs}


class _WorkerView:
    """One worker as seen from the router process: remote introspection
    (``_buckets``, ``_rv``, ``recovered_records``) over the supervisor's
    request pool."""

    def __init__(self, sup: ShardProcSupervisor, idx: int):
        self._sup = sup
        self.idx = idx
        self._buckets = _WorkerBuckets(sup, idx)

    def _info(self) -> dict:
        resp = self._sup.request(self.idx, {"op": "store_info"})
        if not resp.get("ok"):
            raise_remote(resp)
        return resp

    @property
    def _rv(self) -> int:
        return int(self._info()["rv"])

    @property
    def recovered_records(self) -> int:
        return int(self._info().get("recovered", 0))


class ProcShardedStore:
    """The ShardedClusterStore surface over worker PROCESSES: routing
    and fan-out happen here (in the router process), commits happen in
    the workers. ``dispatch`` is the router's wire path — it forwards
    the client's encoded frames verbatim, so the router never decodes an
    object it only needs to route."""

    def __init__(self, sup: ShardProcSupervisor):
        self.sup = sup
        self.n_shards = sup.n_shards
        self.data_dir = sup.data_dir
        self._mu = threading.RLock()
        self.shards = [_WorkerView(sup, i) for i in range(self.n_shards)]
        # forwarded to the router seam so a restarted worker's recovery
        # is observable (the worker re-seeded its own journal already)
        self.on_shard_recovered: Optional[Callable] = None
        sup.on_shard_recovered = self._on_recovered

    def _on_recovered(self, idx: int) -> None:
        if self.on_shard_recovered is not None:
            self.on_shard_recovered(idx, self.shards[idx])

    def locked(self):
        return self._mu

    def shard_of(self, kind: str, key: str) -> int:
        return shard_for(kind, key, self.n_shards)

    # -- the wire path (router dispatch) ------------------------------------

    def dispatch(self, op: str, req: dict) -> dict:
        if op in ("create", "update", "apply"):
            idx = self.shard_of(req.get("kind"),
                                encoded_key(req.get("obj") or {}))
            faults.fire("shard_crash")
            return self.sup.request(idx, req)
        if op in ("delete", "get"):
            ns = req.get("namespace")
            key = f"{ns}/{req['name']}" if ns is not None else req["name"]
            idx = self.shard_of(req.get("kind"), key)
            if op == "delete":
                faults.fire("shard_crash")
            return self.sup.request(idx, req)
        if op == "list":
            return self._list(req)
        if op == "bulk_apply":
            return self._bulk(req)
        if op == "store_info":
            rvs: Dict[str, Any] = {}
            durable = self.data_dir is not None
            recovered = 0
            for i in range(self.n_shards):
                info = self.sup.request(i, {"op": "store_info"})
                rvs[str(i)] = info.get("rv")
                recovered += int(info.get("recovered", 0))
            return {"ok": True, "rv": rvs, "shards": self.n_shards,
                    "durable": durable, "ship_capable": durable,
                    "recovered": recovered,
                    "pid": os.getpid()}
        if op == "topology":
            return self.sup.topology()
        if op == "bootstrap":
            idx = int(req.get("shard") or 0)
            if not 0 <= idx < self.n_shards:
                raise RuntimeError(
                    f"shard {idx} out of range (store has "
                    f"{self.n_shards})")
            # the worker is its own shard 0
            return self.sup.request(idx, dict(req, shard=0))
        if op == "fence_check":
            return self.sup.request(0, req)
        if op in ("ping", "auth"):
            return {"ok": True}
        raise RuntimeError(f"unknown op {op!r}")

    def _list(self, req: dict) -> dict:
        objs: List[Any] = []
        rvs: Dict[str, Any] = {}
        for i in range(self.n_shards):
            # a partial list during a worker outage would silently hide
            # that shard's objects — ShardUnavailableError refuses
            resp = self.sup.request(i, req)
            if not resp.get("ok"):
                return resp
            objs.extend(resp["objs"])
            rvs[str(i)] = resp.get("applied_rv")
        return {"ok": True, "objs": objs, "applied_rv": rvs}

    def _bulk(self, req: dict) -> dict:
        items = req.get("items") or []
        ack = bool(req.get("ack"))
        fencing = req.get("fencing")
        results: List[Any] = [None] * len(items)
        by_shard: Dict[int, List] = {}
        for i, it in enumerate(items):
            try:
                idx = self.shard_of(it.get("kind"),
                                    encoded_key(it.get("obj") or {}))
            except Exception as e:  # noqa: BLE001 — per-item containment
                results[i] = {"error": type(e).__name__, "message": str(e)}
                continue
            by_shard.setdefault(idx, []).append((i, it))
        sub_resp: Dict[int, Any] = {}

        def run(idx: int, sub: List) -> None:
            try:
                faults.fire("shard_crash")
                payload = {"op": "bulk_apply",
                           "items": [it for _, it in sub],
                           "fencing": fencing}
                if ack:
                    payload["ack"] = True
                sub_resp[idx] = self.sup.request(idx, payload)
            except Exception as e:  # noqa: BLE001 — contain the shard
                sub_resp[idx] = e

        # parallel per-shard dispatch: every worker commits (and fsyncs)
        # its sub-batch CONCURRENTLY in its own process — the wave costs
        # the slowest shard, not the sum
        if len(by_shard) > 1:
            threads = [threading.Thread(target=run, args=(idx, sub),
                                        name=f"bulk-shard-{idx}")
                       for idx, sub in by_shard.items()]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        else:
            for idx, sub in by_shard.items():
                run(idx, sub)
        for idx, sub in by_shard.items():
            resp = sub_resp.get(idx)
            if isinstance(resp, Exception):
                # a down (or mid-request-dead) worker costs ITS items,
                # not the wave; a ConnectionError here is ambiguous the
                # same way a dropped client link is — surfaced typed
                err = {"error": "ShardUnavailableError",
                       "message": f"store shard {idx}: "
                                  f"{type(resp).__name__}: {resp}"}
                for i, _ in sub:
                    results[i] = err
            elif not resp.get("ok"):
                err = {"error": resp.get("error", "RuntimeError"),
                       "message": resp.get("message", "bulk failed")}
                for i, _ in sub:
                    results[i] = err
            elif ack:
                errors = resp.get("errors") or {}
                for k, (i, _) in enumerate(sub):
                    results[i] = errors.get(str(k))
            else:
                for (i, _), r in zip(sub, resp["results"]):
                    results[i] = r
        if ack:
            return {"ok": True, "n": len(items),
                    "errors": {str(i): r for i, r in enumerate(results)
                               if r is not None}}
        return {"ok": True, "results": results}

    # -- the object surface (tests, in-process embedding) -------------------

    def _call(self, payload: dict) -> dict:
        resp = self.dispatch(payload["op"], payload)
        if not resp.get("ok"):
            raise_remote(resp)
        return resp

    def create(self, kind: str, obj, fencing: Optional[dict] = None):
        return decode(self._call({"op": "create", "kind": kind,
                                  "obj": encode(obj),
                                  "fencing": fencing})["obj"])

    def update(self, kind: str, obj, fencing: Optional[dict] = None):
        return decode(self._call({"op": "update", "kind": kind,
                                  "obj": encode(obj),
                                  "fencing": fencing})["obj"])

    def apply(self, kind: str, obj, fencing: Optional[dict] = None):
        return decode(self._call({"op": "apply", "kind": kind,
                                  "obj": encode(obj),
                                  "fencing": fencing})["obj"])

    def delete(self, kind: str, name: str, namespace: Optional[str] = None,
               fencing: Optional[dict] = None):
        return decode(self._call({"op": "delete", "kind": kind,
                                  "name": name, "namespace": namespace,
                                  "fencing": fencing})["obj"])

    def get(self, kind: str, name: str, namespace: Optional[str] = None):
        return decode(self._call({"op": "get", "kind": kind, "name": name,
                                  "namespace": namespace})["obj"])

    def try_get(self, kind: str, name: str,
                namespace: Optional[str] = None):
        from .store import NotFoundError
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None,
             name_glob: Optional[str] = None) -> List[Any]:
        resp = self._call({"op": "list", "kind": kind,
                           "namespace": namespace,
                           "label_selector": label_selector,
                           "name_glob": name_glob})
        return [decode(o) for o in resp["objs"]]

    def bulk_apply(self, items, fencing: Optional[dict] = None) -> List[Any]:
        enc = [{"kind": it[0], "obj": encode(it[1]),
                "verb": it[2] if len(it) > 2 else "apply"}
               for it in items]
        resp = self._call({"op": "bulk_apply", "items": enc,
                           "fencing": fencing})
        return [remote_error(r) if "error" in r else decode(r["obj"])
                for r in resp["results"]]

    @property
    def recovered_records(self) -> int:
        return sum(s.recovered_records for s in self.shards)

    @property
    def _rv(self) -> int:
        return max(s._rv for s in self.shards)

    def last_event_rv(self, kind: str) -> int:
        # informational (READY banners); workers own the real sequences
        return self._rv

    def close(self) -> None:
        self.sup.stop()


# -- the router ---------------------------------------------------------------


class _NullJournal:
    """The multi-process router keeps NO resume journals: each worker's
    own EventJournal (seeded from its recovered WAL tail) serves its
    shard's resume window, and watch relays forward resume requests to
    the owning workers."""

    def close(self) -> None:
        pass


class _ProcRouterHandler(_Handler):
    """The wire protocol over worker processes: unary ops route/fan via
    ProcShardedStore.dispatch (frames forwarded verbatim); watch/
    bulk_watch/ship relay the workers' already-shard-tagged frames
    byte-for-byte."""

    def _dispatch(self, store: ProcShardedStore, op: str,
                  req: dict) -> dict:
        # same contract as the in-process router: an armed shard_request
        # fault is ConnectionError-shaped and kills this connection so
        # the client's transport-retry rules engage
        faults.fire("shard_request")
        if op == "admission_info":
            # the router's own gate, plus each worker's (every worker
            # owns an independent gate — one hot shard sheds alone)
            resp = self._admission_info()
            workers: Dict[str, Any] = {}
            for i in range(store.n_shards):
                try:
                    wr = store.sup.request(i, {"op": "admission_info"})
                    workers[str(i)] = wr.get("lanes") \
                        if wr.get("ok") else None
                except Exception:  # noqa: BLE001 — down worker: no table
                    workers[str(i)] = None
            resp["workers"] = workers
            return resp
        if op == "announce_read_endpoint":
            # the registry lives on the router server (base handler);
            # workers never see announcements
            return _Handler._dispatch(self, store, op, req)
        resp = store.dispatch(op, req)
        if op == "topology" and resp.get("ok"):
            # merge the announced read tier into the worker endpoint map
            table = getattr(self.server, "read_endpoints", {}) or {}
            resp["read_endpoints"] = [
                {"endpoint": ep, "depth": meta.get("depth", 1),
                 "shards": meta.get("shards", 1)}
                for ep, meta in table.items()]
        elif op == "store_info" and resp.get("ok"):
            counts = getattr(self.server, "op_counts", None)
            resp["requests"] = dict(counts) if counts is not None else {}
        return resp

    def _serve_watch(self, sock: socket.socket, store: ProcShardedStore,
                     req: dict) -> None:
        kinds = req.get("kinds") or [req.get("kind")]
        bad = [k for k in kinds if k not in KINDS]
        if bad:
            send_frame(sock, {"ok": False, "error": "RuntimeError",
                              "message": f"unknown watch kinds {bad}"})
            return
        replay = bool(req.get("replay", True))
        since = req.get("since") or None
        sup = store.sup
        n = store.n_shards
        if since is not None:
            for kind in kinds:
                smap = since.get(kind)
                if not isinstance(smap, dict) and n != 1:
                    send_frame(sock, {
                        "ok": False, "error": "ResumeGapError",
                        "message": f"resume for {kind!r}: scalar resume "
                                   f"mark against {n} shards"})
                    return
        upstreams: List[socket.socket] = []
        stop = threading.Event()
        # bound every client send (replay phase included): a peer that
        # stalls without closing must not pin this handler thread
        sock.settimeout(WATCH_SEND_TIMEOUT_S)
        try:
            # one upstream stream per worker; each worker replays its
            # own objects / its own journal window and stamps its shard
            # tag, so this relay forwards frames verbatim
            for i in range(n):
                try:
                    usock = sup.connect(i)
                except Exception as e:  # noqa: BLE001 — typed refusal
                    send_frame(sock, {
                        "ok": False, "error": "ShardUnavailableError",
                        "message": f"store shard {i}: {e}"})
                    return
                upstreams.append(usock)
                ureq: dict = {"op": req.get("op", "watch"),
                              "kinds": kinds, "replay": replay}
                if req.get("delta"):
                    # forward the delta ask verbatim: workers emit the
                    # delta frames; this relay stays byte-verbatim
                    ureq["delta"] = True
                if since is not None:
                    ureq["replay"] = False
                    ureq["since"] = {
                        k: (since.get(k) if isinstance(since.get(k), dict)
                            else {"0": since.get(k)})
                        for k in kinds}
                send_frame(usock, ureq)
            # phase 1: drain each upstream to its synced marker, relaying
            # replay frames; hold the synced frames back and emit ONE
            # merged {kind: {shard: rv}} marker (the client returns from
            # its inline replay at the first synced it sees)
            synced_rv: Dict[str, Dict[str, Any]] = {k: {} for k in kinds}
            # delta merge: every worker must have negotiated delta for
            # the merged stream to be delta (fail-safe: one old worker
            # quietly demotes the whole stream to object frames — the
            # client simply sees its ask declined)
            delta_ok = bool(req.get("delta"))
            synced_vtab: Dict[str, dict] = {}
            synced_ks: Dict[str, Dict[str, int]] = {k: {} for k in kinds}
            for i, usock in enumerate(upstreams):
                while True:
                    raw = recv_frame_raw(usock)
                    msg = json.loads(raw)
                    if msg.get("ok") is False:
                        send_frame_raw(sock, raw)  # e.g. ResumeGapError
                        return
                    stream = msg.get("stream")
                    if stream == "synced":
                        for k, val in (msg.get("rv") or {}).items():
                            if isinstance(val, dict):
                                synced_rv.setdefault(k, {}).update(val)
                            else:
                                synced_rv.setdefault(k, {})[str(i)] = val
                        if delta_ok:
                            if msg.get("delta"):
                                # vtab is {kind: {shard: entries}} and
                                # workers own disjoint shards, so the
                                # per-kind inner maps merge cleanly
                                for k, m in (msg.get("vtab")
                                             or {}).items():
                                    synced_vtab.setdefault(
                                        k, {}).update(m)
                                for k, m in (msg.get("ks") or {}).items():
                                    synced_ks.setdefault(k, {}).update(m)
                            else:
                                delta_ok = False
                        break
                    if stream in ("event", "events"):
                        send_frame_raw(sock, raw)
                    # heartbeats are dropped during the open phase
            merged: dict = {"stream": "synced", "rv": synced_rv}
            if delta_ok:
                merged["delta"] = True
                merged["vtab"] = synced_vtab
                merged["ks"] = synced_ks
            send_frame(sock, merged)
            # phase 2: pure byte relay — N reader threads feed one
            # writer (this thread), which serializes frames onto the
            # client socket
            frames: "queue.Queue" = queue.Queue(maxsize=WATCH_QUEUE_MAX)

            def pump_up(us: socket.socket) -> None:
                try:
                    while not stop.is_set():
                        frames.put(recv_frame_raw(us),
                                   timeout=WATCH_SEND_TIMEOUT_S)
                except (ConnectionError, OSError, ValueError,
                        queue.Full):
                    pass
                finally:
                    stop.set()
                    try:
                        frames.put_nowait(_EOF)
                    except queue.Full:
                        pass

            readers = [threading.Thread(target=pump_up, args=(us,),
                                        daemon=True,
                                        name=f"watch-relay-{i}")
                       for i, us in enumerate(upstreams)]
            for t in readers:
                t.start()
            while True:
                try:
                    raw = frames.get(timeout=1.0)
                except queue.Empty:
                    if stop.is_set():
                        break  # an upstream died: condemn this stream;
                    continue   # the client resumes via since:
                if raw is _EOF:
                    break
                send_frame_raw(sock, raw)
        except (ConnectionError, OSError, socket.timeout, ValueError):
            pass  # peer (or a worker) went away
        finally:
            stop.set()
            for us in upstreams:
                try:
                    us.close()
                except OSError:
                    pass

    def _serve_ship(self, sock: socket.socket, store: ProcShardedStore,
                    req: dict) -> None:
        """Relay a WAL ship stream to the worker owning the requested
        shard lineage (the worker is its own shard 0) — replicas can
        ride the router, or tail the worker endpoint directly (see the
        ``topology`` op)."""
        idx = int(req.get("shard") or 0)
        if not 0 <= idx < store.n_shards:
            send_frame(sock, {"ok": False, "error": "RuntimeError",
                              "message": f"shard {idx} out of range "
                                         f"(store has {store.n_shards})"})
            return
        try:
            usock = store.sup.connect(idx)
        except Exception as e:  # noqa: BLE001 — typed refusal
            send_frame(sock, {"ok": False,
                              "error": "ShardUnavailableError",
                              "message": f"store shard {idx}: {e}"})
            return
        try:
            send_frame(usock, dict(req, shard=0))
            sock.settimeout(WATCH_SEND_TIMEOUT_S)
            while True:
                send_frame_raw(sock, recv_frame_raw(usock))
        except (ConnectionError, OSError, socket.timeout, ValueError):
            pass
        finally:
            try:
                usock.close()
            except OSError:
                pass


class ProcShardRouter(StoreServer):
    """One endpoint, the existing wire protocol, N worker PROCESSES
    behind it. Thin by construction: it supervises (via the store's
    ShardProcSupervisor), proxies cross-shard ops, relays streams, and
    serves the ``topology`` op direct-routing clients bootstrap from —
    single-key traffic can bypass it entirely."""

    handler_class = _ProcRouterHandler

    def __init__(self, store: ProcShardedStore, host: str = "127.0.0.1",
                 port: int = 0, token: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 tls_client_ca: Optional[str] = None, gate=None):
        super().__init__(store, host=host, port=port, token=token,
                         tls_cert=tls_cert, tls_key=tls_key,
                         tls_client_ca=tls_client_ca, gate=gate)

    def _make_journal(self, store):
        return _NullJournal()


if __name__ == "__main__":
    raise SystemExit(main())
