"""RemoteClusterStore: the ClusterStore surface over a StoreServer socket.

Gives every store consumer — vcctl, SchedulerCache, controllers, leader
election — the same interface against a deployed control plane that the
in-memory ClusterStore gives them in-process (the reference's client-go
clientset + informer factory against the API server,
pkg/scheduler/cache/cache.go:319-402). CRUD is synchronous request/
response on one mutex-guarded connection; each watch() opens its own
streaming connection, applies the replay inline (list-then-watch: the
caller returns with state loaded, exactly like the in-memory store), then
keeps delivering live events from a reader thread. All listener dispatch
happens under self.locked(), so a consumer holding the lock (the
scheduler cache's snapshot) sees a frozen mirror.

Optimistic concurrency travels the wire: the server compares
resource_version on update and ConflictError/NotFoundError/AdmissionError
re-raise client-side as the same classes — which is what makes the lease
CAS of utils.leader_election work across processes.
"""

from __future__ import annotations

import logging
import os
import socket
import threading
from typing import Any, Callable, Dict, List, Optional

from .codec import decode, encode
from .server import MAGIC, raise_remote, recv_frame, send_frame

log = logging.getLogger(__name__)


class RemoteClusterStore:
    """See module docstring. Two deployment-facing knobs:

    - ``token``: shared-secret auth presented on every connection
      (defaults to $VOLCANO_STORE_TOKEN so vcctl and operator scripts
      pick it up without plumbing).
    - ``on_watch_failure``: called once when a watch stream dies. The
      cache's event handlers are NOT idempotent (replaying adds would
      double-count), so a broken stream cannot be transparently resumed;
      the crash-only answer is to exit and let the supervisor restart
      with a fresh snapshot (HA standbys cover the gap — client-go's
      reflector re-list is this build's process restart). The default
      logs CRITICAL and sets ``watch_failed``; long-running consumers
      (ha_scheduler_proc) pass an exiting callback."""

    def __init__(self, address: str, connect_timeout: float = 5.0,
                 token: Optional[str] = None,
                 on_watch_failure: Optional[Callable[[], None]] = None,
                 tls_ca: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None):
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.connect_timeout = connect_timeout
        self.token = token if token is not None \
            else os.environ.get("VOLCANO_STORE_TOKEN", "")
        # TLS to a StoreServer serving it (see its docstring): tls_ca is
        # the CA bundle the SERVER cert must verify against (also
        # $VOLCANO_STORE_CA); tls_cert/tls_key present a client
        # certificate for mTLS servers
        self.tls_ca = tls_ca if tls_ca is not None \
            else os.environ.get("VOLCANO_STORE_CA") or None
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self._ssl_ctx = None
        if self.tls_ca or self.tls_cert:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.check_hostname = False  # cluster-internal addr, CA-pinned
            ctx.verify_mode = ssl.CERT_REQUIRED
            if self.tls_ca:
                ctx.load_verify_locations(self.tls_ca)
            else:
                # client-cert-only config: verify the server against the
                # system trust store instead of an empty one
                ctx.load_default_certs()
            if self.tls_cert:
                ctx.load_cert_chain(self.tls_cert, self.tls_key)
            self._ssl_ctx = ctx
        self.on_watch_failure = on_watch_failure
        self.watch_failed = False
        self._lock = threading.RLock()   # local mirror/listener lock
        self._conn_lock = threading.Lock()  # serializes request/response
        self._conn: Optional[socket.socket] = None
        self._watch_threads: List[threading.Thread] = []
        self._watch_socks: List[socket.socket] = []
        self._closed = False

    # -- plumbing -----------------------------------------------------------

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.connect_timeout)
        if self._ssl_ctx is not None:
            sock = self._ssl_ctx.wrap_socket(
                sock, server_hostname=self.host)
        sock.settimeout(None)
        sock.sendall(MAGIC)
        if self.token:
            send_frame(sock, {"op": "auth", "token": self.token})
            resp = recv_frame(sock)
            if not resp.get("ok"):
                sock.close()
                raise_remote(resp)
        return sock

    def _request(self, payload: dict) -> dict:
        # Retry rules: a failed SEND is always safe to retry (the server
        # only acts on complete frames, and a broken connection can never
        # complete a partial one). A failure AFTER the send is ambiguous —
        # the server may have applied the op — so only idempotent reads
        # retry there; a mutating op surfaces the error to its caller
        # rather than risk double-apply.
        idempotent = payload.get("op") in ("get", "list", "ping")
        with self._conn_lock:
            for attempt in (0, 1):
                if self._conn is None:
                    self._conn = self._connect()
                sent = False
                try:
                    send_frame(self._conn, payload)
                    sent = True
                    resp = recv_frame(self._conn)
                    break
                except (ConnectionError, OSError):
                    try:
                        self._conn.close()
                    except OSError:
                        pass
                    self._conn = None
                    if attempt or (sent and not idempotent):
                        raise
        if not resp.get("ok"):
            raise_remote(resp)
        return resp

    def close(self) -> None:
        self._closed = True
        with self._conn_lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except OSError:
                    pass
                self._conn = None
        for sock in self._watch_socks:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._watch_socks = []

    # -- ClusterStore surface ----------------------------------------------

    def locked(self):
        return self._lock

    def create(self, kind: str, obj):
        return decode(self._request(
            {"op": "create", "kind": kind, "obj": encode(obj)})["obj"])

    def update(self, kind: str, obj):
        return decode(self._request(
            {"op": "update", "kind": kind, "obj": encode(obj)})["obj"])

    def apply(self, kind: str, obj):
        return decode(self._request(
            {"op": "apply", "kind": kind, "obj": encode(obj)})["obj"])

    def delete(self, kind: str, name: str, namespace: Optional[str] = None):
        return decode(self._request(
            {"op": "delete", "kind": kind, "name": name,
             "namespace": namespace})["obj"])

    def get(self, kind: str, name: str, namespace: Optional[str] = None):
        return decode(self._request(
            {"op": "get", "kind": kind, "name": name,
             "namespace": namespace})["obj"])

    def try_get(self, kind: str, name: str, namespace: Optional[str] = None):
        from .store import NotFoundError
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None,
             name_glob: Optional[str] = None) -> List[Any]:
        resp = self._request(
            {"op": "list", "kind": kind, "namespace": namespace,
             "label_selector": label_selector, "name_glob": name_glob})
        return [decode(o) for o in resp["objs"]]

    def ping(self) -> bool:
        return bool(self._request({"op": "ping"}).get("ok"))

    def add_interceptor(self, fn) -> None:
        raise NotImplementedError(
            "admission interceptors run in the process that OWNS the "
            "store (standalone --serve-store starts the webhook chain "
            "there); a remote client cannot install them")

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str, listener, replay: bool = True) -> None:
        """Subscribe over a dedicated streaming connection. The replay is
        applied inline before returning (list-then-watch, same synchronous
        contract as the in-memory store); live events are then delivered
        from a daemon reader thread under self.locked()."""
        sock = self._connect()
        # register BEFORE the replay loop: close() must be able to unblock
        # a watch() stuck mid-replay on a stalled server
        self._watch_socks.append(sock)
        send_frame(sock, {"op": "watch", "kinds": [kind], "replay": replay})
        while True:
            msg = recv_frame(sock)
            if msg.get("ok") is False:
                # server refused the subscription (e.g. unknown kind):
                # surface its message, not a dangling ConnectionError
                try:
                    self._watch_socks.remove(sock)
                except ValueError:
                    pass
                sock.close()
                raise_remote(msg)
            stream = msg.get("stream")
            if stream == "synced":
                break
            if stream == "event":
                # under self._lock like the reader threads: during the
                # cache's sequential subscriptions (nodes, then pods, ...)
                # a LIVE event on an earlier kind's stream must not mutate
                # the mirror concurrently with a later kind's replay —
                # cache handlers rely on the store serializing dispatch
                with self._lock:
                    self._deliver(listener, msg)

        def reader():
            try:
                while True:
                    msg = recv_frame(sock)
                    if msg.get("stream") != "event":
                        continue  # heartbeat
                    with self._lock:
                        self._deliver(listener, msg)
            except (ConnectionError, OSError, ValueError) as e:
                if not self._closed:
                    self._watch_broke(kind, e)
            except Exception as e:  # noqa: BLE001 — a listener blew up
                log.exception("watch listener for %s failed", kind)
                if not self._closed:
                    self._watch_broke(kind, e)
            finally:
                try:
                    sock.close()
                except OSError:
                    pass

        t = threading.Thread(target=reader, daemon=True,
                             name=f"store-watch-{kind}")
        t.start()
        self._watch_threads.append(t)

    def _watch_broke(self, kind: str, exc: Exception) -> None:
        """A watch stream died: the local mirror is permanently stale
        (see class docstring for why there is no transparent resume)."""
        with self._lock:  # streams die together when the server goes:
            first = not self.watch_failed  # fire the callback exactly once
            self.watch_failed = True
        log.critical(
            "watch stream for %r broke (%s: %s); this store's mirror is "
            "frozen — restart the consumer process to resync",
            kind, type(exc).__name__, exc)
        if first and self.on_watch_failure is not None:
            try:
                self.on_watch_failure()
            except Exception:  # noqa: BLE001 — never kill the reader hook
                log.exception("on_watch_failure callback failed")

    @staticmethod
    def _deliver(listener, msg: dict) -> None:
        old = msg.get("old")
        listener(msg["event"], decode(msg["obj"]),
                 decode(old) if old is not None else None)
