"""RemoteClusterStore: the ClusterStore surface over a StoreServer socket.

Gives every store consumer — vcctl, SchedulerCache, controllers, leader
election — the same interface against a deployed control plane that the
in-memory ClusterStore gives them in-process (the reference's client-go
clientset + informer factory against the API server,
pkg/scheduler/cache/cache.go:319-402). CRUD is synchronous request/
response on one mutex-guarded connection; each watch() opens its own
streaming connection, applies the replay inline (list-then-watch: the
caller returns with state loaded, exactly like the in-memory store), then
keeps delivering live events from a reader thread. All listener dispatch
happens under self.locked(), so a consumer holding the lock (the
scheduler cache's snapshot) sees a frozen mirror.

Optimistic concurrency travels the wire: the server compares
resource_version on update and ConflictError/NotFoundError/AdmissionError
re-raise client-side as the same classes — which is what makes the lease
CAS of utils.leader_election work across processes.
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Dict, List, Optional

from .codec import decode, encode
from .server import MAGIC, raise_remote, recv_frame, send_frame


class RemoteClusterStore:
    def __init__(self, address: str, connect_timeout: float = 5.0):
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.connect_timeout = connect_timeout
        self._lock = threading.RLock()   # local mirror/listener lock
        self._conn_lock = threading.Lock()  # serializes request/response
        self._conn: Optional[socket.socket] = None
        self._watch_threads: List[threading.Thread] = []
        self._watch_socks: List[socket.socket] = []
        self._closed = False

    # -- plumbing -----------------------------------------------------------

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.connect_timeout)
        sock.settimeout(None)
        sock.sendall(MAGIC)
        return sock

    def _request(self, payload: dict) -> dict:
        # Retry rules: a failed SEND is always safe to retry (the server
        # only acts on complete frames, and a broken connection can never
        # complete a partial one). A failure AFTER the send is ambiguous —
        # the server may have applied the op — so only idempotent reads
        # retry there; a mutating op surfaces the error to its caller
        # rather than risk double-apply.
        idempotent = payload.get("op") in ("get", "list", "ping")
        with self._conn_lock:
            for attempt in (0, 1):
                if self._conn is None:
                    self._conn = self._connect()
                sent = False
                try:
                    send_frame(self._conn, payload)
                    sent = True
                    resp = recv_frame(self._conn)
                    break
                except (ConnectionError, OSError):
                    try:
                        self._conn.close()
                    except OSError:
                        pass
                    self._conn = None
                    if attempt or (sent and not idempotent):
                        raise
        if not resp.get("ok"):
            raise_remote(resp)
        return resp

    def close(self) -> None:
        self._closed = True
        with self._conn_lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except OSError:
                    pass
                self._conn = None
        for sock in self._watch_socks:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._watch_socks = []

    # -- ClusterStore surface ----------------------------------------------

    def locked(self):
        return self._lock

    def create(self, kind: str, obj):
        return decode(self._request(
            {"op": "create", "kind": kind, "obj": encode(obj)})["obj"])

    def update(self, kind: str, obj):
        return decode(self._request(
            {"op": "update", "kind": kind, "obj": encode(obj)})["obj"])

    def apply(self, kind: str, obj):
        return decode(self._request(
            {"op": "apply", "kind": kind, "obj": encode(obj)})["obj"])

    def delete(self, kind: str, name: str, namespace: Optional[str] = None):
        return decode(self._request(
            {"op": "delete", "kind": kind, "name": name,
             "namespace": namespace})["obj"])

    def get(self, kind: str, name: str, namespace: Optional[str] = None):
        return decode(self._request(
            {"op": "get", "kind": kind, "name": name,
             "namespace": namespace})["obj"])

    def try_get(self, kind: str, name: str, namespace: Optional[str] = None):
        from .store import NotFoundError
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None,
             name_glob: Optional[str] = None) -> List[Any]:
        resp = self._request(
            {"op": "list", "kind": kind, "namespace": namespace,
             "label_selector": label_selector, "name_glob": name_glob})
        return [decode(o) for o in resp["objs"]]

    def ping(self) -> bool:
        return bool(self._request({"op": "ping"}).get("ok"))

    def add_interceptor(self, fn) -> None:
        raise NotImplementedError(
            "admission interceptors run in the process that OWNS the "
            "store (standalone --serve-store starts the webhook chain "
            "there); a remote client cannot install them")

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str, listener, replay: bool = True) -> None:
        """Subscribe over a dedicated streaming connection. The replay is
        applied inline before returning (list-then-watch, same synchronous
        contract as the in-memory store); live events are then delivered
        from a daemon reader thread under self.locked()."""
        sock = self._connect()
        self._watch_socks.append(sock)
        send_frame(sock, {"op": "watch", "kinds": [kind], "replay": replay})
        while True:
            msg = recv_frame(sock)
            stream = msg.get("stream")
            if stream == "synced":
                break
            if stream == "event":
                self._deliver(listener, msg)

        def reader():
            try:
                while True:
                    msg = recv_frame(sock)
                    if msg.get("stream") != "event":
                        continue  # heartbeat
                    with self._lock:
                        self._deliver(listener, msg)
            except (ConnectionError, OSError, ValueError):
                pass  # server went away; consumers resync on reconnect
            finally:
                try:
                    sock.close()
                except OSError:
                    pass

        t = threading.Thread(target=reader, daemon=True,
                             name=f"store-watch-{kind}")
        t.start()
        self._watch_threads.append(t)

    @staticmethod
    def _deliver(listener, msg: dict) -> None:
        old = msg.get("old")
        listener(msg["event"], decode(msg["obj"]),
                 decode(old) if old is not None else None)
