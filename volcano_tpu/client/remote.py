"""RemoteClusterStore: the ClusterStore surface over a StoreServer socket.

Gives every store consumer — vcctl, SchedulerCache, controllers, leader
election — the same interface against a deployed control plane that the
in-memory ClusterStore gives them in-process (the reference's client-go
clientset + informer factory against the API server,
pkg/scheduler/cache/cache.go:319-402). CRUD is synchronous request/
response on one mutex-guarded connection; each watch() opens its own
streaming connection, applies the replay inline (list-then-watch: the
caller returns with state loaded, exactly like the in-memory store), then
keeps delivering live events from a reader thread. All listener dispatch
happens under self.locked(), so a consumer holding the lock (the
scheduler cache's snapshot) sees a frozen mirror.

Optimistic concurrency travels the wire: the server compares
resource_version on update and ConflictError/NotFoundError/AdmissionError
re-raise client-side as the same classes — which is what makes the lease
CAS of utils.leader_election work across processes.
"""

from __future__ import annotations

import copy
import json
import logging
import os
import random
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..resilience.faultinject import faults
from ..resilience.overload import (
    OverloadedError, RetryBudget, RetryBudgetExhausted, classify,
    current_lane,
)
from .codec import (
    DELTA_VOCAB_MAX, decode, delta_resolve, encode, field_default,
    known_fields, object_key,
)
from .server import (
    MAGIC, raise_remote, recv_frame, recv_frame_sized, remote_error,
    send_frame,
)
from .sharded import shard_for
from .store import ResumeGapError, ShardUnavailableError, _key

log = logging.getLogger(__name__)

#: bulk_apply chunking: an oversized wave splits into frames of at most
#: this many encoded bytes / items each (one journal batch per chunk),
#: so a 50k-pod wave can never produce a single multi-MB frame that
#: trips the server's cap or stalls every other request behind it
BULK_CHUNK_BYTES = 8 << 20
BULK_CHUNK_ITEMS = 2048

#: wire ops whose responses carry an ``applied_rv`` stamp this client
#: folds into its read-your-writes high-water mark (applied_hwm)
_MUTATING_WIRE_OPS = ("create", "update", "apply", "delete", "bulk_apply")


class DeltaFallbackError(ValueError):
    """Typed refusal of a delta watch frame (the reason is ``args[0]``:
    ``delta_gap`` / ``vocab_overflow`` / ``unknown_field`` /
    ``schema_skew``). A ValueError so the stream reader's existing
    broken-stream handling catches it: the stream resumes through the
    normal journal-replay path — with the delta ask OFF — from a
    high-water mark the refused frame never advanced, so the fallback
    loses and repeats nothing."""


class RemoteClusterStore:
    """See module docstring. Deployment-facing knobs:

    - ``token``: shared-secret auth presented on every connection
      (defaults to $VOLCANO_STORE_TOKEN so vcctl and operator scripts
      pick it up without plumbing).
    - ``on_watch_failure``: called once when a watch stream dies beyond
      repair. A broken stream first tries to RESUME in place: reconnect
      with exponential backoff + jitter and ask the server to replay from
      this client's per-kind resource_version high-water mark (the
      server's EventJournal — client-go's reflector re-watch). Only when
      that fails — server gone past ``watch_resume_window_s``, journal
      window lost (ResumeGapError), or a listener itself blew up — does
      the crash-only contract fire: log CRITICAL, set ``watch_failed``,
      call the callback once so a supervisor can restart with a fresh
      snapshot (HA standbys cover the gap).
    - ``retry_attempts``/``retry_base_s``/``retry_cap_s``: idempotent-op
      retry budget (see _request) — defaults ride out a ~3 s server
      restart.
    - ``pool_size``: request connections kept PER ENDPOINT (default 1,
      the historical single-socket behavior). With N > 1, up to N
      requests are in flight concurrently per endpoint — the seam that
      lets fanned-out controller workers ingest in parallel instead of
      queueing behind one socket, and that keeps direct shard
      connections from serializing through the router's pool.
    - ``direct_routing`` (default True): ask the server for its shard
      ``topology`` once (lazily, on first routed op) and, when it
      names per-shard worker endpoints (the multi-process router,
      client/shardproc.py), send single-key CRUD/get straight to the
      owning shard — ``crc32(kind/ns/name) % N`` is deterministic and
      client-visible, so the router hop survives only for cross-shard
      ops (list, bulk waves, bulk_watch merge). Old servers without the
      op, single-process topologies, and TLS deployments (workers are
      loopback-plaintext) all degrade gracefully to router-only
      routing; so does any direct request whose connection fails before
      it could have been applied.
    - ``direct_watch`` (default False): also open watch/bulk_watch
      streams per shard worker directly — events bypass the router
      entirely; each stream resumes against its own worker's journal.
    """

    def __init__(self, address: str, connect_timeout: float = 5.0,
                 token: Optional[str] = None,
                 on_watch_failure: Optional[Callable[[], None]] = None,
                 tls_ca: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 retry_attempts: int = 5,
                 retry_base_s: float = 0.1,
                 retry_cap_s: float = 2.0,
                 watch_resume: bool = True,
                 watch_resume_window_s: float = 30.0,
                 watch_backoff_cap_s: float = 2.0,
                 pool_size: int = 1,
                 direct_routing: bool = True,
                 direct_watch: bool = False,
                 lane: Optional[str] = None,
                 op_deadline_ms: float = 0.0,
                 retry_budget: Optional[RetryBudget] = None,
                 delta_watch: bool = False,
                 read_from_replicas: bool = False):
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.connect_timeout = connect_timeout
        self.token = token if token is not None \
            else os.environ.get("VOLCANO_STORE_TOKEN", "")
        # TLS to a StoreServer serving it (see its docstring): tls_ca is
        # the CA bundle the SERVER cert must verify against (also
        # $VOLCANO_STORE_CA); tls_cert/tls_key present a client
        # certificate for mTLS servers
        self.tls_ca = tls_ca if tls_ca is not None \
            else os.environ.get("VOLCANO_STORE_CA") or None
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self._ssl_ctx = None
        if self.tls_ca or self.tls_cert:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.verify_mode = ssl.CERT_REQUIRED
            if self.tls_ca:
                # CA-pinned: the operator named the exact CA this server
                # must chain to, and cluster-internal addresses are
                # usually bare IPs — hostname matching adds nothing the
                # pin doesn't already guarantee
                ctx.check_hostname = False
                ctx.load_verify_locations(self.tls_ca)
            else:
                # client-cert-only config: falls back to the SYSTEM trust
                # store, where hostname verification is the only thing
                # stopping any public-CA cert for any host from
                # impersonating the store — keep it on (default True)
                ctx.load_default_certs()
            if self.tls_cert:
                ctx.load_cert_chain(self.tls_cert, self.tls_key)
            self._ssl_ctx = ctx
        self.on_watch_failure = on_watch_failure
        self.watch_failed = False
        self.retry_attempts = retry_attempts
        self.retry_base_s = retry_base_s
        self.retry_cap_s = retry_cap_s
        self.watch_resume = watch_resume
        self.watch_resume_window_s = watch_resume_window_s
        self.watch_backoff_cap_s = watch_backoff_cap_s
        self.watch_resumes = 0   # successful in-place stream resumes
        self._lock = threading.RLock()   # local mirror/listener lock
        # per-kind {shard: rv} high-water marks across ALL of this
        # client's watch streams — the causal floor a (possibly retried)
        # list response must not fall behind, and the catch-up target
        # wait_stream_applied blocks on
        self._kind_hwm: Dict[str, Dict[str, int]] = {}
        self._hwm_cv = threading.Condition(self._lock)
        #: applied_rv of the most recent list response (staleness at a
        #: glance for CLIs/dashboards)
        self.last_list_applied_rv = None
        # request-connection pools, one PER ENDPOINT (the router, plus —
        # direct-routed — each shard worker): idle sockets ready for
        # checkout, a live count capping concurrency at pool_size per
        # endpoint, and the full set so close() can unblock an in-flight
        # recv
        self.pool_size = max(1, int(pool_size))
        self._pool_cv = threading.Condition()
        self._default_ep = (self.host, self.port)
        self._pools: Dict[tuple, dict] = {}
        self._conns: set = set()
        # direct shard routing (see class docstring): topology is
        # fetched lazily, once; empty endpoints = router-only
        self.direct_routing = direct_routing
        self.direct_watch = direct_watch
        self._topo_lock = threading.Lock()
        self._topo_checked = False
        self._n_shards = 1
        self._shard_endpoints: List[tuple] = []
        self.direct_requests = 0    # requests sent straight to a shard
        self.direct_fallbacks = 0   # direct failures re-run via router
        # -- read-tier routing (replica fan-out trees) ------------------
        # opt-in: topology's read_endpoints table names announced
        # replicas; idempotent reads prefer the deepest one, stamped
        # min_rv=applied_hwm() so read-your-writes holds, with typed/
        # unreachable fallback to the primary
        self.read_from_replicas = bool(read_from_replicas)
        self._read_endpoints: List[dict] = []
        self._read_client: Optional["RemoteClusterStore"] = None
        self._read_cooldown = 0.0
        self.read_tier_reads = 0      # reads served by the read tier
        self.read_tier_fallbacks = 0  # reads that fell back primary-side
        # rv high-water mark across this client's OWN acked mutations
        # ({shard: rv}; "0" for an unsharded primary) — the min_rv bound
        # a read-your-writes read against a replica must demand
        self._applied_hwm: Dict[str, int] = {}
        self._applied_hwm_mapform = False
        self._watch_threads: List[threading.Thread] = []
        self._watch_socks: List[socket.socket] = []
        self._closed = False
        self._stop_event = threading.Event()  # wakes backoff sleeps
        # -- overload protection (resilience/overload.py) ---------------
        # every request carries additive prio/client headers (and, with
        # op_deadline_ms set, a deadline_ms header the server enforces);
        # old servers ignore unknown fields, so interop is unchanged.
        # ``lane`` is this client's default classification — strong
        # classifications (fenced => system, leases => system, bulk
        # waves => bulk) always win over it.
        self.lane = lane
        self.op_deadline_ms = float(op_deadline_ms or 0.0)
        self.retry_budget = retry_budget if retry_budget is not None \
            else RetryBudget()
        import uuid
        self.client_id = uuid.uuid4().hex[:12]  # flow-fairness identity
        self.overload_retries = 0      # Overloaded responses retried
        self.overload_sheds_seen = 0   # OverloadedError surfaced typed
        # -- delta watch (client/codec.py delta dialect) ----------------
        # opt-in: ask every watch stream for column-patch frames and
        # apply them straight onto the mirrored objects; any frame the
        # dialect can't express — or any consistency break — falls back
        # typed to the object path (fail-safe default: off)
        self.delta_watch = bool(delta_watch)
        self.delta_vocab_max = DELTA_VOCAB_MAX
        #: cumulative across this client's streams, read by
        #: _export_pipeline_metrics and profile_steady: wire frames on
        #: delta streams, patch events applied, fields written, wire
        #: bytes by mode, decode-vs-apply ms split, peak table size,
        #: and typed fallback counts by reason
        self.delta_stats: Dict[str, Any] = {
            "frames": 0, "events": 0, "fields": 0,
            "bytes_delta": 0, "bytes_object": 0,
            "decode_ms": 0.0, "apply_ms": 0.0,
            "vocab": 0, "fallbacks": {}}

    # -- plumbing -----------------------------------------------------------

    def _connect(self, endpoint: Optional[tuple] = None) -> socket.socket:
        host, port = endpoint or self._default_ep
        sock = socket.create_connection((host, port),
                                        timeout=self.connect_timeout)
        if self._ssl_ctx is not None:
            sock = self._ssl_ctx.wrap_socket(
                sock, server_hostname=host)
        sock.settimeout(None)
        sock.sendall(MAGIC)
        if self.token:
            send_frame(sock, {"op": "auth", "token": self.token})
            resp = recv_frame(sock)
            if not resp.get("ok"):
                sock.close()
                raise_remote(resp)
        return sock

    def _pool(self, ep: tuple) -> dict:
        # caller holds self._pool_cv
        pool = self._pools.get(ep)
        if pool is None:
            pool = self._pools[ep] = {"idle": [], "n": 0}
        return pool

    def _acquire_conn(self, ep: tuple) -> Optional[socket.socket]:
        """Check a request connection out of the endpoint's pool: an
        idle socket, or None with a slot reserved (the caller connects
        outside the pool lock). Blocks while pool_size requests are in
        flight TO THAT ENDPOINT — direct shard traffic never queues
        behind the router's sockets."""
        with self._pool_cv:
            while True:
                if self._closed:
                    raise ConnectionError("store client closed")
                pool = self._pool(ep)
                if pool["idle"]:
                    return pool["idle"].pop()
                if pool["n"] < self.pool_size:
                    pool["n"] += 1
                    return None
                self._pool_cv.wait(0.1)

    def _release_slot(self, ep: tuple) -> None:
        with self._pool_cv:
            self._pool(ep)["n"] -= 1
            self._pool_cv.notify()

    def _drop_conn(self, sock: socket.socket) -> None:
        """A connection died mid-request: close it, keep the slot (the
        retry loop reconnects into it)."""
        with self._pool_cv:
            self._conns.discard(sock)
        try:
            sock.close()
        except OSError:
            pass

    def _checkin_conn(self, ep: tuple, sock: socket.socket) -> None:
        with self._pool_cv:
            if self._closed:
                self._conns.discard(sock)
                self._pool(ep)["n"] -= 1
            else:
                self._pool(ep)["idle"].append(sock)
            self._pool_cv.notify()
        if self._closed:
            try:
                sock.close()
            except OSError:
                pass

    # -- direct shard routing ------------------------------------------------

    def _ensure_topology(self) -> None:
        """Fetch the server's shard topology ONCE (lazily): when it
        names per-shard worker endpoints, single-key ops route straight
        to the owning shard. Servers without the op (pre-topology), ok
        responses without endpoints (single process — the in-process
        router, a plain store, a replica), and TLS sessions (workers
        speak loopback plaintext) all leave router-only routing in
        place."""
        if self._topo_checked:
            return
        with self._topo_lock:
            if self._topo_checked:
                return
            eps: List[tuple] = []
            raw: List[str] = []
            n = 1
            if (self.direct_routing or self.read_from_replicas) \
                    and self._ssl_ctx is None:
                try:
                    resp = self._request({"op": "topology"})
                    n = int(resp.get("n_shards", 1))
                    raw = resp.get("endpoints") or []
                    with self._lock:
                        self._read_endpoints = \
                            resp.get("read_endpoints") or []
                    if self.direct_routing and n > 1 and len(raw) == n:
                        for addr in raw:
                            host, _, port = addr.rpartition(":")
                            eps.append((host or "127.0.0.1", int(port)))
                except Exception:  # noqa: BLE001 — old server: no topology
                    eps = []
            if eps:
                self._n_shards = n
                self._shard_endpoints = eps
                log.info("store topology: %d shards, direct routing to "
                         "%s", n, raw)
            self._topo_checked = True

    def _endpoint_for(self, kind: str, key: str) -> Optional[tuple]:
        self._ensure_topology()
        if not self._shard_endpoints:
            return None
        return self._shard_endpoints[
            shard_for(kind, key, self._n_shards)]

    def _routed_request(self, kind: str, key: str, payload: dict) -> dict:
        """A single-key op: straight to the owning shard worker when the
        topology names one, with graceful fallback to the router when
        the direct attempt fails without possibly having been applied
        (a send that completed on a non-idempotent, non-conditional op
        must NOT be blindly replayed through the router)."""
        ep = self._endpoint_for(kind, key)
        if ep is None:
            return self._request(payload)
        try:
            resp = self._request(payload, endpoint=ep)
        except (ConnectionError, OSError) as e:
            if getattr(e, "_sent_unsafe", False):
                raise
            self.direct_fallbacks += 1
            log.warning("direct shard request to %s failed (%s: %s); "
                        "falling back to the router", ep,
                        type(e).__name__, e)
            return self._request(payload)
        self.direct_requests += 1
        return resp

    def _classify(self, payload: dict) -> str:
        """Lane for one request: the strong classifications (fenced
        write / lease traffic => system, bulk wave => bulk) win; then
        any ambient LaneStore hint or this client's default lane; then
        op shape (see resilience/overload.classify)."""
        return classify(payload.get("op"), kind=payload.get("kind"),
                        fencing=payload.get("fencing"),
                        prio=payload.get("prio") or current_lane()
                        or self.lane)

    def _request(self, payload: dict,
                 endpoint: Optional[tuple] = None) -> dict:
        """One request with the full client-side overload discipline on
        top of the transport layer (_request_once): stamp the additive
        ``prio``/``client`` headers (and ``deadline_ms`` when a per-op
        budget is configured), and on a typed Overloaded shed HONOR the
        server's retry-after hint — but cap retries with the global
        retry budget (~10% of recent request volume) so a shedding
        server never faces a retry storm that amplifies the outage.
        ``system``-lane ops (lease renewal, fenced writes) bypass the
        budget: giving up on the lease IS the outage."""
        lane = self._classify(payload)
        payload.setdefault("prio", lane)
        payload.setdefault("client", self.client_id)
        budget_ms = self.op_deadline_ms
        t0 = time.monotonic() if budget_ms else 0.0
        delay = self.retry_base_s
        attempt = 0
        while True:
            if budget_ms:
                left = budget_ms - (time.monotonic() - t0) * 1e3
                if left <= 0:
                    raise OverloadedError(
                        f"op {payload.get('op')!r} deadline "
                        f"({budget_ms:.0f}ms) exhausted client-side "
                        "across retries", lane=lane, reason="deadline")
                payload["deadline_ms"] = round(left, 1)
            self.retry_budget.on_request()
            resp = self._request_once(payload, endpoint)
            if resp.get("ok") is False \
                    and resp.get("error") == "OverloadedError":
                err = remote_error(resp)
                attempt += 1
                with self._lock:
                    self.overload_sheds_seen += 1
                if attempt > self.retry_attempts or self._closed:
                    raise err
                if lane != "system" and not self.retry_budget.try_spend():
                    raise RetryBudgetExhausted(
                        f"retry budget exhausted after a shed "
                        f"(lane {err.lane!r}, reason {err.reason!r}): "
                        f"{err}", retry_after_ms=err.retry_after_ms,
                        lane=err.lane, reason="retry_budget")
                with self._lock:
                    self.overload_retries += 1
                wait = delay
                if err.retry_after_ms:
                    # the server's hint is the floor: it knows how long
                    # its queues need to drain better than our backoff
                    wait = max(wait, float(err.retry_after_ms) / 1000.0)
                self._stop_event.wait(wait * (0.5 + random.random()))
                delay = min(delay * 2.0, self.retry_cap_s)
                continue
            if not resp.get("ok"):
                raise_remote(resp)
            if payload.get("op") in _MUTATING_WIRE_OPS:
                self._note_applied(resp.get("applied_rv"))
            return resp

    def _note_applied(self, applied) -> None:
        """Fold a mutation response's applied_rv stamp into this
        client's high-water mark (see applied_hwm)."""
        if applied is None:
            return
        with self._lock:
            if isinstance(applied, dict):
                self._applied_hwm_mapform = True
                for sh, rv in applied.items():
                    if int(rv) > self._applied_hwm.get(str(sh), 0):
                        self._applied_hwm[str(sh)] = int(rv)
            elif int(applied) > self._applied_hwm.get("0", 0):
                self._applied_hwm["0"] = int(applied)

    def applied_hwm(self):
        """The rv high-water mark across this client's own acked
        mutations: the ``min_rv`` a read-your-writes read against a
        replica must demand. Scalar against an unsharded primary,
        ``{shard: rv}`` once any stamp arrived in map form; None before
        the first stamped mutation."""
        with self._lock:
            if not self._applied_hwm:
                return None
            if not self._applied_hwm_mapform:
                return self._applied_hwm.get("0")
            return dict(self._applied_hwm)

    # -- read-tier routing ---------------------------------------------------

    def _read_tier_client(self) -> Optional["RemoteClusterStore"]:
        """The nested client for the preferred (deepest announced)
        read-tier endpoint, built lazily from topology; None when the
        tier is disabled, undiscovered, or cooling down after a
        failure."""
        if not self.read_from_replicas:
            return None
        self._ensure_topology()
        with self._lock:
            if self._read_client is not None:
                return self._read_client
            if not self._read_endpoints \
                    or time.monotonic() < self._read_cooldown:
                return None
            ep = max(self._read_endpoints,
                     key=lambda e: int(e.get("depth", 1)))
            self._read_client = RemoteClusterStore(
                str(ep["endpoint"]), token=self.token,
                connect_timeout=self.connect_timeout,
                direct_routing=False, retry_attempts=1,
                retry_budget=self.retry_budget)
            return self._read_client

    def _read_request(self, payload: dict, fallback=None) -> dict:
        """Route one idempotent read to the read tier, demanding this
        client's own applied hwm via ``min_rv`` (read-your-writes
        holds even though the answer comes from a replica). Falls back
        to the primary on ReplicaLagError or an unreachable replica;
        other typed errors (NotFoundError, ...) are real answers and
        propagate."""
        from .store import ReplicaLagError
        fb = fallback if fallback is not None \
            else (lambda: self._request(payload))
        client = self._read_tier_client()
        if client is None:
            return fb()
        p = dict(payload)
        if p.get("min_rv") is None:
            hwm = self.applied_hwm()
            if hwm is not None:
                p["min_rv"] = hwm
        try:
            resp = client._request(p)
        except (ReplicaLagError, ConnectionError, OSError) as e:
            with self._lock:
                self.read_tier_fallbacks += 1
                if not isinstance(e, ReplicaLagError):
                    # unreachable (a lagging replica is still alive):
                    # drop the client, cool down, rediscover later
                    dead, self._read_client = self._read_client, None
                    self._read_cooldown = time.monotonic() + 5.0
                else:
                    dead = None
            if dead is not None:
                dead.close()
            log.warning("read-tier request failed (%s: %s); falling "
                        "back to the primary", type(e).__name__, e)
            return fb()
        with self._lock:
            self.read_tier_reads += 1
        return resp

    def _request_once(self, payload: dict,
                      endpoint: Optional[tuple] = None) -> dict:
        # Retry rules: a failed SEND is always safe to retry (the server
        # only acts on complete frames, and a broken connection can never
        # complete a partial one). A failure AFTER the send is ambiguous —
        # the server may have applied the op. Idempotent reads always
        # retry there. A mutating op retries only when it is CONDITIONAL:
        # create/delete land at most once (a replay of an applied-but-
        # unacked attempt surfaces ConflictError/NotFoundError instead of
        # double-applying), and update/apply carrying a nonzero
        # resource_version re-present the same precondition, so the
        # replay of an applied bind surfaces ConflictError. Unconditional
        # mutations (version-0 update/apply) surface the transport error
        # to their caller rather than risk blind double-apply. Retries
        # back off exponentially with jitter (base -> cap), so a
        # briefly-restarting server (a 2-second systemd bounce) is ridden
        # out — and a thundering herd of reconnecting clients spreads
        # instead of synchronizing. Connections come from a pool of
        # pool_size (default 1 — the historical one-socket serialization).
        op = payload.get("op")
        idempotent = op in ("get", "list", "ping", "store_info",
                            "bootstrap", "topology", "fence_check",
                            "replica_info", "admission_info",
                            "announce_read_endpoint")
        conditional = op in ("create", "delete") or (
            op in ("update", "apply")
            and bool(((payload.get("obj") or {}).get("f") or {})
                     .get("resource_version")))
        ep = endpoint or self._default_ep
        delay = self.retry_base_s
        attempt = 0
        sock = self._acquire_conn(ep)
        try:
            while True:
                sent = False
                try:
                    faults.fire("store_request")
                    if sock is None:
                        sock = self._connect(ep)
                        with self._pool_cv:
                            self._conns.add(sock)
                    send_frame(sock, payload)
                    sent = True
                    resp = recv_frame(sock)
                    break
                except (ConnectionError, OSError) as e:
                    if sock is not None:
                        self._drop_conn(sock)
                        sock = None
                    attempt += 1
                    if (sent and not (idempotent or conditional)) \
                            or attempt > self.retry_attempts \
                            or self._closed:
                        # the direct-routing fallback must know whether
                        # this op may already have been APPLIED — only a
                        # failure after a completed send on a
                        # non-retryable op is unsafe to re-run elsewhere
                        e._sent_unsafe = bool(  # type: ignore[attr-defined]
                            sent and not (idempotent or conditional))
                        raise
                    try:
                        from ..metrics import metrics
                        metrics.store_request_retries_total.inc()
                    except Exception:  # noqa: BLE001
                        pass
                    self._stop_event.wait(delay * (0.5 + random.random()))
                    delay = min(delay * 2.0, self.retry_cap_s)
        except BaseException:
            if sock is not None:
                self._drop_conn(sock)
            self._release_slot(ep)
            raise
        self._checkin_conn(ep, sock)
        return resp

    def close(self) -> None:
        self._closed = True
        self._stop_event.set()  # wake any backoff sleep immediately
        with self._lock:
            rc, self._read_client = self._read_client, None
        if rc is not None:
            rc.close()
        with self._pool_cv:
            conns = list(self._conns)
            self._conns.clear()
            for pool in self._pools.values():
                pool["idle"].clear()
            self._pool_cv.notify_all()
        for sock in conns:
            try:
                sock.close()
            except OSError:
                pass
        for sock in self._watch_socks:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._watch_socks = []

    # -- ClusterStore surface ----------------------------------------------

    def locked(self):
        return self._lock

    def create(self, kind: str, obj, fencing: Optional[dict] = None):
        return decode(self._routed_request(
            kind, _key(obj),
            {"op": "create", "kind": kind, "obj": encode(obj),
             "fencing": fencing})["obj"])

    def update(self, kind: str, obj, fencing: Optional[dict] = None):
        return decode(self._routed_request(
            kind, _key(obj),
            {"op": "update", "kind": kind, "obj": encode(obj),
             "fencing": fencing})["obj"])

    def apply(self, kind: str, obj, fencing: Optional[dict] = None):
        return decode(self._routed_request(
            kind, _key(obj),
            {"op": "apply", "kind": kind, "obj": encode(obj),
             "fencing": fencing})["obj"])

    def delete(self, kind: str, name: str, namespace: Optional[str] = None,
               fencing: Optional[dict] = None):
        key = f"{namespace}/{name}" if namespace is not None else name
        return decode(self._routed_request(
            kind, key,
            {"op": "delete", "kind": kind, "name": name,
             "namespace": namespace, "fencing": fencing})["obj"])

    def bulk_apply(self, items, fencing: Optional[dict] = None,
                   chunk_bytes: int = BULK_CHUNK_BYTES,
                   chunk_items: int = BULK_CHUNK_ITEMS,
                   ack: bool = False) -> List[Any]:
        """Batch mutation (the ROADMAP item-3 bulk ingest op): same
        contract as ClusterStore.bulk_apply — items are (kind, obj[,
        verb]) and the result list carries the applied object or the
        rebuilt exception instance per position. An oversized wave is
        CHUNKED: frames are bounded at chunk_bytes/chunk_items each,
        every chunk commits as one journal batch server-side, and the
        per-chunk results reassemble in submission order — a 50k-pod
        wave costs a handful of bounded frames, never one giant one.
        Not retried after an unacked send (a bulk wave is not
        conditional as a unit); a failed SEND retries like every other
        op, per chunk.

        ``ack=True`` is ingest-wave mode: successful positions come
        back as None instead of the applied objects (errors still
        arrive as exception instances at their positions) — the server
        skips encoding 10k result objects and this client skips
        decoding them, roughly halving the wire cost of a pure-ingest
        wave."""
        encoded = []
        for it in items:
            d = {"kind": it[0], "obj": encode(it[1]),
                 "verb": it[2] if len(it) > 2 else "apply"}
            # sizing costs one extra dumps per item; the request frame
            # re-serializes anyway, and bounded frames are what keep a
            # mega-wave from stalling every other request on the server
            encoded.append((d, len(json.dumps(d, separators=(",", ":")))))
        results: List[Any] = []
        i = 0
        while i < len(encoded):
            size = 0
            j = i
            while j < len(encoded) and (
                    j == i or (j - i < chunk_items
                               and size + encoded[j][1] <= chunk_bytes)):
                size += encoded[j][1]
                j += 1
            payload = {"op": "bulk_apply",
                       "items": [d for d, _ in encoded[i:j]],
                       "fencing": fencing}
            if ack:
                payload["ack"] = True
            resp = self._request(payload)
            if ack:
                chunk: List[Any] = [None] * int(resp["n"])
                for idx, err in (resp.get("errors") or {}).items():
                    chunk[int(idx)] = remote_error(err)
                results.extend(chunk)
            else:
                results.extend(
                    remote_error(r) if "error" in r else decode(r["obj"])
                    for r in resp["results"])
            i = j
        return results

    def get(self, kind: str, name: str, namespace: Optional[str] = None,
            min_rv=None, wait_s: Optional[float] = None):
        key = f"{namespace}/{name}" if namespace is not None else name
        payload = {"op": "get", "kind": kind, "name": name,
                   "namespace": namespace}
        if min_rv is not None:
            payload["min_rv"] = min_rv
            if wait_s is not None:
                payload["wait_s"] = wait_s
        if self.read_from_replicas:
            return decode(self._read_request(
                payload,
                lambda: self._routed_request(kind, key, payload))["obj"])
        return decode(self._routed_request(kind, key, payload)["obj"])

    def try_get(self, kind: str, name: str, namespace: Optional[str] = None):
        from .store import NotFoundError
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None,
             name_glob: Optional[str] = None, min_rv=None,
             wait_s: Optional[float] = None) -> List[Any]:
        return self.list_versioned(kind, namespace, label_selector,
                                   name_glob, min_rv=min_rv,
                                   wait_s=wait_s)[0]

    def list_versioned(self, kind: str, namespace: Optional[str] = None,
                       label_selector: Optional[Dict[str, str]] = None,
                       name_glob: Optional[str] = None, min_rv=None,
                       wait_s: Optional[float] = None):
        """``list`` with its staleness made explicit: returns
        ``(objects, applied_rv)`` where ``applied_rv`` is the exact
        store version the response reflects (scalar, or ``{shard: rv}``
        against a sharded endpoint; None from a pre-applied_rv server).

        ``min_rv=`` is the rv-bounded read against a replica: the
        replica blocks until it has applied that rv or fails typed with
        ReplicaLagError after ``wait_s`` (the primary satisfies any rv
        it ever minted, trivially).

        Closing the retried-list hole: list is retried as idempotent,
        so a retry after an unacked response can land on a view that
        DISAGREES with what this client's own watch streams already
        delivered — most sharply, a view BEHIND the stream's rv
        high-water mark (a restarted primary that recovered short of
        its unfsynced tail, or a replica that just re-bootstrapped from
        an older snapshot). Acting on that response would regress a
        mirror the way a blind write replay used to double-apply, so a
        response behind the stream hwm is DISCARDED and re-requested;
        if the server stays behind, ReplicaLagError surfaces instead of
        stale data. (For the other direction — a list AHEAD of the
        stream — see wait_stream_applied.)"""
        from .store import ReplicaLagError
        payload = {"op": "list", "kind": kind, "namespace": namespace,
                   "label_selector": label_selector,
                   "name_glob": name_glob}
        if min_rv is not None:
            payload["min_rv"] = min_rv
            if wait_s is not None:
                payload["wait_s"] = wait_s
        applied = None
        resp = None
        for attempt in range(self.retry_attempts + 1):
            resp = (self._read_request(payload)
                    if self.read_from_replicas else self._request(payload))
            applied = resp.get("applied_rv")
            if not self._behind_stream(kind, applied):
                break
            if attempt >= self.retry_attempts:
                raise ReplicaLagError(
                    f"list({kind!r}) response at applied_rv {applied} is "
                    f"behind this client's watch high-water mark "
                    f"{self._kind_hwm.get(kind)}; refusing to serve a "
                    "view older than the stream already delivered")
            self._stop_event.wait(0.05 * (attempt + 1))
        with self._lock:
            self.last_list_applied_rv = applied
        return [decode(o) for o in resp["objs"]], applied

    def _behind_stream(self, kind: str, applied) -> bool:
        """True when a list response's applied_rv predates an event this
        client's watch streams already delivered for ``kind``."""
        if applied is None:
            return False
        with self._lock:
            hk = self._kind_hwm.get(kind)
            if not hk:
                return False
            if isinstance(applied, dict):
                return any(int(applied.get(sh, -1)) < rv
                           for sh, rv in hk.items())
            return int(applied) < hk.get("0", -1)

    def _stream_covers(self, kind: str, applied) -> bool:
        # caller holds self._lock
        hk = self._kind_hwm.get(kind, {})
        if isinstance(applied, dict):
            return all(hk.get(str(sh), -1) >= int(rv)
                       for sh, rv in applied.items())
        return hk.get("0", -1) >= int(applied)

    def wait_stream_applied(self, kind: str, applied_rv,
                            timeout: float = 5.0) -> bool:
        """Block until this client's watch stream(s) for ``kind`` have
        delivered events up to ``applied_rv`` (a list response's stamp)
        — the complement of the stale-list discard: a list AHEAD of the
        stream must not drive a mirror until the stream has caught up,
        or events older than the list would regress it. Returns False on
        timeout (e.g. no stream is watching the kind)."""
        if applied_rv is None:
            return True
        deadline = time.monotonic() + timeout
        with self._hwm_cv:
            while not self._stream_covers(kind, applied_rv):
                left = deadline - time.monotonic()
                if left <= 0 or self._closed:
                    return False
                self._hwm_cv.wait(min(left, 0.5))
        return True

    def ping(self) -> bool:
        return bool(self._request({"op": "ping"}).get("ok"))

    def admission_info(self) -> dict:
        """The server's per-lane admission table (``admission_info``
        wire op): {lane: {inflight, streams, queued, admitted, sheds,
        shed_reasons, deadline_expired, max_*}}, plus — against a
        multi-process shard router — a ``workers`` map with each
        worker's own table. Old servers raise (unknown op); vcctl
        degrades to no table."""
        return self._request({"op": "admission_info"})

    def add_interceptor(self, fn) -> None:
        raise NotImplementedError(
            "admission interceptors run in the process that OWNS the "
            "store (standalone --serve-store starts the webhook chain "
            "there); a remote client cannot install them")

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str, listener, replay: bool = True) -> None:
        """Subscribe over a dedicated streaming connection. The replay is
        applied inline before returning (list-then-watch, same synchronous
        contract as the in-memory store); live events are then delivered
        from a daemon reader thread under self.locked(). A broken stream
        resumes in place when it can (see class docstring)."""
        self._start_stream({kind: [listener]}, "watch", replay)

    def bulk_watch(self, subscriptions, replay: bool = True) -> None:
        """Subscribe MANY kinds over ONE streaming connection (the
        ``bulk_watch`` wire op): ``subscriptions`` is an ordered iterable
        of ``(kind, listener)`` — a kind may appear more than once, its
        listeners fan out in subscription order. Replays land inline per
        kind, in subscription order, before this returns; live events
        then arrive BATCHED (the server coalesces up to
        WATCH_BATCH_MAX events per frame) and are applied under one
        mirror-lock hold per batch. Resume carries a per-shard
        high-water-mark map per kind ({kind: {shard: rv}}), so a stream
        against the sharded router reconnects without skipping or
        repeating any shard's events."""
        subs: Dict[str, List] = {}
        for kind, listener in subscriptions:
            subs.setdefault(kind, []).append(listener)
        self._start_stream(subs, "bulk_watch", replay)

    def _start_stream(self, subs: Dict[str, List], op: str,
                      replay: bool) -> None:
        endpoints: List[Optional[tuple]] = [None]
        descs = [""]
        if self.direct_watch:
            self._ensure_topology()
            if self._shard_endpoints:
                # one stream PER SHARD WORKER, router bypassed: each
                # worker replays its own objects (their union is the
                # full replay) and each stream resumes against its own
                # worker's journal with that shard's marks
                endpoints = list(self._shard_endpoints)
                descs = [f"@shard{i}" for i in range(len(endpoints))]
        for endpoint, suffix in zip(endpoints, descs):
            self._open_stream(subs, op, replay, endpoint, suffix)

    def _open_stream(self, subs: Dict[str, List], op: str, replay: bool,
                     endpoint: Optional[tuple], suffix: str) -> None:
        sock = self._connect(endpoint)
        # register BEFORE the replay loop: close() must be able to unblock
        # a watch() stuck mid-replay on a stalled server
        self._watch_socks.append(sock)
        kinds = list(subs)
        # bulk_watch is the controller fan-out path (control lane);
        # plain watch setup defaults to this client's lane (read for
        # dashboards/storms) — the gate can then shed a watch storm
        # without touching the control plane's own streams
        prio = "control" if op == "bulk_watch" \
            else (current_lane() or self.lane or "read")
        req = {"op": op, "kinds": kinds, "replay": replay,
               "prio": prio, "client": self.client_id}
        if self.delta_watch:
            req["delta"] = True
        send_frame(sock, req)
        # per-kind, per-shard resume high-water marks; "sharded" flips
        # once any frame carries shard structure, switching the resume
        # request from the legacy scalar form to the per-shard map.
        # The delta keys: "delta_ask" (request the mode on (re)connect —
        # cleared forever by a typed fallback, kept across transport
        # breaks), "delta_on" (this stream's synced frame granted it),
        # "vtab"/"ks" (per-shard interning tables and frame-sequence
        # baselines), "objs" (per-kind key -> live mirrored object, the
        # ledger a patch's dk resolves against)
        state: Dict[str, Any] = {
            "hwm": {}, "sharded": False,
            "delta_ask": self.delta_watch, "delta_on": False,
            "vtab": {}, "ks": {},
            "objs": {} if self.delta_watch else None}
        desc = (kinds[0] if len(kinds) == 1
                else f"bulk({','.join(kinds)})") + suffix
        try:
            try:
                self._apply_stream(sock, subs, state, until_synced=True)
            except DeltaFallbackError:
                # typed delta refusal during the open phase (a synced
                # frame's table the client can't hold or parse): retry
                # once with the ask off — fail-safe object frames. The
                # re-replayed adds land as add-as-update resyncs.
                self._drop_watch_sock(sock)
                sock = self._connect(endpoint)
                self._watch_socks.append(sock)
                req.pop("delta", None)
                send_frame(sock, req)
                state = {"hwm": {}, "sharded": False,
                         "delta_ask": False, "delta_on": False,
                         "vtab": {}, "ks": {}, "objs": None}
                self._apply_stream(sock, subs, state, until_synced=True)
        except Exception:
            # server refused the subscription (e.g. unknown kind) or died
            # mid-replay: surface it to the caller, nothing to resume yet
            self._drop_watch_sock(sock)
            raise

        def reader():
            cur = sock
            while True:
                try:
                    self._apply_stream(cur, subs, state,
                                       until_synced=False)
                except (ConnectionError, OSError, ValueError) as e:
                    self._drop_watch_sock(cur)
                    if self._closed:
                        return
                    cur = self._resume_watch(subs, op, state, desc,
                                             endpoint)
                    if cur is None:
                        # a resume abandoned because close() landed
                        # mid-attempt is a clean shutdown, not a broken
                        # mirror — don't fire the crash-only contract
                        if not self._closed:
                            self._watch_broke(desc, e)
                        return
                    continue
                except Exception as e:  # noqa: BLE001 — a listener blew up
                    # mid-handler: the mirror itself may be inconsistent,
                    # which no stream resume can repair — crash-only
                    log.exception("watch listener for %s failed", desc)
                    self._drop_watch_sock(cur)
                    if not self._closed:
                        self._watch_broke(desc, e)
                    return

        t = threading.Thread(target=reader, daemon=True,
                             name=f"store-watch-{desc}")
        t.start()
        self._watch_threads.append(t)

    def _fold_hwm(self, kind: str, sh: str, rv: int) -> None:
        # caller holds self._lock; the shared cross-stream floor only
        # ever advances (streams may individually resume from behind it)
        hk = self._kind_hwm.setdefault(kind, {})
        if int(rv) > hk.get(str(sh), -1):
            hk[str(sh)] = int(rv)

    @staticmethod
    def _advance_hwm(state: dict, kind: str, val) -> None:
        """Fold a synced-frame rv value — the legacy scalar, or the
        router's per-shard map — into the resume high-water marks."""
        hk = state["hwm"].setdefault(kind, {})
        if isinstance(val, dict):
            state["sharded"] = True
            for sh, rv in val.items():
                if rv is not None:
                    hk[str(sh)] = max(hk.get(str(sh), -1), int(rv))
        elif val is not None:
            hk["0"] = max(hk.get("0", -1), int(val))

    def _apply_stream(self, sock, subs: Dict[str, List], state: dict,
                      until_synced: bool) -> None:
        """Read frames from a watch socket, delivering events under the
        mirror lock and advancing the resume high-water marks atomically
        with each delivery (so a resume never skips or repeats an event).
        Handles per-event frames and the bulk_watch batched form (one
        lock hold per batch). Returns at the 'synced' marker when
        ``until_synced``, else loops until the connection dies."""
        while True:
            msg, nbytes = recv_frame_sized(sock)
            faults.fire("watch_stream")
            if msg.get("ok") is False:
                raise_remote(msg)
            stream = msg.get("stream")
            if stream == "synced":
                rvmap = msg.get("rv") or {}
                with self._lock:
                    for kind in subs:
                        if kind in rvmap:
                            self._advance_hwm(state, kind, rvmap[kind])
                            for sh, rv in state["hwm"][kind].items():
                                self._fold_hwm(kind, sh, rv)
                    if state.get("delta_ask"):
                        self._delta_synced(state, msg)
                    self._hwm_cv.notify_all()
                if until_synced:
                    return
                continue
            if stream == "events":
                batch = msg.get("batch") or []
            elif stream == "event":
                batch = [msg]
            else:
                continue  # heartbeat
            # under self._lock like every delivery: during the cache's
            # sequential subscriptions (nodes, then pods, ...) a LIVE
            # event on an earlier kind's stream must not mutate the
            # mirror concurrently with a later kind's replay — cache
            # handlers rely on the store serializing dispatch
            with self._lock:
                delta_on = state.get("delta_on", False)
                st = self.delta_stats
                # wire accounting for BOTH modes, so a delta client and
                # an object client measure the same thing and the bytes
                # columns compare like-for-like
                st["bytes_delta" if delta_on else "bytes_object"] += nbytes
                if delta_on:
                    st["frames"] += 1
                for ev in batch:
                    kind = ev.get("kind")
                    shard = ev.get("shard")
                    sh = str(shard) if shard is not None else "0"
                    if delta_on:
                        ksv = ev.get("ks")
                        if ksv is not None:
                            # dense per-(kind, shard) frame sequence: a
                            # gap means a frame was lost between server
                            # and here, a repeat means one applied
                            # already — refuse BEFORE touching anything
                            kmap = state["ks"].setdefault(kind, {})
                            if int(ksv) != kmap.get(sh, 0) + 1:
                                self._delta_fallback(state, "delta_gap")
                            kmap[sh] = int(ksv)
                            tb = ev.get("tb")
                            if tb is not None:
                                self._delta_extend_vtab(state, kind,
                                                        sh, tb)
                    if "dk" in ev:
                        if not delta_on:
                            # a patch outside negotiated delta mode can
                            # only be a protocol break
                            self._delta_fallback(state, "schema_skew")
                        self._apply_patch(ev, subs, state, sh)
                    else:
                        fns = subs.get(kind)
                        obj = None
                        if fns:
                            old = ev.get("old")
                            obj = decode(ev["obj"])
                            oldo = decode(old) if old is not None else None
                            for fn in fns:
                                fn(ev["event"], obj, oldo)
                        objs = state.get("objs")
                        if objs is not None and kind is not None:
                            # the delta ledger mirrors live objects by
                            # store key so later patches can resolve dk
                            if obj is None:
                                obj = decode(ev["obj"])
                            km = objs.setdefault(kind, {})
                            if ev.get("event") == "delete":
                                km.pop(object_key(obj), None)
                            else:
                                km[object_key(obj)] = obj
                    rv = ev.get("rv")
                    if rv is not None:
                        if shard is not None:
                            state["sharded"] = True
                        hk = state["hwm"].setdefault(kind, {})
                        hk[sh] = max(hk.get(sh, -1), int(rv))
                        self._fold_hwm(kind, sh, hk[sh])
                self._hwm_cv.notify_all()

    # -- delta watch application (client/codec.py delta dialect) ------------

    def _delta_synced(self, state: dict, msg: dict) -> None:
        """Fold a synced frame's delta grant into the stream state.
        Caller holds self._lock and has checked ``delta_ask``."""
        if not msg.get("delta"):
            # server (or one relay upstream) declined: fail-safe object
            # frames, and stop asking — the answer won't change
            state["delta_on"] = False
            state["delta_ask"] = False
            state["objs"] = None
            return
        try:
            vtab = {k: {str(sh): [decode(e) for e in entries]
                        for sh, entries in m.items()}
                    for k, m in (msg.get("vtab") or {}).items()}
        except Exception:  # noqa: BLE001 — unparseable table entry
            self._delta_fallback(state, "schema_skew")
        for m in vtab.values():
            for entries in m.values():
                if len(entries) > self.delta_vocab_max:
                    self._delta_fallback(state, "vocab_overflow")
        # REPLACE, never merge: each synced is a full snapshot atomic
        # with the (re)subscription it rode in on
        state["vtab"] = vtab
        state["ks"] = {k: {str(sh): int(n) for sh, n in m.items()}
                       for k, m in (msg.get("ks") or {}).items()}
        state["delta_on"] = True
        if state.get("objs") is None:
            state["objs"] = {}
        vocab = max((len(t) for m in vtab.values()
                     for t in m.values()), default=0)
        if vocab > self.delta_stats["vocab"]:
            self.delta_stats["vocab"] = vocab

    def _delta_extend_vtab(self, state: dict, kind: str, sh: str,
                           tb) -> None:
        """Apply a frame's interning-table additions ([start, entries])
        to that kind's table — tables are per (kind, shard) so a stream
        watching a subset of kinds stays id-aligned with the server.
        Caller holds self._lock; ks continuity already passed."""
        table = state["vtab"].setdefault(kind, {}).setdefault(sh, [])
        try:
            t0, entries = tb
        except (TypeError, ValueError):
            self._delta_fallback(state, "schema_skew")
        if t0 != len(table):
            # additions for a table we don't have: the streams' tables
            # are no longer id-aligned
            self._delta_fallback(state, "schema_skew")
        if t0 + len(entries) > self.delta_vocab_max:
            self._delta_fallback(state, "vocab_overflow")
        try:
            table.extend(decode(e) for e in entries)
        except Exception:  # noqa: BLE001 — unparseable entry
            self._delta_fallback(state, "schema_skew")
        if len(table) > self.delta_stats["vocab"]:
            self.delta_stats["vocab"] = len(table)

    def _delta_fallback(self, state: dict, reason: str) -> None:
        """Typed refusal: record it, clear the stream's delta state so
        the resume reconnects plain, and raise. The failed frame applied
        NOTHING and advanced no high-water mark, so the object-path
        resume replay neither loses nor repeats an event. Caller holds
        self._lock."""
        state["delta_on"] = False
        state["delta_ask"] = False
        state["vtab"] = {}
        state["ks"] = {}
        state["objs"] = None
        fb = self.delta_stats["fallbacks"]
        fb[reason] = fb.get(reason, 0) + 1
        try:
            from ..metrics import metrics
            metrics.delta_fallbacks_total.inc(labels={"reason": reason})
        except Exception:  # noqa: BLE001 — accounting only
            pass
        log.warning("delta watch stream falling back to object frames "
                    "(%s)", reason)
        raise DeltaFallbackError(reason)

    def _apply_patch(self, ev: dict, subs: Dict[str, List], state: dict,
                     sh: str) -> None:
        """Apply one column patch onto the mirrored object it names.
        Validate-then-apply: every field resolves (or the whole frame is
        refused typed) before any attribute changes, so a refusal leaves
        the mirror exactly as it was. Caller holds self._lock."""
        t0 = time.perf_counter()
        kind = ev["kind"]
        table = (state["vtab"].get(kind) or {}).get(sh) or ()
        try:
            key = table[ev["dk"]]
        except (IndexError, TypeError):
            self._delta_fallback(state, "schema_skew")
        obj = (state["objs"].get(kind) or {}).get(key)
        if obj is None:
            # a patch for a key whose add this stream never applied:
            # continuity is broken even though ks looked dense
            self._delta_fallback(state, "delta_gap")
        cls = type(obj)
        known = known_fields(cls)
        sets = []
        try:
            for fid, wv in zip(ev.get("df") or (), ev.get("dv") or ()):
                fname = table[fid]
                if fname not in known:
                    self._delta_fallback(state, "unknown_field")
                sets.append((fname, delta_resolve(wv, table)))
            for fid in ev.get("dx") or ():
                fname = table[fid]
                if fname not in known:
                    self._delta_fallback(state, "unknown_field")
                sets.append((fname, field_default(cls, fname)))
        except DeltaFallbackError:
            raise
        except IndexError:
            self._delta_fallback(state, "schema_skew")
        except (ValueError, TypeError):
            # undecodable value, or clearing a field with no default
            self._delta_fallback(state, "schema_skew")
        t1 = time.perf_counter()
        # a shallow copy is a faithful ``old``: patches REPLACE field
        # values, never mutate containers in place, so the copy keeps
        # every pre-patch reference while the live object moves on
        old = copy.copy(obj)
        for fname, val in sets:
            setattr(obj, fname, val)
        changed = [fname for fname, _ in sets]
        for fn in subs.get(kind) or ():
            if getattr(fn, "delta_aware", False):
                # delta-aware consumers (SchedulerCache._on_pod) take
                # the changed-field names and skip the full rebuild
                fn("update", obj, old, changed)
            else:
                fn("update", obj, old)
        t2 = time.perf_counter()
        st = self.delta_stats
        st["events"] += 1
        st["fields"] += len(sets)
        st["decode_ms"] += (t1 - t0) * 1000.0
        st["apply_ms"] += (t2 - t1) * 1000.0

    def _resume_watch(self, subs: Dict[str, List], op: str, state: dict,
                      desc: str, endpoint: Optional[tuple] = None):
        """Reconnect a broken watch stream with exponential backoff +
        jitter and ask the server to replay from our high-water marks.
        Returns the new streaming socket (mirror already resynced), or
        None when resume is impossible — unknown high-water mark, resume
        window lost server-side (ResumeGapError), or the server stayed
        unreachable past ``watch_resume_window_s`` — in which case the
        caller falls back to the crash-only contract. A direct per-shard
        stream resumes against its own worker ``endpoint`` (the
        supervisor restarts a dead worker on the same port, well inside
        the resume window); ShardUnavailableError from a router mid-
        worker-restart keeps backing off the same way."""
        with self._lock:
            if not self.watch_resume or any(
                    not state["hwm"].get(k) for k in subs):
                return None
        deadline = time.monotonic() + self.watch_resume_window_s
        delay = 0.05
        attempt = 0
        while not self._closed:
            attempt += 1
            sock = None
            with self._lock:
                since = ({k: dict(m) for k, m in state["hwm"].items()}
                         if state["sharded"] else
                         {k: m.get("0", -1)
                          for k, m in state["hwm"].items()})
            try:
                sock = self._connect(endpoint)
                self._watch_socks.append(sock)
                # resume is CONTROL-lane regardless of the stream's
                # original lane: keeping an already-established mirror
                # consistent outranks admitting new read traffic
                rreq = {"op": op, "kinds": list(subs),
                        "replay": False, "since": since,
                        "prio": "control", "client": self.client_id}
                if state.get("delta_ask"):
                    # transport breaks keep the delta ask (the journal
                    # replay arrives object-form either way; the fresh
                    # synced re-baselines vtab/ks); typed fallbacks
                    # cleared the ask and resume plain
                    rreq["delta"] = True
                send_frame(sock, rreq)
                # the missed-event replay lands here, inline
                self._apply_stream(sock, subs, state, until_synced=True)
            except ResumeGapError as e:
                self._drop_watch_sock(sock)
                log.error("watch stream for %r cannot resume: %s", desc, e)
                return None
            except (ConnectionError, OSError, ValueError,
                    ShardUnavailableError):
                # ShardUnavailableError: the router refused because the
                # owning worker is down — transient exactly like an
                # unreachable server; the supervisor is restarting it
                self._drop_watch_sock(sock)
                if time.monotonic() >= deadline:
                    return None
                self._stop_event.wait(delay * (0.5 + random.random()))
                delay = min(delay * 2.0, self.watch_backoff_cap_s)
                continue
            with self._lock:
                self.watch_resumes += 1
            try:
                from ..metrics import metrics
                metrics.watch_reconnects_total.inc(labels={"kind": desc})
            except Exception:  # noqa: BLE001
                pass
            log.warning("watch stream for %r resumed from %s "
                        "(attempt %d)", desc, since, attempt)
            return sock
        return None

    def _drop_watch_sock(self, sock) -> None:
        if sock is None:
            return
        try:
            self._watch_socks.remove(sock)
        except ValueError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _watch_broke(self, kind: str, exc: Exception) -> None:
        """A watch stream died beyond repair: the local mirror is
        permanently stale (resume was either disabled, out of window, or
        the listener itself corrupted mid-delivery)."""
        with self._lock:  # streams die together when the server goes:
            first = not self.watch_failed  # fire the callback exactly once
            self.watch_failed = True
        log.critical(
            "watch stream for %r broke (%s: %s) and could not resume; "
            "this store's mirror is frozen — restart the consumer "
            "process to resync", kind, type(exc).__name__, exc)
        if first and self.on_watch_failure is not None:
            try:
                self.on_watch_failure()
            except Exception:  # noqa: BLE001 — never kill the reader hook
                log.exception("on_watch_failure callback failed")
