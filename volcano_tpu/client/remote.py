"""RemoteClusterStore: the ClusterStore surface over a StoreServer socket.

Gives every store consumer — vcctl, SchedulerCache, controllers, leader
election — the same interface against a deployed control plane that the
in-memory ClusterStore gives them in-process (the reference's client-go
clientset + informer factory against the API server,
pkg/scheduler/cache/cache.go:319-402). CRUD is synchronous request/
response on one mutex-guarded connection; each watch() opens its own
streaming connection, applies the replay inline (list-then-watch: the
caller returns with state loaded, exactly like the in-memory store), then
keeps delivering live events from a reader thread. All listener dispatch
happens under self.locked(), so a consumer holding the lock (the
scheduler cache's snapshot) sees a frozen mirror.

Optimistic concurrency travels the wire: the server compares
resource_version on update and ConflictError/NotFoundError/AdmissionError
re-raise client-side as the same classes — which is what makes the lease
CAS of utils.leader_election work across processes.
"""

from __future__ import annotations

import logging
import os
import random
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ..resilience.faultinject import faults
from .codec import decode, encode
from .server import MAGIC, raise_remote, recv_frame, remote_error, send_frame
from .store import ResumeGapError

log = logging.getLogger(__name__)


class RemoteClusterStore:
    """See module docstring. Deployment-facing knobs:

    - ``token``: shared-secret auth presented on every connection
      (defaults to $VOLCANO_STORE_TOKEN so vcctl and operator scripts
      pick it up without plumbing).
    - ``on_watch_failure``: called once when a watch stream dies beyond
      repair. A broken stream first tries to RESUME in place: reconnect
      with exponential backoff + jitter and ask the server to replay from
      this client's per-kind resource_version high-water mark (the
      server's EventJournal — client-go's reflector re-watch). Only when
      that fails — server gone past ``watch_resume_window_s``, journal
      window lost (ResumeGapError), or a listener itself blew up — does
      the crash-only contract fire: log CRITICAL, set ``watch_failed``,
      call the callback once so a supervisor can restart with a fresh
      snapshot (HA standbys cover the gap).
    - ``retry_attempts``/``retry_base_s``/``retry_cap_s``: idempotent-op
      retry budget (see _request) — defaults ride out a ~3 s server
      restart.
    """

    def __init__(self, address: str, connect_timeout: float = 5.0,
                 token: Optional[str] = None,
                 on_watch_failure: Optional[Callable[[], None]] = None,
                 tls_ca: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 retry_attempts: int = 5,
                 retry_base_s: float = 0.1,
                 retry_cap_s: float = 2.0,
                 watch_resume: bool = True,
                 watch_resume_window_s: float = 30.0,
                 watch_backoff_cap_s: float = 2.0):
        host, _, port = address.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self.connect_timeout = connect_timeout
        self.token = token if token is not None \
            else os.environ.get("VOLCANO_STORE_TOKEN", "")
        # TLS to a StoreServer serving it (see its docstring): tls_ca is
        # the CA bundle the SERVER cert must verify against (also
        # $VOLCANO_STORE_CA); tls_cert/tls_key present a client
        # certificate for mTLS servers
        self.tls_ca = tls_ca if tls_ca is not None \
            else os.environ.get("VOLCANO_STORE_CA") or None
        self.tls_cert = tls_cert
        self.tls_key = tls_key
        self._ssl_ctx = None
        if self.tls_ca or self.tls_cert:
            import ssl

            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            ctx.verify_mode = ssl.CERT_REQUIRED
            if self.tls_ca:
                # CA-pinned: the operator named the exact CA this server
                # must chain to, and cluster-internal addresses are
                # usually bare IPs — hostname matching adds nothing the
                # pin doesn't already guarantee
                ctx.check_hostname = False
                ctx.load_verify_locations(self.tls_ca)
            else:
                # client-cert-only config: falls back to the SYSTEM trust
                # store, where hostname verification is the only thing
                # stopping any public-CA cert for any host from
                # impersonating the store — keep it on (default True)
                ctx.load_default_certs()
            if self.tls_cert:
                ctx.load_cert_chain(self.tls_cert, self.tls_key)
            self._ssl_ctx = ctx
        self.on_watch_failure = on_watch_failure
        self.watch_failed = False
        self.retry_attempts = retry_attempts
        self.retry_base_s = retry_base_s
        self.retry_cap_s = retry_cap_s
        self.watch_resume = watch_resume
        self.watch_resume_window_s = watch_resume_window_s
        self.watch_backoff_cap_s = watch_backoff_cap_s
        self.watch_resumes = 0   # successful in-place stream resumes
        self._lock = threading.RLock()   # local mirror/listener lock
        self._conn_lock = threading.Lock()  # serializes request/response
        self._conn: Optional[socket.socket] = None
        self._watch_threads: List[threading.Thread] = []
        self._watch_socks: List[socket.socket] = []
        self._closed = False
        self._stop_event = threading.Event()  # wakes backoff sleeps

    # -- plumbing -----------------------------------------------------------

    def _connect(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.connect_timeout)
        if self._ssl_ctx is not None:
            sock = self._ssl_ctx.wrap_socket(
                sock, server_hostname=self.host)
        sock.settimeout(None)
        sock.sendall(MAGIC)
        if self.token:
            send_frame(sock, {"op": "auth", "token": self.token})
            resp = recv_frame(sock)
            if not resp.get("ok"):
                sock.close()
                raise_remote(resp)
        return sock

    def _request(self, payload: dict) -> dict:
        # Retry rules: a failed SEND is always safe to retry (the server
        # only acts on complete frames, and a broken connection can never
        # complete a partial one). A failure AFTER the send is ambiguous —
        # the server may have applied the op. Idempotent reads always
        # retry there. A mutating op retries only when it is CONDITIONAL:
        # create/delete land at most once (a replay of an applied-but-
        # unacked attempt surfaces ConflictError/NotFoundError instead of
        # double-applying), and update/apply carrying a nonzero
        # resource_version re-present the same precondition, so the
        # replay of an applied bind surfaces ConflictError. Unconditional
        # mutations (version-0 update/apply) surface the transport error
        # to their caller rather than risk blind double-apply. Retries
        # back off exponentially with jitter (base -> cap), so a
        # briefly-restarting server (a 2-second systemd bounce) is ridden
        # out — and a thundering herd of reconnecting clients spreads
        # instead of synchronizing.
        op = payload.get("op")
        idempotent = op in ("get", "list", "ping")
        conditional = op in ("create", "delete") or (
            op in ("update", "apply")
            and bool(((payload.get("obj") or {}).get("f") or {})
                     .get("resource_version")))
        delay = self.retry_base_s
        attempt = 0
        with self._conn_lock:
            while True:
                sent = False
                try:
                    faults.fire("store_request")
                    if self._conn is None:
                        self._conn = self._connect()
                    send_frame(self._conn, payload)
                    sent = True
                    resp = recv_frame(self._conn)
                    break
                except (ConnectionError, OSError):
                    if self._conn is not None:
                        try:
                            self._conn.close()
                        except OSError:
                            pass
                        self._conn = None
                    attempt += 1
                    if (sent and not (idempotent or conditional)) \
                            or attempt > self.retry_attempts \
                            or self._closed:
                        raise
                    try:
                        from ..metrics import metrics
                        metrics.store_request_retries_total.inc()
                    except Exception:  # noqa: BLE001
                        pass
                    self._stop_event.wait(delay * (0.5 + random.random()))
                    delay = min(delay * 2.0, self.retry_cap_s)
        if not resp.get("ok"):
            raise_remote(resp)
        return resp

    def close(self) -> None:
        self._closed = True
        self._stop_event.set()  # wake any backoff sleep immediately
        with self._conn_lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except OSError:
                    pass
                self._conn = None
        for sock in self._watch_socks:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._watch_socks = []

    # -- ClusterStore surface ----------------------------------------------

    def locked(self):
        return self._lock

    def create(self, kind: str, obj, fencing: Optional[dict] = None):
        return decode(self._request(
            {"op": "create", "kind": kind, "obj": encode(obj),
             "fencing": fencing})["obj"])

    def update(self, kind: str, obj, fencing: Optional[dict] = None):
        return decode(self._request(
            {"op": "update", "kind": kind, "obj": encode(obj),
             "fencing": fencing})["obj"])

    def apply(self, kind: str, obj, fencing: Optional[dict] = None):
        return decode(self._request(
            {"op": "apply", "kind": kind, "obj": encode(obj),
             "fencing": fencing})["obj"])

    def delete(self, kind: str, name: str, namespace: Optional[str] = None,
               fencing: Optional[dict] = None):
        return decode(self._request(
            {"op": "delete", "kind": kind, "name": name,
             "namespace": namespace, "fencing": fencing})["obj"])

    def bulk_apply(self, items, fencing: Optional[dict] = None) -> List[Any]:
        """Batch mutation in ONE frame each way (the ROADMAP item-3 bulk
        ingest op): same contract as ClusterStore.bulk_apply — items are
        (kind, obj[, verb]) and the result list carries the applied
        object or the rebuilt exception instance per position. Not
        retried after an unacked send (a bulk wave is not conditional as
        a unit); a failed SEND retries like every other op."""
        resp = self._request({
            "op": "bulk_apply",
            "items": [{"kind": it[0], "obj": encode(it[1]),
                       "verb": it[2] if len(it) > 2 else "apply"}
                      for it in items],
            "fencing": fencing})
        return [remote_error(r) if "error" in r else decode(r["obj"])
                for r in resp["results"]]

    def get(self, kind: str, name: str, namespace: Optional[str] = None):
        return decode(self._request(
            {"op": "get", "kind": kind, "name": name,
             "namespace": namespace})["obj"])

    def try_get(self, kind: str, name: str, namespace: Optional[str] = None):
        from .store import NotFoundError
        try:
            return self.get(kind, name, namespace)
        except NotFoundError:
            return None

    def list(self, kind: str, namespace: Optional[str] = None,
             label_selector: Optional[Dict[str, str]] = None,
             name_glob: Optional[str] = None) -> List[Any]:
        resp = self._request(
            {"op": "list", "kind": kind, "namespace": namespace,
             "label_selector": label_selector, "name_glob": name_glob})
        return [decode(o) for o in resp["objs"]]

    def ping(self) -> bool:
        return bool(self._request({"op": "ping"}).get("ok"))

    def add_interceptor(self, fn) -> None:
        raise NotImplementedError(
            "admission interceptors run in the process that OWNS the "
            "store (standalone --serve-store starts the webhook chain "
            "there); a remote client cannot install them")

    # -- watch --------------------------------------------------------------

    def watch(self, kind: str, listener, replay: bool = True) -> None:
        """Subscribe over a dedicated streaming connection. The replay is
        applied inline before returning (list-then-watch, same synchronous
        contract as the in-memory store); live events are then delivered
        from a daemon reader thread under self.locked(). A broken stream
        resumes in place when it can (see class docstring)."""
        sock = self._connect()
        # register BEFORE the replay loop: close() must be able to unblock
        # a watch() stuck mid-replay on a stalled server
        self._watch_socks.append(sock)
        send_frame(sock, {"op": "watch", "kinds": [kind], "replay": replay})
        state = {"hwm": -1}  # per-kind resume high-water mark
        try:
            self._apply_stream(sock, kind, listener, state,
                               until_synced=True)
        except Exception:
            # server refused the subscription (e.g. unknown kind) or died
            # mid-replay: surface it to the caller, nothing to resume yet
            self._drop_watch_sock(sock)
            raise

        def reader():
            cur = sock
            while True:
                try:
                    self._apply_stream(cur, kind, listener, state,
                                       until_synced=False)
                except (ConnectionError, OSError, ValueError) as e:
                    self._drop_watch_sock(cur)
                    if self._closed:
                        return
                    cur = self._resume_watch(kind, listener, state)
                    if cur is None:
                        # a resume abandoned because close() landed
                        # mid-attempt is a clean shutdown, not a broken
                        # mirror — don't fire the crash-only contract
                        if not self._closed:
                            self._watch_broke(kind, e)
                        return
                    continue
                except Exception as e:  # noqa: BLE001 — a listener blew up
                    # mid-handler: the mirror itself may be inconsistent,
                    # which no stream resume can repair — crash-only
                    log.exception("watch listener for %s failed", kind)
                    self._drop_watch_sock(cur)
                    if not self._closed:
                        self._watch_broke(kind, e)
                    return

        t = threading.Thread(target=reader, daemon=True,
                             name=f"store-watch-{kind}")
        t.start()
        self._watch_threads.append(t)

    def _apply_stream(self, sock, kind: str, listener, state: dict,
                      until_synced: bool) -> None:
        """Read frames from a watch socket, delivering events under the
        mirror lock and advancing the resume high-water mark atomically
        with each delivery (so a resume never skips or repeats an event).
        Returns at the 'synced' marker when ``until_synced``, else loops
        until the connection dies."""
        while True:
            msg = recv_frame(sock)
            faults.fire("watch_stream")
            if msg.get("ok") is False:
                raise_remote(msg)
            stream = msg.get("stream")
            if stream == "synced":
                rv = (msg.get("rv") or {}).get(kind)
                if rv is not None:
                    with self._lock:
                        state["hwm"] = max(state["hwm"], int(rv))
                if until_synced:
                    return
                continue
            if stream != "event":
                continue  # heartbeat
            # under self._lock like every delivery: during the cache's
            # sequential subscriptions (nodes, then pods, ...) a LIVE
            # event on an earlier kind's stream must not mutate the
            # mirror concurrently with a later kind's replay — cache
            # handlers rely on the store serializing dispatch
            with self._lock:
                self._deliver(listener, msg)
                rv = msg.get("rv")
                if rv is not None:
                    state["hwm"] = max(state["hwm"], int(rv))

    def _resume_watch(self, kind: str, listener, state: dict):
        """Reconnect a broken watch stream with exponential backoff +
        jitter and ask the server to replay from our high-water mark.
        Returns the new streaming socket (mirror already resynced), or
        None when resume is impossible — unknown high-water mark, resume
        window lost server-side (ResumeGapError), or the server stayed
        unreachable past ``watch_resume_window_s`` — in which case the
        caller falls back to the crash-only contract."""
        hwm = state["hwm"]
        if not self.watch_resume or hwm < 0:
            return None
        deadline = time.monotonic() + self.watch_resume_window_s
        delay = 0.05
        attempt = 0
        while not self._closed:
            attempt += 1
            sock = None
            try:
                sock = self._connect()
                self._watch_socks.append(sock)
                send_frame(sock, {"op": "watch", "kinds": [kind],
                                  "replay": False,
                                  "since": {kind: state["hwm"]}})
                # the missed-event replay lands here, inline
                self._apply_stream(sock, kind, listener, state,
                                   until_synced=True)
            except ResumeGapError as e:
                self._drop_watch_sock(sock)
                log.error("watch stream for %r cannot resume: %s", kind, e)
                return None
            except (ConnectionError, OSError, ValueError):
                self._drop_watch_sock(sock)
                if time.monotonic() >= deadline:
                    return None
                self._stop_event.wait(delay * (0.5 + random.random()))
                delay = min(delay * 2.0, self.watch_backoff_cap_s)
                continue
            with self._lock:
                self.watch_resumes += 1
            try:
                from ..metrics import metrics
                metrics.watch_reconnects_total.inc(labels={"kind": kind})
            except Exception:  # noqa: BLE001
                pass
            log.warning("watch stream for %r resumed from rv %s "
                        "(attempt %d)", kind, hwm, attempt)
            return sock
        return None

    def _drop_watch_sock(self, sock) -> None:
        if sock is None:
            return
        try:
            self._watch_socks.remove(sock)
        except ValueError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    def _watch_broke(self, kind: str, exc: Exception) -> None:
        """A watch stream died beyond repair: the local mirror is
        permanently stale (resume was either disabled, out of window, or
        the listener itself corrupted mid-delivery)."""
        with self._lock:  # streams die together when the server goes:
            first = not self.watch_failed  # fire the callback exactly once
            self.watch_failed = True
        log.critical(
            "watch stream for %r broke (%s: %s) and could not resume; "
            "this store's mirror is frozen — restart the consumer "
            "process to resync", kind, type(exc).__name__, exc)
        if first and self.on_watch_failure is not None:
            try:
                self.on_watch_failure()
            except Exception:  # noqa: BLE001 — never kill the reader hook
                log.exception("on_watch_failure callback failed")

    @staticmethod
    def _deliver(listener, msg: dict) -> None:
        old = msg.get("old")
        listener(msg["event"], decode(msg["obj"]),
                 decode(old) if old is not None else None)
