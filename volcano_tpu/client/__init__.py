"""Cluster store: the API-server/informer seam (in-memory + over TCP),
plus the optional WAL/snapshot durability layer behind it, the sharded
front door (partitioned store + one-endpoint router), the WAL-shipped
read-replica tier, and the overload-protected admission layer every
server consults before dispatch (resilience/overload.py)."""

from ..resilience.overload import (  # noqa: F401
    AdmissionGate, OverloadedError, RetryBudget, RetryBudgetExhausted,
)
from .durable import DurableClusterStore, WriteAheadLog  # noqa: F401
from .readtier import ReadTierStore  # noqa: F401
from .remote import RemoteClusterStore  # noqa: F401
from .replica import (  # noqa: F401
    ReplicaGapError, ReplicaServer, ReplicaStore, ShardedReplicaServer,
)
from .server import StoreServer  # noqa: F401
from .sharded import (  # noqa: F401
    ShardedClusterStore, ShardRouter, shard_for,
)
from .shardproc import (  # noqa: F401
    ProcShardRouter, ProcShardedStore, ShardProcSupervisor,
    ShardWorkerServer,
)
from .store import (  # noqa: F401
    AdmissionError, ClusterStore, ConflictError, FencedError, FencedStore,
    NotFoundError, ReplicaLagError, ReplicaReadOnlyError, ResumeGapError,
    ShardUnavailableError,
)
