"""Cluster store: the API-server/informer seam (in-memory + over TCP)."""

from .remote import RemoteClusterStore  # noqa: F401
from .server import StoreServer  # noqa: F401
from .store import (  # noqa: F401
    AdmissionError, ClusterStore, ConflictError, FencedError, FencedStore,
    NotFoundError, ResumeGapError,
)
