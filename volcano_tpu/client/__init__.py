"""Cluster store: the API-server/informer seam (in-memory + over TCP),
plus the optional WAL/snapshot durability layer behind it."""

from .durable import DurableClusterStore, WriteAheadLog  # noqa: F401
from .remote import RemoteClusterStore  # noqa: F401
from .server import StoreServer  # noqa: F401
from .store import (  # noqa: F401
    AdmissionError, ClusterStore, ConflictError, FencedError, FencedStore,
    NotFoundError, ResumeGapError,
)
