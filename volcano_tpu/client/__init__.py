"""Cluster store: the API-server/informer seam (in-memory + over TCP),
plus the optional WAL/snapshot durability layer behind it and the
sharded front door (partitioned store + one-endpoint router)."""

from .durable import DurableClusterStore, WriteAheadLog  # noqa: F401
from .remote import RemoteClusterStore  # noqa: F401
from .server import StoreServer  # noqa: F401
from .sharded import (  # noqa: F401
    ShardedClusterStore, ShardRouter, shard_for,
)
from .store import (  # noqa: F401
    AdmissionError, ClusterStore, ConflictError, FencedError, FencedStore,
    NotFoundError, ResumeGapError, ShardUnavailableError,
)
