"""In-memory cluster store: the API-server/informer seam."""

from .store import (  # noqa: F401
    AdmissionError, ClusterStore, ConflictError, NotFoundError,
)
