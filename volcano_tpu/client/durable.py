"""Durable ClusterStore: write-ahead log + compacted snapshots.

Every crash-safety layer above the store — the bind-intent journal
(PR 5), the migration-intent journal (PR 8), the HA lease, the
watch-resume EventJournal — persists *into the store*, so a store crash
silently voided all of them. The reference never had this hole: the k8s
API server persists every object through etcd's WAL + raft snapshots.
This module is that durability floor.

``DurableClusterStore`` is a ``ClusterStore`` whose every committed
mutation appends one fsync'd record to an append-only log BEFORE any
watcher observes it, and which periodically compacts the log into a full
snapshot. On start it recovers: newest valid snapshot (CRC-framed; a
corrupt one falls back to the previous), then the WAL tail replayed on
top (CRC-checked per record, a torn final record truncated), restoring
the buckets, the global ``resource_version`` counter, the per-kind event
rvs, AND a bounded per-kind tail of the replayed events so the server's
``EventJournal`` can seed its resume window — a watcher that was mid-
stream when the store died resumes over the restart through the normal
``since:`` path instead of the crash-only full resync.

File layout under ``data_dir``::

    snapshot-<rv>.ckpt   one CRC-framed JSON blob (tmp+rename, fsync'd)
    wal-<rv>.log         records with resource_version > <rv>; a new
                         segment opens at every snapshot (and at every
                         process start), so segments fully covered by
                         the oldest retained snapshot can be pruned

Record/snapshot framing: ``<u32 len><u32 crc32(payload)><payload>`` with
JSON payloads built from the wire codec (client/codec.py) — the WAL
speaks the same tagged-JSON dialect as the TCP protocol, inspectable
with a text editor and closed over the model registry.

fsync policy (``--store-fsync``): ``every`` (default — an acked write is
durable; one fsync per commit, batched to one per ``bulk_apply``),
``interval`` (group commit: at most one fsync per interval; a crash can
lose the last interval's acked writes), ``off`` (flush to the OS, never
fsync; survives process kill, not host power loss). The in-memory
default path is untouched: a plain ``ClusterStore`` has no WAL and pays
nothing.

Fault points: ``wal_fsync`` fires inside every fsync (arm ``delay:`` for
a slow disk, ``exc:`` for a write error surfacing to the client);
``store_crash`` fires after the WAL append and before the commit is
announced (arm ``exc:exit`` to kill -9 the store process with the record
durable but the response never sent — the ambiguous-crash case the
conditional-retry rules in client/remote.py exist for).
"""

from __future__ import annotations

import collections
import glob
import json
import logging
import os
import struct
import time
import zlib
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..resilience.faultinject import faults
from .codec import decode, encode
from .store import ClusterStore

log = logging.getLogger(__name__)

_HEADER = struct.Struct("<II")  # payload length, crc32(payload)
FSYNC_POLICIES = ("every", "interval", "off")
SNAPSHOT_EVERY_RECORDS = 4096   # WAL records between compactions
KEEP_SNAPSHOTS = 2              # newest + one fallback
TAIL_CAPACITY = 4096            # per-kind recovered events kept for the
                                # EventJournal's resume window


def _frame(payload: bytes) -> bytes:
    return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def read_frames(path: str) -> Tuple[List[dict], int, bool]:
    """All valid frames in ``path`` -> (payloads, valid_bytes, torn).

    Stops at the first torn or corrupt frame (short header, short body,
    CRC mismatch, undecodable JSON): everything before it is good,
    everything from it on is the debris of a crash mid-append. Returns
    the byte offset the file should be truncated to."""
    out: List[dict] = []
    offset = 0
    torn = False
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return out, 0, False
    while offset < len(data):
        if offset + _HEADER.size > len(data):
            torn = True
            break
        length, crc = _HEADER.unpack_from(data, offset)
        body = data[offset + _HEADER.size: offset + _HEADER.size + length]
        if len(body) < length or zlib.crc32(body) != crc:
            torn = True
            break
        try:
            out.append(json.loads(body))
        except ValueError:
            torn = True
            break
        offset += _HEADER.size + length
    return out, offset, torn


class WriteAheadLog:
    """One append-only WAL segment. Appends always flush to the OS;
    fsync follows the policy (see module docstring). Not thread-safe on
    its own — the owning store serializes appends under its write lock,
    exactly like the mutations they record."""

    def __init__(self, path: str, fsync: str = "every",
                 fsync_interval_s: float = 0.05,
                 metric_labels: Optional[Dict[str, str]] = None):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(f"fsync policy {fsync!r} not in "
                             f"{FSYNC_POLICIES}")
        self.path = path
        self.fsync_policy = fsync
        self.fsync_interval_s = float(fsync_interval_s)
        # e.g. {"shard": "3"} on a sharded member's WAL, so the
        # volcano_store_wal_* family separates per shard lineage; the
        # unsharded store stays label-free (byte-identical exposition)
        self.metric_labels = metric_labels
        self._f = open(path, "ab")
        self.size_bytes = self._f.tell()
        self.appends = 0
        self.fsyncs = 0
        self._last_sync = 0.0

    def append(self, record: dict, sync: bool = True) -> None:
        raw = json.dumps(record, separators=(",", ":")).encode()
        frame = _frame(raw)
        self._f.write(frame)
        self._f.flush()
        self.size_bytes += len(frame)
        self.appends += 1
        if sync:
            self.maybe_sync()

    def maybe_sync(self) -> None:
        """fsync if the policy calls for one now (``every`` always,
        ``interval`` at most once per interval, ``off`` never)."""
        if self.fsync_policy == "off":
            return
        if self.fsync_policy == "interval" and \
                time.monotonic() - self._last_sync < self.fsync_interval_s:
            return
        self.sync()

    def sync(self) -> None:
        faults.fire("wal_fsync")
        os.fsync(self._f.fileno())
        self._last_sync = time.monotonic()
        self.fsyncs += 1
        try:
            from ..metrics import metrics
            metrics.store_wal_fsyncs_total.inc(labels=self.metric_labels)
        except Exception:  # noqa: BLE001 — accounting never fails a write
            pass

    def close(self) -> None:
        try:
            self._f.flush()
            if self.fsync_policy != "off":
                self.sync()
        finally:
            self._f.close()


def _snapshot_paths(data_dir: str) -> List[str]:
    return sorted(glob.glob(os.path.join(data_dir, "snapshot-*.ckpt")))


def _segment_paths(data_dir: str) -> List[str]:
    return sorted(glob.glob(os.path.join(data_dir, "wal-*.log")))


def _start_rv(path: str) -> int:
    base = os.path.basename(path)
    return int(base.split("-", 1)[1].split(".", 1)[0])


def write_snapshot(data_dir: str, state: dict,
                   metric_labels: Optional[Dict[str, str]] = None) -> str:
    """Atomically persist one snapshot blob: tmp file, fsync, rename,
    fsync the directory — a crash at any point leaves either the old
    snapshot set or the old set plus one complete new snapshot."""
    rv = int(state["rv"])
    raw = json.dumps(state, separators=(",", ":")).encode()
    path = os.path.join(data_dir, f"snapshot-{rv:016d}.ckpt")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_frame(raw))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(data_dir)
    try:
        from ..metrics import metrics
        metrics.store_wal_snapshots_total.inc(labels=metric_labels)
        metrics.store_wal_snapshot_bytes.set(os.path.getsize(path),
                                             labels=metric_labels)
        metrics.store_wal_snapshot_timestamp.set(time.time(),
                                                 labels=metric_labels)
    except Exception:  # noqa: BLE001
        pass
    return path


def load_snapshot(path: str) -> Optional[dict]:
    """The snapshot's state dict, or None when the blob is torn/corrupt
    (recovery then falls back to the previous snapshot)."""
    frames, _, torn = read_frames(path)
    if torn or not frames:
        return None
    return frames[0]


def _fsync_dir(data_dir: str) -> None:
    try:
        fd = os.open(data_dir, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class DurableClusterStore(ClusterStore):
    """See module docstring. Drop-in for ``ClusterStore`` behind
    ``--store-data-dir``; construction IS recovery (an empty directory
    recovers to an empty store)."""

    #: this store can feed a replica: it exposes the ship interface
    #: (ship_floor / add_ship_listener / newest_snapshot_state). The
    #: replica mirror sets the same flag — a replica can re-serve its
    #: applied stream to a deeper replica (client/replica.py)
    ship_capable = True

    def __init__(self, data_dir: str, fsync: str = "every",
                 fsync_interval_s: float = 0.05,
                 snapshot_every: int = SNAPSHOT_EVERY_RECORDS,
                 keep_snapshots: int = KEEP_SNAPSHOTS,
                 tail_capacity: int = TAIL_CAPACITY,
                 shard: Optional[str] = None):
        super().__init__()
        self.data_dir = data_dir
        # shard name of a sharded member (client/sharded.py): labels the
        # volcano_store_wal_* metric family so per-shard WAL lineages
        # separate; None (the unsharded store) keeps the exposition
        # byte-identical to before
        self.shard = shard
        self.metric_labels = {"shard": shard} if shard is not None else None
        self.fsync_policy = fsync
        self.fsync_interval_s = fsync_interval_s
        self.snapshot_every = int(snapshot_every)
        self.keep_snapshots = max(1, int(keep_snapshots))
        self.tail_capacity = int(tail_capacity)
        os.makedirs(data_dir, exist_ok=True)
        #: per kind: [(rv, event, obj, old)] replayed from the WAL tail,
        #: bounded; the EventJournal seeds its resume window from these
        self.recovery_tail: Dict[str, Deque] = {}
        #: per kind: rv at/below which recovered events are NOT
        #: replayable (the snapshot's per-kind event rv, advanced when
        #: the bounded tail drops its oldest entry)
        self.recovery_floors: Dict[str, int] = {}
        self.recovered_records = 0
        self.recovered_snapshot_rv = 0
        self.snapshot_fallbacks = 0
        self.recovery_ms = 0.0
        self._fence_ctx: Optional[dict] = None
        self._batch_depth = 0
        self._records_since_snapshot = 0
        self._wal: Optional[WriteAheadLog] = None  # None during recovery
        #: WAL-shipping hooks (client/replica.py): called with each
        #: committed record dict, under the store lock, AFTER the append
        #: — a ship stream's live tail sees exactly the records the WAL
        #: holds, in commit order
        self._ship_listeners: list = []
        self._recover()
        self._wal = self._open_segment()
        try:
            from ..metrics import metrics
            metrics.store_wal_recovery_ms.set(self.recovery_ms,
                                              labels=self.metric_labels)
            metrics.store_wal_recovery_records.set(
                self.recovered_records, labels=self.metric_labels)
        except Exception:  # noqa: BLE001
            pass

    # -- recovery -----------------------------------------------------------

    def _recover(self) -> None:
        t0 = time.perf_counter()
        snap_rv = 0
        kind_rv_floor: Dict[str, int] = {}
        for path in reversed(_snapshot_paths(self.data_dir)):
            state = load_snapshot(path)
            if state is None:
                log.error("store snapshot %s is corrupt; falling back to "
                          "the previous snapshot + full WAL replay", path)
                self.snapshot_fallbacks += 1
                continue
            for kind, objs in state["buckets"].items():
                bucket = self._buckets.setdefault(kind, {})
                for eobj in objs:
                    obj = decode(eobj)
                    bucket[self._obj_key(obj)] = obj
            self._rv = int(state["rv"])
            for kind, rv in state["kind_rv"].items():
                self._kind_rv[kind] = int(rv)
            snap_rv = self._rv
            kind_rv_floor = {k: int(v)
                             for k, v in state["kind_rv"].items()}
            self.recovered_snapshot_rv = snap_rv
            break
        segments = _segment_paths(self.data_dir)
        for path in segments:
            records, valid_bytes, torn = read_frames(path)
            for rec in records:
                rv = int(rec["rv"])
                if rv <= snap_rv:
                    continue  # already in the snapshot
                self._apply_recovered(rec, rv, kind_rv_floor)
            if torn:
                if path == segments[-1]:
                    # a crash mid-append left a torn record: everything
                    # before it committed, everything from it on never
                    # acked — cut it off so the next append starts on a
                    # clean frame boundary
                    log.warning("truncating torn WAL tail in %s at byte "
                                "%d", path, valid_bytes)
                    with open(path, "ab") as f:
                        f.truncate(valid_bytes)
                else:
                    # corruption in a CLOSED segment is not crash debris
                    # (rotation fsync'd it whole): keep the file for
                    # forensics, but nothing after it is trustworthy
                    log.error("WAL segment %s is corrupt at byte %d; "
                              "stopping replay there", path, valid_bytes)
                break  # nothing after a torn record is trustworthy
        self.recovery_ms = (time.perf_counter() - t0) * 1e3
        if self.recovered_records or snap_rv:
            log.info("store recovered: rv=%d (%d snapshot, %d WAL "
                     "records replayed) in %.1f ms", self._rv, snap_rv,
                     self.recovered_records, self.recovery_ms)

    def _apply_recovered(self, rec: dict, rv: int,
                         kind_rv_floor: Dict[str, int]) -> None:
        kind, event = rec["kind"], rec["event"]
        obj = decode(rec["obj"])
        bucket = self._buckets.setdefault(kind, {})
        key = self._obj_key(obj)
        old = bucket.get(key)
        if event == "delete":
            bucket.pop(key, None)
        else:
            bucket[key] = obj
        self._rv = max(self._rv, rv)
        self._kind_rv[kind] = rv
        self.recovered_records += 1
        tail = self.recovery_tail.get(kind)
        if tail is None:
            tail = self.recovery_tail[kind] = collections.deque()
            self.recovery_floors[kind] = kind_rv_floor.get(kind, 0)
        if len(tail) >= self.tail_capacity:
            self.recovery_floors[kind] = tail.popleft()[0]
        # update events without a snapshot-era predecessor replay with
        # old=obj — the in-place-update idiom the live stream already
        # exhibits, and the cache's handlers are resync-safe either way
        tail.append((rv, event, obj,
                     old if event == "update" and old is not None
                     else (obj if event == "update" else None)))

    @staticmethod
    def _obj_key(obj: Any) -> str:
        ns = getattr(obj, "namespace", None)
        return f"{ns}/{obj.name}" if ns is not None else obj.name

    # -- journaling seam ----------------------------------------------------

    def create(self, kind: str, obj, fencing: Optional[dict] = None):
        with self._lock:
            self._fence_ctx = fencing
            try:
                return super().create(kind, obj, fencing=fencing)
            finally:
                self._fence_ctx = None

    def update(self, kind: str, obj, fencing: Optional[dict] = None):
        with self._lock:
            self._fence_ctx = fencing
            try:
                return super().update(kind, obj, fencing=fencing)
            finally:
                self._fence_ctx = None

    def delete(self, kind: str, name: str, namespace: Optional[str] = None,
               fencing: Optional[dict] = None):
        with self._lock:
            self._fence_ctx = fencing
            try:
                return super().delete(kind, name, namespace,
                                      fencing=fencing)
            finally:
                self._fence_ctx = None

    def _notify(self, kind: str, event: str, obj, old=None) -> None:
        # runs under the store lock at the commit point: append (and per
        # policy fsync) BEFORE any listener — a watcher must never observe
        # a write that a crash could still lose
        if self._wal is not None:
            t0 = time.perf_counter()
            # ts: commit wall time, so a replica tailing shipped records
            # can report lag in SECONDS, not just records
            rec = {"rv": self._rv, "kind": kind, "event": event,
                   "obj": encode(obj), "ts": round(time.time(), 3)}
            if self._fence_ctx:
                rec["fencing"] = self._fence_ctx
            self._wal.append(rec, sync=self._batch_depth == 0)
            try:
                from ..metrics import metrics
                metrics.store_wal_appends_total.inc(
                    labels=self.metric_labels)
                metrics.store_wal_append_seconds.observe(
                    time.perf_counter() - t0, labels=self.metric_labels)
                metrics.store_wal_size_bytes.set(
                    self._wal.size_bytes, labels=self.metric_labels)
            except Exception:  # noqa: BLE001
                pass
            faults.fire("store_crash")
            for fn in list(self._ship_listeners):
                fn(rec)
            self._records_since_snapshot += 1
            if self._records_since_snapshot >= self.snapshot_every \
                    and self._batch_depth == 0:
                self.snapshot()
        super()._notify(kind, event, obj, old)

    def _batch_begin(self) -> None:
        self._batch_depth += 1

    def _batch_end(self, sync: bool = True) -> None:
        self._batch_depth -= 1
        if self._batch_depth == 0 and self._wal is not None:
            if sync:
                self._wal.maybe_sync()  # ONE fsync for the whole batch
            # sync=False: the sharded store owns the fsync — it runs one
            # batch per touched shard and syncs every touched WAL in
            # parallel afterwards (client/sharded.py _sync_shards)
            if self._records_since_snapshot >= self.snapshot_every:
                self.snapshot()

    # -- compaction ---------------------------------------------------------

    def snapshot(self) -> str:
        """Compact: persist the full store state as one snapshot, rotate
        the WAL onto a fresh segment, prune snapshots/segments the
        retained set no longer needs. Runs inline under the store lock
        every ``snapshot_every`` records, or on demand."""
        with self._lock:
            state = {
                "rv": self._rv,
                "kind_rv": dict(self._kind_rv),
                "buckets": {k: [encode(o) for o in b.values()]
                            for k, b in self._buckets.items()},
            }
            path = write_snapshot(self.data_dir, state,
                                  metric_labels=self.metric_labels)
            if self._wal is not None:
                self._wal.close()
                self._wal = self._open_segment()
            self._records_since_snapshot = 0
            self._prune()
            return path

    def _open_segment(self) -> WriteAheadLog:
        return WriteAheadLog(
            os.path.join(self.data_dir, f"wal-{self._rv:016d}.log"),
            fsync=self.fsync_policy,
            fsync_interval_s=self.fsync_interval_s,
            metric_labels=self.metric_labels)

    def _prune(self) -> None:
        snaps = _snapshot_paths(self.data_dir)
        keep = snaps[-self.keep_snapshots:]
        for path in snaps[:-self.keep_snapshots]:
            try:
                os.unlink(path)
            except OSError:
                pass
        if len(keep) < self.keep_snapshots:
            # no fallback snapshot yet: every segment must stay, or a
            # corrupt newest snapshot would have nothing to replay from
            return
        oldest_kept_rv = _start_rv(keep[0])
        # a segment is deletable when the NEXT segment's start rv (== the
        # last rv this one can contain; segments rotate at snapshots) is
        # covered by the oldest retained snapshot
        segments = _segment_paths(self.data_dir)
        for path, nxt in zip(segments, segments[1:]):
            if _start_rv(nxt) <= oldest_kept_rv:
                try:
                    os.unlink(path)
                except OSError:
                    pass

    def close(self) -> None:
        """Flush and fsync the WAL (clean shutdown; crash recovery does
        not depend on this running)."""
        with self._lock:
            if self._wal is not None:
                self._wal.close()
                self._wal = None

    # -- WAL shipping (read replicas, client/replica.py) --------------------

    def add_ship_listener(self, fn) -> None:
        """Subscribe to committed WAL records (called under the store
        lock with the record dict, after the append). The ship stream's
        live-tail seam."""
        with self._lock:
            self._ship_listeners.append(fn)

    def remove_ship_listener(self, fn) -> None:
        with self._lock:
            try:
                self._ship_listeners.remove(fn)
            except ValueError:
                pass

    def ship_floor(self) -> int:
        """Oldest rv a ship stream can resume AFTER: records at rv <=
        this are no longer in retained WAL segments (pruned into
        snapshots), so a replica whose applied rv fell below it has a
        HOLE it must close with a fresh snapshot bootstrap, never by
        skipping. Call under the store lock to pair it with ``_rv``."""
        segments = _segment_paths(self.data_dir)
        return _start_rv(segments[0]) if segments else self._rv

    def newest_snapshot_state(self) -> Tuple[int, Optional[dict]]:
        """The newest VALID on-disk snapshot as ``(rv, state)`` — the
        replica bootstrap payload. A corrupt newest snapshot falls back
        to the previous (same rule recovery applies); no valid snapshot
        means ``(0, None)``: the replica starts empty and the WAL (still
        fully retained — pruning requires snapshots) replays history."""
        for path in reversed(_snapshot_paths(self.data_dir)):
            state = load_snapshot(path)
            if state is not None:
                return int(state["rv"]), state
        return 0, None

    # -- introspection ------------------------------------------------------

    @property
    def wal(self) -> Optional[WriteAheadLog]:
        return self._wal
