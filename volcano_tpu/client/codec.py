"""Wire codec for model objects: tagged JSON <-> dataclasses.

The networked ClusterStore (client.server / client.remote) carries the same
model objects the in-process store holds (volcano_tpu.models dataclasses,
str-enums nested inside). The codec tags every dataclass node with its
class name and every enum with its enum class, so the receiving side
reconstructs real model instances — not dicts — and code like
``pg.status.phase == PodGroupPhase.RUNNING`` behaves identically on both
sides of the wire. JSON (not pickle) keeps the protocol inspectable and
closed over the model registry: a hostile peer can only instantiate
volcano_tpu.models classes. Reference parity: the k8s API server speaks
typed JSON for the same objects (vcctl.go talks to it via client-go).

Hot path: the sharded front door moves tens of thousands of objects per
second through encode/decode (bulk ingest waves in, watch events out),
so the codec is built for throughput:

- per-class field plans are cached (``dataclasses.fields`` walks and
  per-call ``is_dataclass`` probes are paid once per class, not per
  object);
- encoding is SPARSE: a field whose value equals its static default (or
  an empty container from its default factory) is omitted — ``decode``
  has always rebuilt instances with ``cls(**present_fields)``, so
  missing fields regain their defaults on the other side, the wire/WAL
  stays format-compatible in both directions, and a mostly-default Pod
  costs less than half the bytes (and correspondingly less json time);
- primitives fast-path on exact type, so enums (str/int subclasses)
  still route to their tagged form first.
"""

from __future__ import annotations

import base64
import dataclasses
import enum
from typing import Any, Dict, List, Optional, Tuple

from .. import models as _models

_T = "__t"   # dataclass tag
_E = "__e"   # enum tag
_B = "__b"   # bytes tag (Secret data values are bytes)
_D = "__d"   # escape tag: plain dict whose own keys collide with a tag
_RESERVED = frozenset((_T, _E, _B, _D))


def _registry() -> Dict[str, type]:
    reg: Dict[str, type] = {}
    for name in dir(_models):
        cls = getattr(_models, name)
        if isinstance(cls, type) and (
                dataclasses.is_dataclass(cls)
                or issubclass(cls, enum.Enum)):
            reg[cls.__name__] = cls
    return reg


_REGISTRY = _registry()

_MISSING = object()

#: per dataclass: ((field_name, skip_sentinel), ...) — skip_sentinel is
#: the value to omit from the wire (the field's static default, or the
#: empty container its default factory produces), or _MISSING when the
#: field must always be encoded
_ENC_PLANS: Dict[type, Tuple[Tuple[str, Any], ...]] = {}
#: per dataclass: frozenset of constructable field names
_KNOWN: Dict[type, frozenset] = {}


def _enc_plan(cls: type) -> Tuple[Tuple[str, Any], ...]:
    plan = _ENC_PLANS.get(cls)
    if plan is None:
        rows: List[Tuple[str, Any]] = []
        for f in dataclasses.fields(cls):
            sentinel: Any = _MISSING
            if f.default is not dataclasses.MISSING:
                sentinel = f.default
            elif f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
                produced = f.default_factory()  # type: ignore[misc]
                # only stable, empty containers are skippable — a
                # factory like new_uid() produces a fresh value every
                # call, which an omitted field would silently replace
                if produced == {} or produced == [] or produced == ():
                    sentinel = produced
            rows.append((f.name, sentinel))
        plan = _ENC_PLANS[cls] = tuple(rows)
    return plan


def _known(cls: type) -> frozenset:
    known = _KNOWN.get(cls)
    if known is None:
        known = _KNOWN[cls] = frozenset(
            f.name for f in dataclasses.fields(cls))
    return known


def encode(obj: Any) -> Any:
    """Model object -> JSON-able structure (sparse: default-valued
    fields are omitted; decode restores them)."""
    t = obj.__class__
    # exact-type fast path: a str-enum's class is the enum, not str, so
    # enums fall through to their tagged form below
    if obj is None or t is str or t is int or t is float or t is bool:
        return obj
    if t is dict:
        out = {k: encode(v) for k, v in obj.items()}
        if _RESERVED & out.keys():
            # a user dict (annotation/label/template) whose own keys
            # collide with a tag must not be mistaken for a tagged node
            return {_D: out}
        return out
    if t is list or t is tuple:
        return [encode(v) for v in obj]
    plan = _ENC_PLANS.get(t)
    if plan is None:
        if isinstance(obj, enum.Enum):
            return {_E: t.__name__, "v": obj.value}
        if isinstance(obj, bytes):
            return {_B: base64.b64encode(obj).decode()}
        if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
            plan = _enc_plan(t)
        elif isinstance(obj, (int, float, str, bool)):
            return obj  # bool/int/str subclasses that are not enums
        elif isinstance(obj, dict):
            out = {k: encode(v) for k, v in obj.items()}
            return {_D: out} if _RESERVED & out.keys() else out
        elif isinstance(obj, (list, tuple)):
            return [encode(v) for v in obj]
        else:
            raise TypeError(
                f"cannot encode {t.__name__} for the wire")
    fields: Dict[str, Any] = {}
    for name, sentinel in plan:
        v = getattr(obj, name)
        if sentinel is not _MISSING and (
                v is sentinel or v == sentinel):
            continue
        fields[name] = encode(v)
    return {_T: t.__name__, "f": fields}


def known_fields(cls: type) -> frozenset:
    """Constructable field names of a model dataclass (the set decode()
    filters against) — the delta patch path validates field names from
    the wire against this before any setattr."""
    return _known(cls)


def decode(data: Any) -> Any:
    """JSON structure -> model object (closed over the models registry).
    Fields absent from the wire regain their class defaults."""
    if isinstance(data, dict):
        tag = data.get(_T)
        if tag is not None:
            cls = _REGISTRY.get(tag)
            if cls is None or not dataclasses.is_dataclass(cls):
                raise ValueError(f"unknown model class {tag!r}")
            known = _known(cls)
            return cls(**{k: decode(v) for k, v in data["f"].items()
                          if k in known})
        etag = data.get(_E)
        if etag is not None:
            cls = _REGISTRY.get(etag)
            if cls is None or not issubclass(cls, enum.Enum):
                raise ValueError(f"unknown enum class {etag!r}")
            return cls(data["v"])
        btag = data.get(_B)
        if btag is not None:
            return base64.b64decode(btag)
        if _D in data:
            return {k: decode(v) for k, v in data[_D].items()}
        return {k: decode(v) for k, v in data.items()}
    if isinstance(data, list):
        return [decode(v) for v in data]
    return data


# -- delta watch dialect ------------------------------------------------------
#
# The ``delta: true`` watch mode (client/server.py negotiation) ships an
# UPDATE event as a field-sparse column patch instead of the full object
# form: one interned key id ("dk"), parallel columns of interned field
# ids and wire values ("df"/"dv"), and the fields that returned to their
# class defaults ("dx"). Hot immutable strings and enums (names, nodes,
# phases) are interned into an append-only per-stream table — the frame
# carries {"__i": id} references plus the table additions this event
# created ("tb": [start, [entries...]]) — so a storm of phase flips costs
# a few ints per event on the wire and ZERO full-object decodes on the
# client. Adds/deletes (and any update the dialect cannot express) stay
# object frames; the two forms interleave freely on one stream, which is
# what keeps journal-resume replay (always object form) compatible.

_I = "__i"   # interned-value reference (delta frames only)

#: interning-table hard cap per stream/shard: past this the server ships
#: raw values (no fallback needed server-side); a CLIENT asked to grow
#: beyond its own cap falls back typed (``vocab_overflow``)
DELTA_VOCAB_MAX = 65536


class Interner:
    """Append-only value table for the delta dialect. Entries are wire
    (encoded) values — plain strings, or tagged enum forms — identified
    by position; callers snapshot the whole table into the stream's
    ``synced`` frame and ship per-event additions in order, so both
    sides' tables stay id-aligned without any retraction protocol."""

    __slots__ = ("entries", "_ids", "cap")

    def __init__(self, cap: int = DELTA_VOCAB_MAX):
        self.entries: List[Any] = []
        self._ids: Dict[Any, int] = {}
        self.cap = cap

    def intern(self, enc: Any) -> Optional[int]:
        """Table id for an encoded value worth interning (str, or the
        tagged enum form), or None when it must ship raw — not an
        internable shape, or the table is at cap."""
        if isinstance(enc, str):
            key: Any = enc
        elif isinstance(enc, dict) and len(enc) == 2 and _E in enc:
            key = (_E, enc[_E], enc["v"])
        else:
            return None
        i = self._ids.get(key)
        if i is None:
            if len(self.entries) >= self.cap:
                return None
            i = len(self.entries)
            self._ids[key] = i
            self.entries.append(enc)
        return i

    def snapshot(self) -> List[Any]:
        return list(self.entries)


def object_key(obj: Any) -> str:
    """The store bucket key of a model object ('<ns>/<name>', or bare
    name for unnamespaced kinds) — what a patch's ``dk`` id resolves to
    on both sides of the wire."""
    ns = getattr(obj, "namespace", None)
    return f"{ns}/{obj.name}" if ns is not None else obj.name


def delta_diff(enc_new: Any, enc_old: Any) -> Optional[Tuple[dict, list]]:
    """Field-sparse diff of two sparse-encoded ({__t, f}) forms of the
    same object: ``(changed {field: wire value}, cleared [field, ...])``
    where *cleared* fields went back to their class defaults (encode()
    omitted them). None when the dialect cannot express the change —
    either side is not a tagged dataclass form, or the class changed."""
    if not (isinstance(enc_new, dict) and isinstance(enc_old, dict)):
        return None
    tag = enc_new.get(_T)
    if tag is None or tag != enc_old.get(_T):
        return None
    fnew, fold = enc_new["f"], enc_old["f"]
    changed = {k: v for k, v in fnew.items()
               if k not in fold or fold[k] != v}
    cleared = [k for k in fold if k not in fnew]
    return changed, cleared


def delta_value(enc: Any, interner: Interner) -> Any:
    """Wire form of one changed field's encoded value: an {"__i": id}
    reference for interned hot immutables, the raw encoded value
    otherwise — escaped when a genuine single-key user dict could be
    mistaken for a reference."""
    i = interner.intern(enc)
    if i is not None:
        return {_I: i}
    if isinstance(enc, dict) and len(enc) == 1 and _I in enc:
        return {_D: enc}
    return enc


def delta_resolve(v: Any, table: List[Any]) -> Any:
    """One wire value back to a model value: interned references hit the
    table's pre-decoded cache (so a phase flip pays zero decode); raw
    values go through decode(). IndexError on an unknown reference — the
    caller's typed ``schema_skew`` fallback."""
    if isinstance(v, dict) and len(v) == 1 and _I in v:
        return table[v[_I]]
    return decode(v)


#: per dataclass: field name -> dataclasses.Field (clearing support)
_FIELD_MAP: Dict[type, Dict[str, Any]] = {}


def field_default(cls: type, name: str) -> Any:
    """A fresh default for clearing field ``name`` back to its class
    default (fresh container per call: cleared fields must never share
    mutable state across objects). ValueError when the field has no
    default — a patch clearing a required field is schema skew."""
    fmap = _FIELD_MAP.get(cls)
    if fmap is None:
        fmap = _FIELD_MAP[cls] = {
            f.name: f for f in dataclasses.fields(cls)}
    f = fmap.get(name)
    if f is None:
        raise ValueError(f"{cls.__name__} has no field {name!r}")
    if f.default is not dataclasses.MISSING:
        return f.default
    if f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
        return f.default_factory()  # type: ignore[misc]
    raise ValueError(
        f"field {cls.__name__}.{name} has no default to clear to")
