"""Wire codec for model objects: tagged JSON <-> dataclasses.

The networked ClusterStore (client.server / client.remote) carries the same
model objects the in-process store holds (volcano_tpu.models dataclasses,
str-enums nested inside). The codec tags every dataclass node with its
class name and every enum with its enum class, so the receiving side
reconstructs real model instances — not dicts — and code like
``pg.status.phase == PodGroupPhase.RUNNING`` behaves identically on both
sides of the wire. JSON (not pickle) keeps the protocol inspectable and
closed over the model registry: a hostile peer can only instantiate
volcano_tpu.models classes. Reference parity: the k8s API server speaks
typed JSON for the same objects (vcctl.go talks to it via client-go).
"""

from __future__ import annotations

import base64
import dataclasses
import enum
from typing import Any, Dict

from .. import models as _models

_T = "__t"   # dataclass tag
_E = "__e"   # enum tag
_B = "__b"   # bytes tag (Secret data values are bytes)
_D = "__d"   # escape tag: plain dict whose own keys collide with a tag
_RESERVED = frozenset((_T, _E, _B, _D))


def _registry() -> Dict[str, type]:
    reg: Dict[str, type] = {}
    for name in dir(_models):
        cls = getattr(_models, name)
        if isinstance(cls, type) and (
                dataclasses.is_dataclass(cls)
                or issubclass(cls, enum.Enum)):
            reg[cls.__name__] = cls
    return reg


_REGISTRY = _registry()


def encode(obj: Any) -> Any:
    """Model object -> JSON-able structure."""
    # str/int-enums would pass the primitive isinstance test: tag first
    if isinstance(obj, enum.Enum):
        return {_E: type(obj).__name__, "v": obj.value}
    if obj is None or isinstance(obj, (int, float, str, bool)):
        return obj
    if isinstance(obj, bytes):
        return {_B: base64.b64encode(obj).decode()}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {_T: type(obj).__name__,
                "f": {f.name: encode(getattr(obj, f.name))
                      for f in dataclasses.fields(obj)}}
    if isinstance(obj, dict):
        out = {k: encode(v) for k, v in obj.items()}
        if _RESERVED & out.keys():
            # a user dict (annotation/label/template) whose own keys
            # collide with a tag must not be mistaken for a tagged node
            return {_D: out}
        return out
    if isinstance(obj, (list, tuple)):
        return [encode(v) for v in obj]
    raise TypeError(f"cannot encode {type(obj).__name__} for the wire")


def decode(data: Any) -> Any:
    """JSON structure -> model object (closed over the models registry)."""
    if isinstance(data, dict):
        tag = data.get(_T)
        if tag is not None:
            cls = _REGISTRY.get(tag)
            if cls is None or not dataclasses.is_dataclass(cls):
                raise ValueError(f"unknown model class {tag!r}")
            fields = {k: decode(v) for k, v in data["f"].items()}
            known = {f.name for f in dataclasses.fields(cls)}
            return cls(**{k: v for k, v in fields.items() if k in known})
        etag = data.get(_E)
        if etag is not None:
            cls = _REGISTRY.get(etag)
            if cls is None or not issubclass(cls, enum.Enum):
                raise ValueError(f"unknown enum class {etag!r}")
            return cls(data["v"])
        btag = data.get(_B)
        if btag is not None:
            return base64.b64decode(btag)
        if _D in data:
            return {k: decode(v) for k, v in data[_D].items()}
        return {k: decode(v) for k, v in data.items()}
    if isinstance(data, list):
        return [decode(v) for v in data]
    return data
