"""Wire codec for model objects: tagged JSON <-> dataclasses.

The networked ClusterStore (client.server / client.remote) carries the same
model objects the in-process store holds (volcano_tpu.models dataclasses,
str-enums nested inside). The codec tags every dataclass node with its
class name and every enum with its enum class, so the receiving side
reconstructs real model instances — not dicts — and code like
``pg.status.phase == PodGroupPhase.RUNNING`` behaves identically on both
sides of the wire. JSON (not pickle) keeps the protocol inspectable and
closed over the model registry: a hostile peer can only instantiate
volcano_tpu.models classes. Reference parity: the k8s API server speaks
typed JSON for the same objects (vcctl.go talks to it via client-go).

Hot path: the sharded front door moves tens of thousands of objects per
second through encode/decode (bulk ingest waves in, watch events out),
so the codec is built for throughput:

- per-class field plans are cached (``dataclasses.fields`` walks and
  per-call ``is_dataclass`` probes are paid once per class, not per
  object);
- encoding is SPARSE: a field whose value equals its static default (or
  an empty container from its default factory) is omitted — ``decode``
  has always rebuilt instances with ``cls(**present_fields)``, so
  missing fields regain their defaults on the other side, the wire/WAL
  stays format-compatible in both directions, and a mostly-default Pod
  costs less than half the bytes (and correspondingly less json time);
- primitives fast-path on exact type, so enums (str/int subclasses)
  still route to their tagged form first.
"""

from __future__ import annotations

import base64
import dataclasses
import enum
from typing import Any, Dict, List, Optional, Tuple

from .. import models as _models

_T = "__t"   # dataclass tag
_E = "__e"   # enum tag
_B = "__b"   # bytes tag (Secret data values are bytes)
_D = "__d"   # escape tag: plain dict whose own keys collide with a tag
_RESERVED = frozenset((_T, _E, _B, _D))


def _registry() -> Dict[str, type]:
    reg: Dict[str, type] = {}
    for name in dir(_models):
        cls = getattr(_models, name)
        if isinstance(cls, type) and (
                dataclasses.is_dataclass(cls)
                or issubclass(cls, enum.Enum)):
            reg[cls.__name__] = cls
    return reg


_REGISTRY = _registry()

_MISSING = object()

#: per dataclass: ((field_name, skip_sentinel), ...) — skip_sentinel is
#: the value to omit from the wire (the field's static default, or the
#: empty container its default factory produces), or _MISSING when the
#: field must always be encoded
_ENC_PLANS: Dict[type, Tuple[Tuple[str, Any], ...]] = {}
#: per dataclass: frozenset of constructable field names
_KNOWN: Dict[type, frozenset] = {}


def _enc_plan(cls: type) -> Tuple[Tuple[str, Any], ...]:
    plan = _ENC_PLANS.get(cls)
    if plan is None:
        rows: List[Tuple[str, Any]] = []
        for f in dataclasses.fields(cls):
            sentinel: Any = _MISSING
            if f.default is not dataclasses.MISSING:
                sentinel = f.default
            elif f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
                produced = f.default_factory()  # type: ignore[misc]
                # only stable, empty containers are skippable — a
                # factory like new_uid() produces a fresh value every
                # call, which an omitted field would silently replace
                if produced == {} or produced == [] or produced == ():
                    sentinel = produced
            rows.append((f.name, sentinel))
        plan = _ENC_PLANS[cls] = tuple(rows)
    return plan


def _known(cls: type) -> frozenset:
    known = _KNOWN.get(cls)
    if known is None:
        known = _KNOWN[cls] = frozenset(
            f.name for f in dataclasses.fields(cls))
    return known


def encode(obj: Any) -> Any:
    """Model object -> JSON-able structure (sparse: default-valued
    fields are omitted; decode restores them)."""
    t = obj.__class__
    # exact-type fast path: a str-enum's class is the enum, not str, so
    # enums fall through to their tagged form below
    if obj is None or t is str or t is int or t is float or t is bool:
        return obj
    if t is dict:
        out = {k: encode(v) for k, v in obj.items()}
        if _RESERVED & out.keys():
            # a user dict (annotation/label/template) whose own keys
            # collide with a tag must not be mistaken for a tagged node
            return {_D: out}
        return out
    if t is list or t is tuple:
        return [encode(v) for v in obj]
    plan = _ENC_PLANS.get(t)
    if plan is None:
        if isinstance(obj, enum.Enum):
            return {_E: t.__name__, "v": obj.value}
        if isinstance(obj, bytes):
            return {_B: base64.b64encode(obj).decode()}
        if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
            plan = _enc_plan(t)
        elif isinstance(obj, (int, float, str, bool)):
            return obj  # bool/int/str subclasses that are not enums
        elif isinstance(obj, dict):
            out = {k: encode(v) for k, v in obj.items()}
            return {_D: out} if _RESERVED & out.keys() else out
        elif isinstance(obj, (list, tuple)):
            return [encode(v) for v in obj]
        else:
            raise TypeError(
                f"cannot encode {t.__name__} for the wire")
    fields: Dict[str, Any] = {}
    for name, sentinel in plan:
        v = getattr(obj, name)
        if sentinel is not _MISSING and (
                v is sentinel or v == sentinel):
            continue
        fields[name] = encode(v)
    return {_T: t.__name__, "f": fields}


def decode(data: Any) -> Any:
    """JSON structure -> model object (closed over the models registry).
    Fields absent from the wire regain their class defaults."""
    if isinstance(data, dict):
        tag = data.get(_T)
        if tag is not None:
            cls = _REGISTRY.get(tag)
            if cls is None or not dataclasses.is_dataclass(cls):
                raise ValueError(f"unknown model class {tag!r}")
            known = _known(cls)
            return cls(**{k: decode(v) for k, v in data["f"].items()
                          if k in known})
        etag = data.get(_E)
        if etag is not None:
            cls = _REGISTRY.get(etag)
            if cls is None or not issubclass(cls, enum.Enum):
                raise ValueError(f"unknown enum class {etag!r}")
            return cls(data["v"])
        btag = data.get(_B)
        if btag is not None:
            return base64.b64decode(btag)
        if _D in data:
            return {k: decode(v) for k, v in data[_D].items()}
        return {k: decode(v) for k, v in data.items()}
    if isinstance(data, list):
        return [decode(v) for v in data]
    return data
