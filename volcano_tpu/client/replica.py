"""Read replicas: WAL-shipped followers serving the read tier.

Millions of users means the dominant control-plane traffic is reads —
`vcctl` lists, dashboard polls, job-status watches — and until now every
one of them landed on the same process the scheduler writes through
(ROADMAP item 3). The reference absorbs that fan-out in the apiserver
tier above etcd (SURVEY §2/§5); this module is the TPU build's
equivalent, assembled from two pieces earlier PRs proved: the durable
store's totally-ordered, CRC-framed, rv-stamped WAL (PR 9) and the
router's encode-once watch fan-out (PR 10).

``ReplicaStore`` bootstraps from the primary's newest on-disk snapshot
(the ``bootstrap`` wire op), then tails the primary's WAL over the new
``ship`` wire op — sealed segments plus the live tail, streamed as
framed record batches — applying each record to an in-process mirror
store and serving ``list``/``get``/``watch``/``bulk_watch`` over the
UNCHANGED wire protocol. Staleness is explicit, never silent:

- every read response carries ``applied_rv``, the exact primary
  resource_version(s) the answer reflects;
- ``min_rv=`` on list blocks until the replica has applied that rv (or
  fails typed with ``ReplicaLagError`` after ``wait_s``) — the
  read-your-writes bound a client that just wrote to the primary needs;
- mutations (and with them fencing, leases and conditional-write
  arbitration) fail CLOSED with ``ReplicaReadOnlyError``: every write
  belongs to the primary, so scheduler correctness is untouched.

Robustness is the design center, not a footnote:

- WAL record rvs are DENSE per shard (every committed mutation appends
  exactly one record), so ``apply_record`` refuses any record that does
  not extend ``applied_rv`` by exactly one (``ReplicaGapError``) — a
  dropped or duplicated record can never be silently absorbed; the
  tailer answers with a fresh snapshot re-bootstrap, counted in
  ``volcano_replica_bootstraps_total{reason}``.
- A replica crash loses nothing anyone was promised: restart
  re-bootstraps from the newest snapshot and re-tails; watchers resume
  through the normal ``since:`` path against the rebuilt journal (its
  floor is the snapshot's per-kind rv, so marks at or past it resume
  without a resync).
- A primary crash mid-ship leaves the replica at a consistent rv prefix
  (only complete, CRC-clean frames were ever applied); the tailer
  reconnects with backoff and resumes at its applied rv once the
  primary recovers.
- A replica that falls out of the primary's retained-segment window is
  REFUSED by the ship op (``ResumeGapError`` — the same refuse-to-seed
  rule PR 10 added to the EventJournal) and degrades to a fresh
  bootstrap instead of skipping events.

Sharded primaries ship per shard: one tailer per member WAL lineage
into a mirrored shard layout, served through the router handler
(events carry shard tags, resume marks stay per-shard maps);
``applied_rv``/``min_rv`` generalize to ``{shard: rv}`` maps.

Fan-out trees (ROADMAP item 1): a replica is a composable TIER, not a
leaf. Each mirror shard keeps a bounded ring of the raw records it
applied and exposes the same ship interface the durable store does
(``ship_floor``/``add_ship_listener``/``newest_snapshot_state``), so a
replica SERVES ``ship`` and ``bootstrap`` to deeper replicas: a
depth-2 replica tails a depth-1 replica with byte-identical mirrors
(the relayed records carry the primary's dense rv stamps unchanged, so
downstream gap detection works exactly as against the primary), and a
mid-tree re-bootstrap is answered from the parent's mirror state —
the primary never hears about it. ``serve()`` announces this endpoint
up the chain (``announce_read_endpoint``), so the primary's
``topology`` response grows a ``read_endpoints`` table direct-routing
clients use to prefer the nearest replica for reads.

Fault points: ``replica_apply`` (fires before each record applies; an
armed firing DROPS the record — the continuity check detects the hole
at the next record), ``replica_apply_dup`` (fires after; an armed
firing applies the record a second time — detected immediately), and
``replica_stale_read`` (fires at the head of every ``min_rv`` wait; an
armed firing expires the block typed — ReplicaLagError — without
waiting). ``wal_ship`` lives on the primary's send seam
(client/server.py); a REPLICA serving ship fires ``ship_relay`` there
instead, so chaos can drop a relayed frame mid-tree without touching
the primary's streams.
"""

from __future__ import annotations

import collections
import json
import logging
import random
import socket
import struct
import threading
import time
from typing import Dict, List, Optional

from ..resilience.faultinject import FaultError, faults
from .codec import decode, encode
from .remote import RemoteClusterStore
from .server import (
    MAX_FRAME_BYTES, EventJournal, StoreServer, _Handler, recv_exact,
    send_frame,
)
from .sharded import ShardedClusterStore, ShardRouter, _RouterHandler
from .store import (
    KINDS, ClusterStore, ReplicaLagError, ReplicaReadOnlyError, _key,
)

log = logging.getLogger(__name__)

_MUTATING_OPS = ("create", "update", "apply", "delete", "bulk_apply")
#: default block budget for an rv-bounded list before ReplicaLagError
DEFAULT_LIST_WAIT_S = 5.0
#: tailer reconnect backoff cap (same shape as the watch-resume path)
TAIL_BACKOFF_CAP_S = 2.0
#: applied records a mirror shard retains for re-shipping downstream
#: (the replica-tier analog of the primary's retained WAL segments);
#: a child below the ring's floor re-bootstraps from THIS replica's
#: mirror state, never from the primary
SHIP_RING_CAPACITY = 4096

_READONLY = ("replica is read-only: writes (and fencing/lease/"
             "conditional-update arbitration) belong to the primary")


class ReplicaGapError(Exception):
    """A shipped record does not extend the replica's applied rv by
    exactly one — a record was lost or duplicated somewhere between the
    primary's WAL and this apply. Never served around: the tailer
    re-bootstraps from a fresh snapshot."""


class _ReplicaShard(ClusterStore):
    """The replica's mirror of one primary store (or one member shard):
    a ClusterStore that is written ONLY by ``apply_record`` (preserving
    the primary's rv stamps exactly) and whose mutating surface fails
    closed. Watch listeners, the resume journal and list/get all work
    against it unchanged.

    The mirror is also a SHIP SOURCE (fan-out trees): it retains the
    raw records it applied in a bounded ring and exposes the durable
    store's ship interface, so server._serve_ship re-serves this
    lineage to deeper replicas and ``bootstrap`` is answered from the
    mirror state itself (always complete at the applied rv — unlike
    the primary's newest-on-disk snapshot, it can never be behind a
    compaction)."""

    #: this mirror can feed a deeper replica (see server._ship_source)
    ship_capable = True

    def __init__(self):
        super().__init__()
        #: raw shipped record dicts at rv in (_ship_floor_rv, _rv],
        #: appended under self._lock at the apply commit point
        self._ship_ring: "collections.deque" = collections.deque()
        self._ship_floor_rv = 0
        self._ship_listeners: List = []

    # -- the only write path ------------------------------------------------

    def apply_record(self, rv: int, kind: str, event: str, obj,
                     rec: Optional[dict] = None) -> None:
        """Apply one shipped WAL record. Refuses (ReplicaGapError) any
        record that does not extend the applied rv by exactly one —
        WAL rvs are dense, so a jump is a lost record and a repeat is a
        duplicate, and neither may be absorbed silently. ``rec`` is the
        raw wire record: when given it enters the re-ship ring and
        fires downstream ship listeners, atomically with the apply."""
        rv = int(rv)
        with self._lock:
            if rv != self._rv + 1:
                raise ReplicaGapError(
                    f"shipped record rv {rv} does not extend applied rv "
                    f"{self._rv} (lost or duplicated record)")
            bucket = self._buckets.setdefault(kind, {})
            key = _key(obj)
            old = bucket.get(key)
            if event == "delete":
                bucket.pop(key, None)
            else:
                bucket[key] = obj
            self._rv = rv
            # update events without a known predecessor carry old=obj —
            # the in-place-update idiom live streams already exhibit
            self._notify(kind, event, obj,
                         (old if old is not None else obj)
                         if event == "update" else None)
            if rec is not None:
                self._relay(rec)

    def _relay(self, rec: dict) -> None:
        # under self._lock (the apply commit point): ring append +
        # listener fire are atomic with respect to _serve_ship's
        # registration hold, so no record can fall between a child's
        # ring replay and its live tail
        self._ship_ring.append(rec)
        if len(self._ship_ring) > SHIP_RING_CAPACITY:
            self._ship_floor_rv = int(self._ship_ring.popleft()["rv"])
        for fn in list(self._ship_listeners):
            fn(rec)

    def load_state(self, rv: int, state: Optional[dict]) -> None:
        """Replace the mirror with a bootstrap snapshot (state may be
        None: an empty primary, or one that has never compacted — the
        ship stream then replays history from rv 0). Listeners stay
        subscribed; the serving layer rebuilds its journal and kicks
        live streams so no watcher silently spans the discontinuity."""
        with self._lock:
            self._buckets = {k: {} for k in KINDS}
            self._kind_rv = {k: 0 for k in KINDS}
            if state:
                rv = int(state["rv"])
                for kind, objs in state["buckets"].items():
                    bucket = self._buckets.setdefault(kind, {})
                    for eobj in objs:
                        obj = decode(eobj)
                        bucket[_key(obj)] = obj
                for kind, krv in state["kind_rv"].items():
                    self._kind_rv[kind] = int(krv)
            self._rv = int(rv)
            # the re-ship window restarts at the snapshot: a child below
            # this floor re-bootstraps from THIS mirror's state (above),
            # never from the primary
            self._ship_ring.clear()
            self._ship_floor_rv = self._rv

    # -- the ship interface (mirror as a ship source) -----------------------

    def ship_floor(self) -> int:
        """Oldest rv the ring can resume from (exclusive). Same contract
        as the durable store's retained-segment floor."""
        with self._lock:
            return self._ship_floor_rv

    def ship_records(self, since_rv: int, live_to: int) -> List[dict]:
        """Ring records with since_rv < rv <= live_to. Caller holds the
        shard lock (server._serve_ship's registration hold)."""
        return [r for r in self._ship_ring
                if since_rv < int(r["rv"]) <= live_to]

    def add_ship_listener(self, fn) -> None:
        with self._lock:
            self._ship_listeners.append(fn)

    def remove_ship_listener(self, fn) -> None:
        with self._lock:
            try:
                self._ship_listeners.remove(fn)
            except ValueError:
                pass

    def newest_snapshot_state(self):
        """Bootstrap source for a downstream replica: the mirror state
        itself, complete at the applied rv by construction (no snapshot
        cadence to lag behind)."""
        with self._lock:
            if self._rv == 0:
                return 0, None
            state = {
                "rv": self._rv,
                "kind_rv": dict(self._kind_rv),
                "buckets": {k: [encode(o) for o in b.values()]
                            for k, b in self._buckets.items()},
            }
            return self._rv, state

    # -- mutations fail closed ----------------------------------------------

    def create(self, kind, obj, fencing=None):
        raise ReplicaReadOnlyError(_READONLY)

    def update(self, kind, obj, fencing=None):
        raise ReplicaReadOnlyError(_READONLY)

    def apply(self, kind, obj, fencing=None):
        raise ReplicaReadOnlyError(_READONLY)

    def delete(self, kind, name, namespace=None, fencing=None):
        raise ReplicaReadOnlyError(_READONLY)

    def bulk_apply(self, items, fencing=None, _sync=True):
        raise ReplicaReadOnlyError(_READONLY)


class _ReplicaShardedStore(ShardedClusterStore):
    """Mirror of a sharded primary: one _ReplicaShard per member WAL
    lineage, behind the sharded store's watch/list surface so the
    router handler serves it unchanged. Mutations fail closed at the
    top (and again at every shard, defense in depth)."""

    def _make_shard(self, i: int) -> ClusterStore:
        return _ReplicaShard()

    def create(self, kind, obj, fencing=None):
        raise ReplicaReadOnlyError(_READONLY)

    def update(self, kind, obj, fencing=None):
        raise ReplicaReadOnlyError(_READONLY)

    def apply(self, kind, obj, fencing=None):
        raise ReplicaReadOnlyError(_READONLY)

    def delete(self, kind, name, namespace=None, fencing=None):
        raise ReplicaReadOnlyError(_READONLY)

    def bulk_apply(self, items, fencing=None):
        raise ReplicaReadOnlyError(_READONLY)


# -- serving ------------------------------------------------------------------


class _ReplicaHandler(_Handler):
    """The wire protocol over a replica mirror: reads pass through (list
    already stamps ``applied_rv`` via the base dispatch), ``min_rv``
    blocks-or-fails against the replica's applied rv, and every mutating
    op is refused typed before it can touch any state."""

    def _dispatch(self, store, op: str, req: dict) -> dict:
        replica = self.server.replica  # type: ignore[attr-defined]
        if op in _MUTATING_OPS:
            raise ReplicaReadOnlyError(
                f"{_READONLY} (primary: {replica.primary_address})")
        if op in ("list", "get"):
            min_rv = req.get("min_rv")
            if min_rv is not None:
                replica.wait_applied(
                    min_rv, float(req.get("wait_s", DEFAULT_LIST_WAIT_S)))
            return _Handler._dispatch(self, store, op, req)
        if op == "store_info":
            resp = _Handler._dispatch(self, store, op, req)
            # a replica IS a valid ship source: a deeper replica's
            # handshake passes the same check the durable primary does
            resp["ship_capable"] = True
            resp["depth"] = replica.depth
            resp["upstream"] = replica.primary_address
            return resp
        if op == "bootstrap":
            replica.ship_served["bootstraps"] += 1
            try:
                from ..metrics import metrics
                metrics.replica_ship_served_bootstraps_total.inc()
            except Exception:  # noqa: BLE001 — accounting only
                pass
            return _Handler._dispatch(self, store, op, req)
        if op == "replica_info":
            return replica.info()
        if op == "announce_read_endpoint":
            resp = _Handler._dispatch(self, store, op, req)
            # relay up the chain so the PRIMARY's topology table learns
            # about endpoints announced anywhere in the tree
            replica._announce_upstream(req)
            return resp
        return _Handler._dispatch(self, store, op, req)

    def _serve_watch(self, sock, store, req) -> None:
        replica = self.server.replica  # type: ignore[attr-defined]
        replica._watcher_delta(1)
        try:
            super()._serve_watch(sock, store, req)
        finally:
            replica._watcher_delta(-1)


class _ShardedReplicaHandler(_ReplicaHandler, _RouterHandler):
    """Replica dispatch rules over the router's shard-aware watch
    serving (events tagged per shard, per-shard resume marks)."""


class ReplicaServer(StoreServer):
    """Serve a replica mirror on host:port — the unchanged wire
    protocol, reads only. ``on_rebootstrap`` rebuilds the watch-resume
    journal from the fresh snapshot floor and kicks every live
    connection: a watcher must re-enter through ``since:`` (resuming if
    its mark is inside the new window, resyncing if not) rather than
    silently span a bootstrap discontinuity."""

    handler_class = _ReplicaHandler

    def __init__(self, replica: "ReplicaStore", host: str = "127.0.0.1",
                 port: int = 0, token: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 tls_client_ca: Optional[str] = None, gate=None):
        super().__init__(replica.store, host=host, port=port, token=token,
                         tls_cert=tls_cert, tls_key=tls_key,
                         tls_client_ca=tls_client_ca, gate=gate)
        self.replica = replica
        self._server.replica = replica  # type: ignore[attr-defined]
        # a replica relaying ship fires its own chaos seam, so a test
        # can drop a mid-tree frame without touching primary streams
        self._server.ship_fault_point = "ship_relay"  # type: ignore

    def on_rebootstrap(self, shard_idx: Optional[int]) -> None:
        self.journal.close()
        self.journal = self._make_journal(self.replica.store)
        self._server.journal = self.journal  # type: ignore[attr-defined]
        self.kick_connections()

    def kick_connections(self) -> None:
        """Drop every live connection (watchers resume via ``since:``,
        requests ride the client retry rules)."""
        for sock in list(self._server.active):  # type: ignore[attr-defined]
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass


class ShardedReplicaServer(ShardRouter):
    """ReplicaServer for a sharded mirror: one endpoint, shard-tagged
    events, per-shard resume journals — the router's serving surface
    over read-only shards."""

    handler_class = _ShardedReplicaHandler

    def __init__(self, replica: "ReplicaStore", host: str = "127.0.0.1",
                 port: int = 0, token: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 tls_client_ca: Optional[str] = None, gate=None):
        super().__init__(replica.store, host=host, port=port, token=token,
                         tls_cert=tls_cert, tls_key=tls_key,
                         tls_client_ca=tls_client_ca, gate=gate)
        self.replica = replica
        self._server.replica = replica  # type: ignore[attr-defined]
        self._server.ship_fault_point = "ship_relay"  # type: ignore

    def on_rebootstrap(self, shard_idx: Optional[int]) -> None:
        # only the re-bootstrapped shard's journal restarts from the new
        # snapshot floor; the other shards' windows are still continuous
        self.journal.rebuild(shard_idx, self.replica.store.shards[shard_idx])
        self.kick_connections()

    kick_connections = ReplicaServer.kick_connections


# -- the replica process ------------------------------------------------------


def _recv_counted(sock) -> tuple:
    """recv_frame + how many wire bytes it cost (ship accounting)."""
    (length,) = struct.unpack("<I", recv_exact(sock, 4))
    if length > MAX_FRAME_BYTES:
        raise ConnectionError(f"frame length {length} exceeds cap")
    return json.loads(recv_exact(sock, length)), 4 + length


class ReplicaStore:
    """See module docstring. Lifecycle::

        replica = ReplicaStore("127.0.0.1:7000")   # bootstraps now
        replica.serve(port=7100)                   # optional read endpoint
        replica.start()                            # tailers begin
        ...
        replica.close()

    Construction performs the handshake (``store_info``) and the initial
    snapshot bootstrap, so a constructed replica can already serve its
    (possibly stale) mirror; ``start()`` begins tailing. In-process
    consumers may also use ``replica.store`` directly (list/get/watch —
    mutations fail closed)."""

    def __init__(self, primary: str, token: Optional[str] = None,
                 tls_ca: Optional[str] = None,
                 tls_cert: Optional[str] = None,
                 tls_key: Optional[str] = None,
                 connect_timeout: float = 5.0,
                 tail_backoff_cap_s: float = TAIL_BACKOFF_CAP_S):
        self.primary_address = primary
        self.tail_backoff_cap_s = float(tail_backoff_cap_s)
        self._client = RemoteClusterStore(
            primary, connect_timeout=connect_timeout, token=token,
            tls_ca=tls_ca, tls_cert=tls_cert, tls_key=tls_key,
            retry_attempts=8, retry_cap_s=2.0)
        info = self._client._request({"op": "store_info"})
        if not (info.get("durable") or info.get("ship_capable")):
            raise RuntimeError(
                f"primary {primary} is not durable (no --store-data-dir): "
                "there is no WAL to ship, so it cannot feed a replica")
        self.n_shards = int(info.get("shards", 1))
        #: 1 when tailing the primary, parent depth + 1 down a tree
        self.depth = int(info.get("depth", 0) or 0) + 1
        self.store = (_ReplicaShard() if self.n_shards == 1
                      else _ReplicaShardedStore(self.n_shards))
        self.server: Optional[StoreServer] = None
        #: re/bootstrap count per reason (initial/out_of_window/apply_gap)
        self.bootstraps: "collections.Counter" = collections.Counter()
        #: ship/bootstrap traffic THIS replica absorbed for its children
        #: (streams/records/bootstraps — the primary never sees it)
        self.ship_served: "collections.Counter" = collections.Counter()
        self._ship_streams = 0
        #: last primary rv seen on each shard's ship stream (lag floor)
        self.primary_rv: Dict[int, int] = {}
        self.ship_bytes = 0
        self._cv = threading.Condition()
        self._closed = threading.Event()
        self._threads: List[threading.Thread] = []
        self._tail_socks: List[socket.socket] = []
        self._sock_lock = threading.Lock()
        self._watchers = 0
        self._last_applied_ts: Dict[int, float] = {}
        try:
            from ..metrics import metrics
            metrics.replica_upstream_depth.set(self.depth)
        except Exception:  # noqa: BLE001 — accounting only
            pass
        for idx in range(self.n_shards):
            self._bootstrap(idx, "initial")

    # -- shards ---------------------------------------------------------------

    def _shard(self, idx: int) -> _ReplicaShard:
        if self.n_shards == 1:
            return self.store  # type: ignore[return-value]
        return self.store.shards[idx]  # type: ignore[attr-defined]

    def applied_rv(self):
        """The primary rv(s) this mirror reflects: a scalar, or the
        per-shard map against a sharded primary."""
        if self.n_shards == 1:
            return self.store._rv
        return {str(i): s._rv
                for i, s in enumerate(self.store.shards)}  # type: ignore

    # -- rv-bounded staleness -------------------------------------------------

    def _covers(self, min_rv) -> bool:
        if isinstance(min_rv, dict):
            return all(self._shard(int(i))._rv >= int(rv)
                       for i, rv in min_rv.items())
        if self.n_shards != 1:
            raise RuntimeError(
                "scalar min_rv against a sharded replica is ambiguous "
                "(each shard owns its own rv sequence); pass a "
                "{shard: rv} map")
        return self.store._rv >= int(min_rv)

    def wait_applied(self, min_rv, wait_s: float = DEFAULT_LIST_WAIT_S):
        """Block until the mirror has applied ``min_rv`` (scalar, or
        ``{shard: rv}``); raise ReplicaLagError past ``wait_s``."""
        try:
            faults.fire("replica_stale_read")
        except FaultError:
            # injected staleness: the block expires typed immediately,
            # driving the caller's primary-fallback path
            raise ReplicaLagError(
                f"injected stale read: replica at applied_rv "
                f"{self.applied_rv()} refused min_rv {min_rv}")
        deadline = time.monotonic() + float(wait_s)
        with self._cv:
            while not self._covers(min_rv):
                left = deadline - time.monotonic()
                if left <= 0 or self._closed.is_set():
                    raise ReplicaLagError(
                        f"replica at applied_rv {self.applied_rv()} did "
                        f"not reach min_rv {min_rv} within {wait_s}s")
                self._cv.wait(min(left, 0.5))

    # -- lifecycle ------------------------------------------------------------

    def serve(self, host: str = "127.0.0.1", port: int = 0,
              token: Optional[str] = None,
              tls_cert: Optional[str] = None, tls_key: Optional[str] = None,
              tls_client_ca: Optional[str] = None,
              gate=None) -> StoreServer:
        cls = ReplicaServer if self.n_shards == 1 else ShardedReplicaServer
        self.server = cls(self, host=host, port=port, token=token,
                          tls_cert=tls_cert, tls_key=tls_key,
                          tls_client_ca=tls_client_ca, gate=gate).start()
        self._announce_self()
        return self.server

    def _announce_self(self) -> None:
        """Best-effort: register this read endpoint up the chain so the
        primary's ``topology`` table can hand it to direct-routing
        clients. Discovery is advisory — a failed announce degrades to
        clients reading the primary, never to an error."""
        if self.server is None:
            return
        self._announce_upstream({"endpoint": self.server.address,
                                 "depth": self.depth,
                                 "shards": self.n_shards})

    def _announce_upstream(self, req: dict) -> None:
        try:
            self._client._request({
                "op": "announce_read_endpoint",
                "endpoint": req["endpoint"],
                "depth": int(req.get("depth", 1)),
                "shards": int(req.get("shards", 1))})
        except Exception:  # noqa: BLE001 — discovery is advisory
            log.debug("announce_read_endpoint upstream failed",
                      exc_info=True)

    def start(self) -> "ReplicaStore":
        for idx in range(self.n_shards):
            t = threading.Thread(target=self._tail, args=(idx,),
                                 daemon=True, name=f"replica-tail-{idx}")
            t.start()
            self._threads.append(t)
        return self

    def close(self) -> None:
        self._closed.set()
        with self._cv:
            self._cv.notify_all()
        with self._sock_lock:
            socks, self._tail_socks = self._tail_socks, []
        for sock in socks:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        if self.server is not None:
            self.server.stop()
        self._client.close()
        for t in self._threads:
            t.join(timeout=5)

    # -- bootstrap ------------------------------------------------------------

    def _bootstrap(self, idx: int, reason: str) -> None:
        """(Re)seed one shard's mirror from the primary's newest
        snapshot. Every call is counted by reason — a hole NEVER closes
        silently."""
        resp = self._client._request({"op": "bootstrap", "shard": idx})
        with self.store.locked():
            self._shard(idx).load_state(int(resp["rv"]), resp.get("state"))
        self.bootstraps[reason] += 1
        try:
            from ..metrics import metrics
            metrics.replica_bootstraps_total.inc(labels={"reason": reason})
            metrics.replica_applied_rv.set(
                self._shard(idx)._rv, labels={"shard": str(idx)})
        except Exception:  # noqa: BLE001 — accounting only
            pass
        with self._cv:
            self._cv.notify_all()
        if self.server is not None:
            self.server.on_rebootstrap(idx if self.n_shards > 1 else None)
        log.log(logging.INFO if reason == "initial" else logging.WARNING,
                "replica shard %d bootstrapped (%s) at rv %d",
                idx, reason, self._shard(idx)._rv)

    # -- the tailer -----------------------------------------------------------

    def _tail(self, idx: int) -> None:
        delay = 0.05
        while not self._closed.is_set():
            sock = None
            try:
                sock = self._client._connect()
                with self._sock_lock:
                    self._tail_socks.append(sock)
                send_frame(sock, {"op": "ship", "shard": idx,
                                  "since_rv": self._shard(idx)._rv})
                resp, _ = _recv_counted(sock)
                if resp.get("ok") is False:
                    if resp.get("error") == "ResumeGapError":
                        # fell out of the retained-segment window: the
                        # hole closes with a fresh bootstrap, never by
                        # skipping ahead
                        self._drop_sock(sock)
                        sock = None
                        self._bootstrap(idx, "out_of_window")
                        continue
                    raise ConnectionError(
                        f"ship refused: {resp.get('message')}")
                delay = 0.05
                while not self._closed.is_set():
                    msg, nbytes = _recv_counted(sock)
                    self.ship_bytes += nbytes
                    stream = msg.get("stream")
                    prv = msg.get("prv", msg.get("rv"))
                    if stream == "wal":
                        self._apply_batch(idx, msg["recs"])
                    if prv is not None:
                        self.primary_rv[idx] = int(prv)
                    self._export_lag(idx, nbytes)
            except ReplicaGapError as e:
                log.error("replica shard %d detected an rv gap: %s — "
                          "re-bootstrapping", idx, e)
                self._drop_sock(sock)
                sock = None
                if not self._closed.is_set():
                    self._bootstrap(idx, "apply_gap")
                continue
            except (ConnectionError, OSError, ValueError):
                # primary gone (or link dropped mid-segment): only
                # complete CRC-clean frames were applied, so the mirror
                # sits at a consistent rv prefix — back off, reconnect,
                # resume shipping at the applied rv
                self._drop_sock(sock)
                sock = None
                if self._closed.is_set():
                    return
                self._closed.wait(delay * (0.5 + random.random()))
                delay = min(delay * 2.0, self.tail_backoff_cap_s)
            finally:
                self._drop_sock(sock)

    def _drop_sock(self, sock) -> None:
        if sock is None:
            return
        with self._sock_lock:
            try:
                self._tail_socks.remove(sock)
            except ValueError:
                pass
        try:
            sock.close()
        except OSError:
            pass

    def _apply_batch(self, idx: int, recs: List[dict]) -> None:
        shard = self._shard(idx)
        with self.store.locked():
            for rec in recs:
                try:
                    faults.fire("replica_apply")
                except FaultError:
                    # injected drop: the record is lost between wire and
                    # mirror; the next record's continuity check refuses
                    continue
                shard.apply_record(rec["rv"], rec["kind"], rec["event"],
                                   decode(rec["obj"]), rec=rec)
                ts = rec.get("ts")
                if ts is not None:
                    self._last_applied_ts[idx] = float(ts)
                try:
                    faults.fire("replica_apply_dup")
                except FaultError:
                    # injected duplicate: refused immediately (rv repeat)
                    shard.apply_record(rec["rv"], rec["kind"],
                                       rec["event"], decode(rec["obj"]))
        with self._cv:
            self._cv.notify_all()

    # -- observability --------------------------------------------------------

    def lag_records(self, idx: int = 0) -> Optional[int]:
        prv = self.primary_rv.get(idx)
        if prv is None:
            return None
        return max(0, prv - self._shard(idx)._rv)

    def lag_seconds(self, idx: int = 0) -> Optional[float]:
        lag = self.lag_records(idx)
        if lag == 0:
            return 0.0
        ts = self._last_applied_ts.get(idx)
        if lag is None or ts is None:
            return None
        return max(0.0, time.time() - ts)

    def info(self) -> dict:
        """The ``replica_info`` wire response: this hop's place in the
        tree, its lag, and the ship traffic it absorbed downstream.
        vcctl walks ``upstream`` hop by hop to print the chain."""
        per_shard = {}
        for idx in range(self.n_shards):
            per_shard[str(idx)] = {
                "applied_rv": self._shard(idx)._rv,
                "lag_records": self.lag_records(idx),
                "lag_seconds": self.lag_seconds(idx),
            }
        return {
            "ok": True,
            "upstream": self.primary_address,
            "depth": self.depth,
            "shards": self.n_shards,
            "applied_rv": self.applied_rv(),
            "per_shard": per_shard,
            "bootstraps": dict(self.bootstraps),
            "watchers": self._watchers,
            "ship_served": dict(self.ship_served),
        }

    def _export_lag(self, idx: int, nbytes: int) -> None:
        try:
            from ..metrics import metrics
            labels = {"shard": str(idx)}
            applied = self._shard(idx)._rv
            metrics.replica_applied_rv.set(applied, labels=labels)
            prv = self.primary_rv.get(idx)
            if prv is not None:
                metrics.replica_upstream_rv.set(prv, labels=labels)
            lag = self.lag_records(idx)
            if lag is not None:
                metrics.replica_lag_records.set(lag, labels=labels)
                ts = self._last_applied_ts.get(idx)
                metrics.replica_lag_seconds.set(
                    max(0.0, time.time() - ts) if lag > 0 and ts is not None
                    else 0.0, labels=labels)
            metrics.replica_ship_bytes_total.inc(
                nbytes, labels=labels)
        except Exception:  # noqa: BLE001 — accounting only
            pass

    def _watcher_delta(self, d: int) -> None:
        with self._cv:
            self._watchers += d
            n = self._watchers
        try:
            from ..metrics import metrics
            metrics.replica_watchers.set(n)
        except Exception:  # noqa: BLE001
            pass

    def _ship_stream_delta(self, d: int) -> None:
        with self._cv:
            self._ship_streams += d
            n = self._ship_streams
        try:
            from ..metrics import metrics
            metrics.replica_ship_served_streams.set(n)
        except Exception:  # noqa: BLE001
            pass
