"""Session: snapshot-backed per-cycle scheduling state + plugin dispatch.

Reimplements reference framework/{session.go:37-429, session_plugins.go:26-591}:
19 plugin-fn registries with tiered dispatch — first tier with an answer wins
for orders and victims (victims additionally intersected within a tier),
vetoes short-circuit for ready/pipelined/valid/enqueueable, and scores sum.

The TPU twist: the Session also carries the flattened device-array view of
the snapshot (built lazily by volcano_tpu.ops.SnapshotArrays) so actions can
hand the whole decision problem to the solver kernel, then replay results
through exactly these Allocate/Pipeline/Evict primitives.
"""

from __future__ import annotations

import logging
import uuid
from typing import Callable, Dict, List, Optional

from ..api import (
    ClusterInfo, JobInfo, NodeInfo, QueueInfo, Resource, TaskInfo,
    TaskStatus, allocated_status,
)
from ..models import PodGroupPhase
from .event import Event, EventHandler
from .interface import ValidateResult

log = logging.getLogger(__name__)

#: registry-name -> PluginOption enable-flag attribute (None = always on)
FN_REGISTRIES = {
    "job_order_fns": "enabled_job_order",
    "queue_order_fns": "enabled_queue_order",
    "task_order_fns": "enabled_task_order",
    "namespace_order_fns": "enabled_namespace_order",
    "job_ready_fns": "enabled_job_ready",
    "job_pipelined_fns": "enabled_job_pipelined",
    "job_valid_fns": None,
    "job_enqueueable_fns": None,
    "predicate_fns": "enabled_predicate",
    "best_node_fns": "enabled_best_node",
    "node_order_fns": "enabled_node_order",
    "batch_node_order_fns": "enabled_node_order",
    "node_map_fns": "enabled_node_order",
    "node_reduce_fns": "enabled_node_order",
    "preemptable_fns": "enabled_preemptable",
    "reclaimable_fns": "enabled_reclaimable",
    "overused_fns": None,
    "target_job_fns": "enabled_target_job",
    "reserved_nodes_fns": "enabled_reserved_nodes",
}


def _enabled(plugin_option, flag_attr: Optional[str]) -> bool:
    if flag_attr is None:
        return True
    v = getattr(plugin_option, flag_attr, None)
    return True if v is None else bool(v)


class Session:
    def __init__(self, cache, snapshot: ClusterInfo):
        self.uid = str(uuid.uuid4())
        self.cache = cache
        self.jobs: Dict[str, JobInfo] = snapshot.jobs
        self.nodes: Dict[str, NodeInfo] = snapshot.nodes
        self.queues: Dict[str, QueueInfo] = snapshot.queues
        self.namespace_info = snapshot.namespace_info

        self.tiers = []          # List[conf.Tier]
        self.configurations = []  # per-action args
        self.plugins = {}        # name -> Plugin instance

        # status fingerprint of every PodGroup at session open; the job
        # updater diffs end-of-session status against this to decide writes
        # (job_updater.go:95-100 ssn.podGroupStatus). A significance tuple
        # replaces the earlier per-field status copy: same diff answer,
        # ~3x cheaper at 1k jobs/cycle (close_session's floor)
        self.pod_group_status = {
            uid: job.pod_group.status.fingerprint()
            for uid, job in self.jobs.items() if job.pod_group is not None
        }
        self._total_allocatable: Optional[Resource] = None
        # jobs whose podgroup conditions changed significantly this
        # session (update_pod_group_condition); one of the job updater's
        # dirty signals
        self._conditions_touched = set()

        for reg in FN_REGISTRIES:
            setattr(self, reg, {})
        self.event_handlers: List[EventHandler] = []
        # memoized _tier_fns lists (invalidated by _add): dispatchers run
        # O(tasks) times per cycle, so rebuilding the tier walk each call
        # dominates the host profile at 10k tasks
        self._tier_cache: Dict[str, list] = {}
        # optional per-plugin sort KEY extractors mirroring the pairwise
        # order fns: when every active provider of an order registry also
        # registered a key, actions may sort once by composite key instead
        # of O(n log n) comparator dispatches (solver-mode collection only
        # — the host loop needs live comparators)
        self.order_key_fns: Dict[str, Dict[str, Callable]] = {}
        # per-plugin key CONTEXT extractors (add_order_key_context_fn):
        # a key fn that reads state beyond the item itself declares that
        # outside state here so the cross-session OrderCache can tell when
        # cached keys of UNCHANGED items went stale (drf: cluster total;
        # priority: the priority-class table)
        self.order_key_context_fns: Dict[str, Dict[str, Callable]] = {}

        # TPU seam: plugins contribute scalar weights for the on-device
        # scoring families here instead of per-(task,node) callbacks; the
        # allocate action feeds them to ops.solve_allocate
        from ..ops.arrays import ScoreParams
        self.score_params = ScoreParams()
        self.solver_options: Dict[str, object] = {}
        # session-side mutation odometer: bumped by every allocate/
        # pipeline/evict applied to the session's clones (fire sites +
        # statement records). The allocate action reads it before its
        # flatten — a non-zero count means an earlier action mutated the
        # flatten inputs OUTSIDE the event ledger's sight (e.g. a conf
        # ordering preempt before allocate), so the event-sourced fast
        # path must stand down for this cycle
        self._mutation_ops = 0
        self.flatten_cache = getattr(cache, "flatten_cache", None)
        # event-sourced ordering inputs (ops.ordering.OrderCache): the
        # allocate action's collection pass patches only event-dirty jobs;
        # preempt/reclaim reuse its per-job sorted pending lists
        self.order_cache = getattr(cache, "order_cache", None)
        self.evict_flatten_caches = getattr(cache, "evict_flatten_caches",
                                            None) or {}
        self.device_cache = getattr(cache, "device_cache", None)
        # node-axis sharded arena + --solver-mode routing preference (the
        # allocate action builds the arena lazily and writes it back to
        # the cache so it persists across sessions)
        self.sharded_device_cache = getattr(cache, "sharded_device_cache",
                                            None)
        self.solver_mode = getattr(cache, "solver_mode", None)
        self.sharded_byte_budget = getattr(cache, "sharded_byte_budget", 0)
        self.sidecar = getattr(cache, "sidecar", None)
        # compile-and-dispatch pipeline knobs (ops.precompile): background
        # bucket pre-warm and the allocate action's dispatch/collect
        # overlap (False = strictly serial solve for parity testing)
        self.prewarmer = getattr(cache, "prewarmer", None)
        self.pipeline_solver = getattr(cache, "pipeline_solver", True)
        # resilience seams: the device-path circuit breaker (installed on
        # the cache by the Scheduler; consumed by allocate/evict_solver
        # for the device -> host-oracle degradation ladder), plus the
        # open-statement ledger + action epochs the scheduler's per-action
        # containment uses to roll back a hung or throwing action's
        # uncommitted transactions (see resilience/watchdog.py)
        self.breaker = getattr(cache, "breaker", None)
        self._open_statements: Dict[int, object] = {}
        self._action_epoch = 0
        self._contained_epochs: set = set()
        # decision-trace seam (sim.recorder.DecisionRecorder): when the
        # cache carries a recorder, close_session hands it the finished
        # session so pipeline statements and per-job FitErrors reach the
        # trace; binds/evicts are captured at the effector boundary
        # (cache.RecordingBinder/RecordingEvictor)
        self.decision_recorder = getattr(cache, "decision_recorder", None)

    # ------------------------------------------------------------------
    # registration API used by plugins (session_plugins.go:26-118)
    # ------------------------------------------------------------------

    def _add(self, registry: str, name: str, fn: Callable) -> None:
        getattr(self, registry)[name] = fn
        self._tier_cache.pop(registry, None)

    def add_order_key_fn(self, registry: str, name: str, fn: Callable) -> None:
        """Register a sort-key extractor equivalent to plugin ``name``'s
        pairwise comparator in ``registry`` (e.g. "job_order_fns"):
        fn(item) -> value such that comparator(l, r) < 0 iff fn(l) < fn(r).
        Keys must be static for the duration of a solver-mode collection.

        Cross-session contract (ops.ordering.OrderCache): a key must be a
        pure function of the item's own version-gated state; a key that
        also reads anything else (cluster totals, config tables) MUST
        declare that state via add_order_key_context_fn, or cached orders
        can go silently stale."""
        self.order_key_fns.setdefault(registry, {})[name] = fn

    def add_order_key_context_fn(self, registry: str, name: str,
                                 fn: Callable) -> None:
        """Declare the outside state plugin ``name``'s key extractor in
        ``registry`` depends on: fn() -> hashable whose value changes
        whenever that state changes. The OrderCache compares contexts
        every cycle and falls back to the full sort when any moved."""
        self.order_key_context_fns.setdefault(registry, {})[name] = fn

    def composite_order_key(self, registry: str) -> Optional[Callable]:
        """A key(item) -> tuple covering every active provider of
        ``registry`` in tier order, or None when some provider has no
        registered key (callers fall back to comparator sorting)."""
        keyfns = []
        reg_keys = self.order_key_fns.get(registry, {})
        for _, name, _ in self._tier_fns(registry):
            kf = reg_keys.get(name)
            if kf is None:
                return None
            keyfns.append(kf)
        return lambda item: tuple(kf(item) for kf in keyfns)

    def full_order_key(self, registry: str,
                       ct_of: Callable = None) -> Optional[Callable]:
        """Composite plugin key + the creation-timestamp/uid tiebreak that
        the comparator dispatchers apply after plugin ties (job_order_fn /
        task_order_fn), as ONE key function; None when some provider has
        no registered key."""
        key = self.composite_order_key(registry)
        if key is None:
            return None
        if ct_of is None:
            ct_of = lambda item: item.creation_timestamp  # noqa: E731

        def full_key(item):
            ct = ct_of(item)
            return (key(item), ct is not None, ct or 0, item.uid)

        return full_key

    def keyed_job_queue_factory(self) -> Optional[Callable]:
        """Factory for KeySortedQueue job queues, or None when a job-order
        plugin lacks a key and callers must keep comparator
        PriorityQueues."""
        from ..utils import KeySortedQueue
        full_key = self.full_order_key("job_order_fns")
        if full_key is None:
            return None
        return lambda: KeySortedQueue(full_key)

    def add_job_order_fn(self, name, fn): self._add("job_order_fns", name, fn)
    def add_queue_order_fn(self, name, fn): self._add("queue_order_fns", name, fn)
    def add_task_order_fn(self, name, fn): self._add("task_order_fns", name, fn)
    def add_namespace_order_fn(self, name, fn): self._add("namespace_order_fns", name, fn)
    def add_job_ready_fn(self, name, fn): self._add("job_ready_fns", name, fn)
    def add_job_pipelined_fn(self, name, fn): self._add("job_pipelined_fns", name, fn)
    def add_job_valid_fn(self, name, fn): self._add("job_valid_fns", name, fn)
    def add_job_enqueueable_fn(self, name, fn): self._add("job_enqueueable_fns", name, fn)
    def add_predicate_fn(self, name, fn): self._add("predicate_fns", name, fn)
    def add_best_node_fn(self, name, fn): self._add("best_node_fns", name, fn)
    def add_node_order_fn(self, name, fn): self._add("node_order_fns", name, fn)
    def add_batch_node_order_fn(self, name, fn): self._add("batch_node_order_fns", name, fn)
    def add_node_map_fn(self, name, fn): self._add("node_map_fns", name, fn)
    def add_node_reduce_fn(self, name, fn): self._add("node_reduce_fns", name, fn)
    def add_preemptable_fn(self, name, fn): self._add("preemptable_fns", name, fn)
    def add_reclaimable_fn(self, name, fn): self._add("reclaimable_fns", name, fn)
    def add_overused_fn(self, name, fn): self._add("overused_fns", name, fn)
    def add_target_job_fn(self, name, fn): self._add("target_job_fns", name, fn)
    def add_reserved_nodes_fn(self, name, fn): self._add("reserved_nodes_fns", name, fn)

    def add_event_handler(self, eh: EventHandler) -> None:
        self.event_handlers.append(eh)

    # ------------------------------------------------------------------
    # tier iteration helper
    # ------------------------------------------------------------------

    def _tier_fns(self, registry: str):
        """(tier_index, plugin_name, fn) for enabled plugins holding a fn in
        this registry, in tier order. Memoized: dispatchers call this per
        comparison/task, and the tier walk itself was ~15% of a 10k-task
        cycle before caching (_add invalidates)."""
        cached = self._tier_cache.get(registry)
        if cached is None:
            flag = FN_REGISTRIES[registry]
            fns = getattr(self, registry)
            cached = [
                (ti, opt.name, fns[opt.name])
                for ti, tier in enumerate(self.tiers)
                for opt in tier.plugins
                if _enabled(opt, flag) and opt.name in fns
            ]
            self._tier_cache[registry] = cached
        return cached

    # ------------------------------------------------------------------
    # dispatchers (session_plugins.go:120-591)
    # ------------------------------------------------------------------

    def _compare_dispatch(self, registry: str, l, r) -> int:
        for _, _, fn in self._tier_fns(registry):
            j = fn(l, r)
            if j != 0:
                return j
        return 0

    def job_order_fn(self, l: JobInfo, r: JobInfo) -> bool:
        j = self._compare_dispatch("job_order_fns", l, r)
        if j != 0:
            return j < 0
        if l.creation_timestamp == r.creation_timestamp:
            return l.uid < r.uid
        return l.creation_timestamp < r.creation_timestamp

    def queue_order_fn(self, l: QueueInfo, r: QueueInfo) -> bool:
        j = self._compare_dispatch("queue_order_fns", l, r)
        if j != 0:
            return j < 0
        lt = l.queue.creation_timestamp
        rt = r.queue.creation_timestamp
        if lt == rt:
            return l.uid < r.uid
        return lt < rt

    def task_compare_fns(self, l: TaskInfo, r: TaskInfo) -> int:
        return self._compare_dispatch("task_order_fns", l, r)

    def task_order_fn(self, l: TaskInfo, r: TaskInfo) -> bool:
        j = self.task_compare_fns(l, r)
        if j != 0:
            return j < 0
        if l.pod.creation_timestamp == r.pod.creation_timestamp:
            return l.uid < r.uid
        return l.pod.creation_timestamp < r.pod.creation_timestamp

    def namespace_order_fn(self, l: str, r: str) -> bool:
        j = self._compare_dispatch("namespace_order_fns", l, r)
        if j != 0:
            return j < 0
        return l < r

    def _victims_dispatch(self, registry: str, claimer, claimees):
        """Intersect candidate lists across plugins; return at the end of the
        first tier whose running intersection is non-empty.

        The intersection accumulator is NOT reset between tiers
        (session_plugins.go:121-160: `init` persists) — once any fn returns
        no victims, every later tier intersects against the empty set. In
        practice this means e.g. reclaim only yields victims when the
        first tier's gang fn (priority-based) approves them, which is why
        the reference's positive reclaim e2e cases all use high-vs-low
        priority classes."""
        victims = None
        for _, group in _group_by_tier(self._tier_fns(registry)):
            for _, _, fn in group:
                candidates = fn(claimer, claimees)
                if victims is None:
                    victims = list(candidates)
                else:
                    cand_uids = {c.uid for c in candidates}
                    victims = [v for v in victims if v.uid in cand_uids]
            if victims:
                return victims
        return []

    def preemptable(self, preemptor: TaskInfo, preemptees: List[TaskInfo]):
        return self._victims_dispatch("preemptable_fns", preemptor, preemptees)

    def reclaimable(self, reclaimer: TaskInfo, reclaimees: List[TaskInfo]):
        return self._victims_dispatch("reclaimable_fns", reclaimer, reclaimees)

    def overused(self, queue: QueueInfo) -> bool:
        return any(fn(queue) for _, _, fn in self._tier_fns("overused_fns"))

    def job_ready(self, job: JobInfo) -> bool:
        return all(fn(job) for _, _, fn in self._tier_fns("job_ready_fns"))

    def job_pipelined(self, job: JobInfo) -> bool:
        return all(fn(job) for _, _, fn in self._tier_fns("job_pipelined_fns"))

    def job_valid(self, job: JobInfo) -> Optional[ValidateResult]:
        for _, _, fn in self._tier_fns("job_valid_fns"):
            vr = fn(job)
            if vr is not None and not vr.passed:
                return vr
        return None

    def job_enqueueable(self, job: JobInfo) -> bool:
        return all(fn(job) for _, _, fn in self._tier_fns("job_enqueueable_fns"))

    def target_job(self, jobs: List[JobInfo]) -> Optional[JobInfo]:
        for _, _, fn in self._tier_fns("target_job_fns"):
            return fn(jobs)
        return None

    def reserved_nodes(self) -> None:
        for _, _, fn in self._tier_fns("reserved_nodes_fns"):
            fn()

    def predicate_fn(self, task: TaskInfo, node: NodeInfo) -> None:
        """Raises FitError-carrying exception on failure (error = veto)."""
        for _, _, fn in self._tier_fns("predicate_fns"):
            fn(task, node)

    def best_node_fn(self, task: TaskInfo, node_scores) -> Optional[NodeInfo]:
        for _, _, fn in self._tier_fns("best_node_fns"):
            best = fn(task, node_scores)
            if best is not None:
                return best
        return None

    def node_order_fn(self, task: TaskInfo, node: NodeInfo) -> float:
        """Sum of per-plugin scores (session_plugins.go NodeOrderFn)."""
        return sum(fn(task, node) for _, _, fn in self._tier_fns("node_order_fns"))

    def batch_node_order_fn(self, task: TaskInfo, nodes: List[NodeInfo]):
        score: Dict[str, float] = {n.name: 0.0 for n in nodes}
        for _, _, fn in self._tier_fns("batch_node_order_fns"):
            per_node = fn(task, nodes)
            for name, s in per_node.items():
                score[name] = score.get(name, 0.0) + s
        return score

    def node_order_map_fn(self, task: TaskInfo, node: NodeInfo):
        """Per-plugin map scores: returns (plugin->score dict, sum of
        priority scores)."""
        node_score_map: Dict[str, float] = {}
        total = 0.0
        for _, name, fn in self._tier_fns("node_map_fns"):
            score = fn(task, node)
            node_score_map[name] = score
            total += score
        return node_score_map, total

    def node_order_reduce_fn(self, task: TaskInfo, plugin_node_scores):
        """Reduce phase: plugin -> {node -> score} maps reduced to node sums."""
        out: Dict[str, float] = {}
        reduce_fns = dict(
            (name, fn) for _, name, fn in self._tier_fns("node_reduce_fns"))
        for plugin, node_scores in plugin_node_scores.items():
            rf = reduce_fns.get(plugin)
            scores = rf(task, node_scores) if rf is not None else node_scores
            for node_name, s in scores.items():
                out[node_name] = out.get(node_name, 0.0) + s
        return out

    # ------------------------------------------------------------------
    # state mutation (session.go:214-378)
    # ------------------------------------------------------------------

    def total_allocatable(self) -> Resource:
        """Cluster-wide allocatable, summed once per session — drf and
        proportion each walked all nodes for the same total, which at 2k
        nodes was a measurable slice of the steady-state cycle. Callers
        must not mutate the returned Resource (clone first)."""
        t = self._total_allocatable
        if t is None:
            t = Resource.sum_of(
                n.allocatable for n in self.nodes.values())
            self._total_allocatable = t
        return t

    def statement(self, defer_events: bool = False):
        from .statement import Statement
        stmt = Statement(self, defer_events=defer_events)
        # ledger for containment sweeps; commit/discard remove themselves
        self._open_statements[id(stmt)] = stmt
        return stmt

    def discard_open_statements(self) -> int:
        """Containment sweep: discard every statement that was opened but
        neither committed nor discarded, newest first — a contained
        (throwing or timed-out) action's in-flight transactions must not
        leak half-applied session state into the rest of the cycle.
        Returns the number of statements that actually carried ops."""
        stmts = list(self._open_statements.values())
        self._open_statements.clear()
        n = 0
        for stmt in reversed(stmts):
            try:
                if stmt.operations:
                    n += 1
                stmt.discard()
            except Exception:  # noqa: BLE001 — sweep every statement
                log.exception("failed to discard a contained statement")
        return n

    def _fire_allocate(self, task: TaskInfo) -> None:
        self._mutation_ops += 1
        for eh in self.event_handlers:
            if eh.allocate_func is not None:
                eh.allocate_func(Event(task))

    def _fire_deallocate(self, task: TaskInfo) -> None:
        self._mutation_ops += 1
        for eh in self.event_handlers:
            if eh.deallocate_func is not None:
                eh.deallocate_func(Event(task))

    def _fire_allocate_batch(self, tasks: list) -> None:
        """Fire allocate events for many tasks at once; handlers with a
        batch form get one call, others get the per-task loop."""
        if not tasks:
            return
        self._mutation_ops += len(tasks)
        for eh in self.event_handlers:
            if eh.batch_allocate_func is not None:
                eh.batch_allocate_func(tasks)
            elif eh.allocate_func is not None:
                for t in tasks:
                    eh.allocate_func(Event(t))

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job} when pipelining")
        job.update_task_status(task, TaskStatus.PIPELINED)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        node.add_task(task)
        self._fire_allocate(task)

    def allocate(self, task: TaskInfo, hostname: str) -> None:
        """Assign in-session; auto-dispatch the whole job once JobReady
        (session.go:255-311)."""
        self.cache.allocate_volumes(task, hostname)
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        job.update_task_status(task, TaskStatus.ALLOCATED)
        task.node_name = hostname
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to find node {hostname}")
        node.add_task(task)
        self._fire_allocate(task)

        if self.job_ready(job):
            for t in list(job.task_status_index.get(TaskStatus.ALLOCATED, {}).values()):
                self.dispatch(t)

    def dispatch(self, task: TaskInfo) -> None:
        self.cache.bind_volumes(task)
        self.cache.bind(task, task.node_name)
        job = self.jobs.get(task.job)
        if job is None:
            raise KeyError(f"failed to find job {task.job}")
        job.update_task_status(task, TaskStatus.BINDING)

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        self.cache.evict(reclaimee, reason)
        job = self.jobs.get(reclaimee.job)
        if job is None:
            raise KeyError(f"failed to find job {reclaimee.job}")
        job.update_task_status(reclaimee, TaskStatus.RELEASING)
        node = self.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        self._fire_deallocate(reclaimee)

    def update_pod_group_condition(self, job_info: JobInfo, cond) -> None:
        job = self.jobs.get(job_info.uid)
        if job is None:
            raise KeyError(f"failed to find job {job_info.uid}")
        conds = job.pod_group.status.conditions
        for i, c in enumerate(conds):
            if c.type == cond.type:
                # only a significant change dirties the job for the
                # updater — same significance rule as
                # PodGroupStatus.fingerprint() (transition_id/time don't
                # count), so gang's steady per-cycle re-post of an
                # identical Scheduled condition doesn't force 1k no-op
                # recomputes
                if (c.status, c.reason, c.message) != (
                        cond.status, cond.reason, cond.message):
                    self._conditions_touched.add(job.uid)
                conds[i] = cond
                return
        self._conditions_touched.add(job.uid)
        conds.append(cond)

    def __str__(self) -> str:
        return (f"Session {self.uid}: jobs={len(self.jobs)} "
                f"nodes={len(self.nodes)}")


def _group_by_tier(it):
    """Group (tier, name, fn) triples by tier index preserving order."""
    groups: Dict[int, list] = {}
    for t, name, fn in it:
        groups.setdefault(t, []).append((t, name, fn))
    return sorted(groups.items())


def job_status(ssn: Session, job: JobInfo):
    """Recompute PodGroup status from session state (session.go:166-205)."""
    from ..models import POD_GROUP_UNSCHEDULABLE_TYPE

    pg = job.pod_group
    status = pg.status
    unschedulable = any(
        c.type == POD_GROUP_UNSCHEDULABLE_TYPE and c.status == "True"
        and c.transition_id == ssn.uid
        for c in status.conditions)

    if job.task_status_index.get(TaskStatus.RUNNING) and unschedulable:
        status.phase = PodGroupPhase.UNKNOWN
    else:
        allocated = sum(
            len(tasks) for st, tasks in job.task_status_index.items()
            if allocated_status(st) or st == TaskStatus.SUCCEEDED)
        if allocated >= pg.spec.min_member:
            status.phase = PodGroupPhase.RUNNING
        elif pg.status.phase != PodGroupPhase.INQUEUE:
            status.phase = PodGroupPhase.PENDING

    status.running = len(job.task_status_index.get(TaskStatus.RUNNING, {}))
    status.failed = len(job.task_status_index.get(TaskStatus.FAILED, {}))
    status.succeeded = len(job.task_status_index.get(TaskStatus.SUCCEEDED, {}))
    return status
