"""Statement: transaction log of {Evict, Pipeline, Allocate} session ops.

The gang all-or-nothing primitive (reference framework/statement.go:28-388):
operations mutate session state immediately; Commit() applies side effects
through the cache (bind/evict), Discard() undoes everything in reverse order.
The TPU solver's assignments are replayed through exactly this boundary.
"""

from __future__ import annotations

import enum
import logging
from typing import List, Tuple

from ..api import Resource, TaskInfo, TaskStatus
from ..resilience.faultinject import faults

log = logging.getLogger(__name__)


class Op(enum.Enum):
    EVICT = "evict"
    PIPELINE = "pipeline"
    ALLOCATE = "allocate"


#: operation log entries are plain (op, task, reason) tuples — a 10k-task
#: replay appends one per task, and dataclass construction was measurable
_Operation = Tuple[Op, TaskInfo, str]


class Statement:
    def __init__(self, ssn, defer_events: bool = False):
        self.ssn = ssn
        self.operations: List[_Operation] = []
        # defer_events: don't fire per-task allocate events as ALLOCATE ops
        # are recorded; fire them as ONE batch at commit. A discarded
        # statement then fires nothing for its allocate ops — identical
        # final handler state to the reference's fire-then-unfire (handlers
        # are additive), at a tenth of the cost. Pipelined tasks are NOT
        # covered: ssn.pipeline() is outside the Statement (allocate.go
        # pipelines via ssn.Pipeline) and keeps firing live, surviving
        # discard exactly as before. Used by the solver replay; the host
        # loop keeps live events because its ordering decisions read
        # shares mid-flight.
        self.defer_events = defer_events
        # containment bookkeeping: which action epoch opened this
        # statement. A watchdog-contained (timed-out) action's zombie
        # thread may call commit() long after the scheduler moved on; the
        # epoch guard turns that late commit into a discard so nothing an
        # abandoned action decided reaches the cluster (see
        # resilience/watchdog.py).
        self._epoch = getattr(ssn, "_action_epoch", 0)

    # -- evict --------------------------------------------------------------

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Mark Releasing in session now; the pod delete happens at Commit."""
        job = self.ssn.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.RELEASING)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        self.ssn._fire_deallocate(reclaimee)
        self.operations.append((Op.EVICT, reclaimee, reason))

    def _commit_evict(self, reclaimee: TaskInfo, reason: str) -> None:
        try:
            self.ssn.cache.evict(reclaimee, reason)
        except Exception:
            log.exception("commit evict failed for %s", reclaimee.key)
            self._unevict(reclaimee)
            raise

    def _unevict(self, reclaimee: TaskInfo) -> None:
        job = self.ssn.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.RUNNING)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        self.ssn._fire_allocate(reclaimee)

    # -- pipeline -----------------------------------------------------------

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PIPELINED)
        task.node_name = hostname
        node = self.ssn.nodes.get(hostname)
        if node is not None:
            node.add_task(task)
        self.ssn._fire_allocate(task)
        self.operations.append((Op.PIPELINE, task, ""))

    def _unpipeline(self, task: TaskInfo) -> None:
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PENDING)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            node.remove_task(task)
        task.node_name = ""
        self.ssn._fire_deallocate(task)

    # -- allocate -----------------------------------------------------------

    def allocate(self, task: TaskInfo, hostname: str) -> None:
        self.ssn.cache.allocate_volumes(task, hostname)
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.ALLOCATED)
        task.node_name = hostname
        node = self.ssn.nodes.get(hostname)
        if node is not None:
            node.add_task(task)
        if not self.defer_events:
            self.ssn._fire_allocate(task)
        else:
            self.ssn._mutation_ops += 1
        self.operations.append((Op.ALLOCATE, task, ""))

    def allocate_bulk(self, pairs) -> list:
        """allocate() over a whole assignment wave ([(task, hostname)]) in
        one pass: volume assumptions, events and undo records keep their
        per-task semantics, while job/node accounting is applied as bulk
        index moves + one summed resource delta per (job, node) group.
        Pairs the fast path can't take (missing job/node, wave that doesn't
        fit, foreign objects) go through plain allocate() instead. Returns
        [(task, hostname, exc)] for pairs that failed — the same exceptions
        allocate() would have raised (callers record FitErrors from
        them)."""
        ssn = self.ssn
        failures = []
        slow = []
        vol_batch = getattr(ssn.cache, "allocate_volumes_batch", None)
        if vol_batch is not None:
            vol_failures = vol_batch(pairs)
            if vol_failures:
                failures.extend(vol_failures)
                failed = {id(t) for t, _, _ in vol_failures}
                pairs = [(t, h) for t, h in pairs if id(t) not in failed]
        by_node = {}
        jobs = ssn.jobs
        last_jobid = None  # replay waves are per-job: one lookup suffices
        job = None
        seen = set()
        for task, hostname in pairs:
            if vol_batch is None:
                try:
                    ssn.cache.allocate_volumes(task, hostname)
                except (KeyError, ValueError) as e:
                    failures.append((task, hostname, e))
                    continue
            if task.job != last_jobid:
                job = jobs.get(task.job)
                last_jobid = task.job
            key = task.key
            # slow-path pairs: unknown job, a task that is not the job's
            # stored object (bulk_update_status would quietly route it but
            # the atomicity argument needs stored-only waves), or a
            # duplicate within the wave (the per-task loop raises on the
            # second occurrence; the wave must not double-count it)
            if job is None or job.tasks.get(key) is not task \
                    or key in seen:
                slow.append((task, hostname))
                continue
            seen.add(key)
            group = by_node.get(hostname)
            if group is None:
                by_node[hostname] = [task]
            else:
                group.append(task)
        # the fast path must be unable to raise mid-wave (a partial bulk
        # mutation would leave applied tasks without undo records), so each
        # node group is validated with the same checks add_task makes —
        # whole-group fit included — and demoted to the per-task path
        # otherwise, whose partial-application + raise semantics the caller
        # already handles
        fast_nodes = []
        bad = (TaskStatus.RELEASING, TaskStatus.PIPELINED)
        for hostname, tasks in by_node.items():
            node = ssn.nodes.get(hostname)
            ok = node is not None and node.node is not None
            if ok:
                node_tasks = node.tasks
                for t in tasks:
                    if (t.node_name and t.node_name != hostname) \
                            or t.key in node_tasks or t.status in bad:
                        ok = False
                        break
            if ok:
                req = tasks[0].resreq if len(tasks) == 1 \
                    else Resource.sum_of(t.resreq for t in tasks)
                ok = req.less_equal(node.idle)
            if ok:
                fast_nodes.append((node, tasks))
            else:
                slow.extend((t, hostname) for t in tasks)
        by_job = {}
        for node, tasks in fast_nodes:
            for t in tasks:
                by_job.setdefault(t.job, []).append(t)
        demoted = set()
        for jobid, tasks in by_job.items():
            try:
                # raises BEFORE mutating (aggregates pre-checked), so a
                # failed job's whole wave can still demote to the per-task
                # path and surface per-task failures
                ssn.jobs[jobid].bulk_update_status(
                    tasks, TaskStatus.ALLOCATED)
            except (KeyError, ValueError):
                demoted.update(id(t) for t in tasks)
        ops = self.operations
        for node, tasks in fast_nodes:
            if demoted:
                kept = [t for t in tasks if id(t) not in demoted]
                slow.extend((t, node.name) for t in tasks
                            if id(t) in demoted)
                if not kept:
                    continue
                tasks = kept
            node.add_tasks_bulk(tasks, validated=True)
            if not self.defer_events:
                for task in tasks:
                    ssn._fire_allocate(task)
            else:
                ssn._mutation_ops += len(tasks)
            for task in tasks:
                ops.append((Op.ALLOCATE, task, ""))
        for task, hostname in slow:
            try:
                # volumes were already assumed; re-assuming is idempotent
                self.allocate(task, hostname)
            except (KeyError, ValueError) as e:
                failures.append((task, hostname, e))
        return failures

    def _commit_allocate(self, task: TaskInfo) -> None:
        try:
            self.ssn.cache.bind_volumes(task)
            self.ssn.cache.bind(task, task.node_name)
        except Exception:
            log.exception("commit allocate failed for %s", task.key)
            self._unallocate(task)
            raise

    def _unallocate(self, task: TaskInfo, fired: bool = True) -> None:
        _undo_allocate(self.ssn, task, fired)

    # -- transaction boundary ----------------------------------------------

    def _close_ledger(self) -> None:
        ledger = getattr(self.ssn, "_open_statements", None)
        if ledger is not None:
            ledger.pop(id(self), None)

    def commit(self) -> None:
        """Apply side effects (statement.go:370-388)."""
        if self._epoch in getattr(self.ssn, "_contained_epochs", ()):
            # the action that opened this statement was contained (it
            # blew its deadline and was abandoned): its decisions were
            # rolled back, so a zombie thread's late commit must discard
            log.warning("discarding commit from a contained action")
            self.discard()
            return
        self._close_ledger()
        acc = getattr(self.ssn, "_bulk_commit_acc", None)
        if acc is not None and self.defer_events and self.operations \
                and getattr(self.ssn.cache, "bind_batch", None) is not None \
                and all(name is Op.ALLOCATE
                        for name, _, _ in self.operations):
            # bulk-commit window (the solver replay): defer this
            # statement's cache-side effects and allocate events to ONE
            # end-of-replay wave (flush_bulk_commit). Per-job commits
            # produce node groups of ~1 task when a job's gang spreads
            # across nodes, degrading every bulk helper to per-task work;
            # the merged wave re-groups the whole replay by node.
            acc.extend(task for _, task, _ in self.operations)
            self.operations = []
            return
        # crash-safe window: journal the decided binds BEFORE any effect
        # dispatches (resilience/recovery.py; leader-only — bind_journal
        # is None outside HA), then cross the bind_commit fault seam. A
        # crash landing anywhere past this line leaves a durable intent
        # the next leader reconciles; a FencedError from the journal
        # means this writer was deposed and must discard, not commit.
        _journal_statement_binds(self)
        faults.fire("bind_commit")
        if self.defer_events:
            self.ssn._fire_allocate_batch(
                [task for name, task, _ in self.operations
                 if name is Op.ALLOCATE])
        bind_batch = getattr(self.ssn.cache, "bind_batch", None)
        if bind_batch is not None and len(self.operations) > 1 and all(
                name is Op.ALLOCATE for name, _, _ in self.operations):
            # pure-allocate statement (the solver replay shape): volumes
            # bind as one wave, then ONE batched cache bind — identical
            # cache state and failure handling to the per-op loop, without
            # its per-task dispatch cost
            cache = self.ssn.cache
            tasks = [task for _, task, _ in self.operations]
            vb_batch = getattr(cache, "bind_volumes_batch", None)
            if vb_batch is not None:
                vol_failures = vb_batch(tasks)
            else:
                vol_failures = []
                for task in tasks:
                    try:
                        cache.bind_volumes(task)
                    except Exception as e:  # noqa: BLE001
                        vol_failures.append((task, e))
            if vol_failures:
                failed = {id(t) for t, _ in vol_failures}
                tasks = [t for t in tasks if id(t) not in failed]
                for task, exc in vol_failures:
                    log.error("commit bind_volumes failed for %s: %s",
                              task.key, exc)
                    self._unallocate(task)
            for task, exc in bind_batch(tasks):
                log.error("commit bind failed for %s: %s", task.key, exc)
                self._unallocate(task)
            self.operations = []
            return
        for name, task, reason in self.operations:
            try:
                if name is Op.EVICT:
                    self._commit_evict(task, reason)
                elif name is Op.ALLOCATE:
                    self._commit_allocate(task)
                # Pipeline has no cache side effect: the promise lives in
                # session/PodGroup state until resources actually free.
            except Exception:
                continue
        self.operations = []

    def discard(self) -> None:
        """Reverse-order undo (statement.go:345-367)."""
        self._close_ledger()
        # a discarded statement must leave nothing in the bulk-commit
        # window (its ops were never accumulated — commit() is the only
        # writer — so plain reverse-undo below is complete)
        for name, task, _ in reversed(self.operations):
            if name is Op.EVICT:
                self._unevict(task)
            elif name is Op.PIPELINE:
                self._unpipeline(task)
            elif name is Op.ALLOCATE:
                # deferred mode never fired the allocate event, so the
                # undo must not fire the deallocate one
                self._unallocate(task, fired=not self.defer_events)
        self.operations = []


def _journal_statement_binds(stmt: "Statement") -> None:
    """Persist a Statement's decided ALLOCATE wave as one bind intent
    (see resilience/recovery.py) before any effect dispatches. No-op
    unless the cache carries a journal (leader-only). FencedError aborts
    the commit — a deposed leader's decisions discard instead of
    reaching the cluster; any other journal failure is logged and the
    commit proceeds (the journal narrows crash windows, it must not
    widen availability ones)."""
    journal = getattr(stmt.ssn.cache, "bind_journal", None)
    if journal is None or not stmt.operations:
        return
    tasks = [task for name, task, _ in stmt.operations
             if name is Op.ALLOCATE]
    if not tasks:
        return
    try:
        journal.record(tasks)
    except Exception as e:  # noqa: BLE001 — classify below
        from ..client.store import FencedError
        if isinstance(e, FencedError):
            log.error("bind-intent journal fenced; discarding the "
                      "deposed leader's statement: %s", e)
            stmt.discard()
            raise
        log.exception("bind-intent journal write failed; committing "
                      "without the intent record")


def _journal_wave_binds(ssn, tasks: list) -> None:
    """flush_bulk_commit's counterpart of _journal_statement_binds: one
    intent for the whole merged replay wave."""
    journal = getattr(ssn.cache, "bind_journal", None)
    if journal is None or not tasks:
        return
    try:
        journal.record(tasks)
    except Exception as e:  # noqa: BLE001 — classify below
        from ..client.store import FencedError
        if isinstance(e, FencedError):
            log.error("bind-intent journal fenced; unwinding the "
                      "deposed leader's replay wave: %s", e)
            # the deferred allocate events were never fired for this
            # wave, so the unwind fires nothing either (handler parity
            # with a discarded deferred statement)
            for task in tasks:
                _undo_allocate(ssn, task, fired=False)
            raise
        log.exception("bind-intent journal write failed; committing "
                      "without the intent record")


def _undo_allocate(ssn, task: TaskInfo, fired: bool = True) -> None:
    """Reverse one session-side allocate (shared by Statement._unallocate
    and the bulk-commit flush, which outlives its statements)."""
    revert = getattr(ssn.cache, "revert_volumes", None)
    if revert is not None:
        revert(task)  # drop the AllocateVolumes assumption
    job = ssn.jobs.get(task.job)
    if job is not None:
        job.update_task_status(task, TaskStatus.PENDING)
    node = ssn.nodes.get(task.node_name)
    if node is not None:
        node.remove_task(task)
    task.node_name = ""
    if fired:
        ssn._fire_deallocate(task)


def begin_bulk_commit(ssn) -> list:
    """Open a bulk-commit window on the session: subsequent pure-allocate
    deferred-event statements queue their tasks here instead of paying a
    cache bind wave each (see Statement.commit). Caller MUST pair with
    flush_bulk_commit."""
    acc: list = []
    ssn._bulk_commit_acc = acc
    return acc


def flush_bulk_commit(ssn, acc: list) -> None:
    """Close the window and apply every queued statement's side effects as
    one wave: a single allocate-event batch, one volume bind wave, one
    cache bind_batch over the WHOLE replay (node groups re-form at full
    width instead of per job). Cache state and failure semantics are
    identical to per-statement commits — a task whose cache-side bind
    fails is unallocated session-side exactly as Statement.commit would."""
    ssn._bulk_commit_acc = None
    if not acc:
        return
    # same crash-safe window as Statement.commit: intent first, then the
    # bind_commit fault seam, then effects (see resilience/recovery.py)
    _journal_wave_binds(ssn, acc)
    faults.fire("bind_commit")
    ssn._fire_allocate_batch(acc)
    cache = ssn.cache
    tasks = acc
    vb_batch = getattr(cache, "bind_volumes_batch", None)
    if vb_batch is not None:
        vol_failures = vb_batch(tasks)
    else:
        vol_failures = []
        for task in tasks:
            try:
                cache.bind_volumes(task)
            except Exception as e:  # noqa: BLE001
                vol_failures.append((task, e))
    if vol_failures:
        failed = {id(t) for t, _ in vol_failures}
        tasks = [t for t in tasks if id(t) not in failed]
        for task, exc in vol_failures:
            log.error("commit bind_volumes failed for %s: %s",
                      task.key, exc)
            _undo_allocate(ssn, task, fired=False)
            ssn._fire_deallocate(task)
    # Statement.commit only queues into the window when the cache HAS
    # bind_batch; the guard here keeps the flush total anyway
    bind_batch = getattr(cache, "bind_batch", None)
    if bind_batch is not None:
        failures = bind_batch(tasks)
    else:
        failures = []
        for task in tasks:
            try:
                cache.bind(task, task.node_name)
            except Exception as e:  # noqa: BLE001
                failures.append((task, e))
    for task, exc in failures:
        log.error("commit bind failed for %s: %s", task.key, exc)
        _undo_allocate(ssn, task, fired=False)
        ssn._fire_deallocate(task)
