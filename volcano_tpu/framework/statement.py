"""Statement: transaction log of {Evict, Pipeline, Allocate} session ops.

The gang all-or-nothing primitive (reference framework/statement.go:28-388):
operations mutate session state immediately; Commit() applies side effects
through the cache (bind/evict), Discard() undoes everything in reverse order.
The TPU solver's assignments are replayed through exactly this boundary.
"""

from __future__ import annotations

import enum
import logging
from dataclasses import dataclass
from typing import List

from ..api import TaskInfo, TaskStatus

log = logging.getLogger(__name__)


class Op(enum.Enum):
    EVICT = "evict"
    PIPELINE = "pipeline"
    ALLOCATE = "allocate"


@dataclass
class _Operation:
    name: Op
    task: TaskInfo
    reason: str = ""


class Statement:
    def __init__(self, ssn, defer_events: bool = False):
        self.ssn = ssn
        self.operations: List[_Operation] = []
        # defer_events: don't fire per-task allocate events as ALLOCATE ops
        # are recorded; fire them as ONE batch at commit. A discarded
        # statement then fires nothing for its allocate ops — identical
        # final handler state to the reference's fire-then-unfire (handlers
        # are additive), at a tenth of the cost. Pipelined tasks are NOT
        # covered: ssn.pipeline() is outside the Statement (allocate.go
        # pipelines via ssn.Pipeline) and keeps firing live, surviving
        # discard exactly as before. Used by the solver replay; the host
        # loop keeps live events because its ordering decisions read
        # shares mid-flight.
        self.defer_events = defer_events

    # -- evict --------------------------------------------------------------

    def evict(self, reclaimee: TaskInfo, reason: str) -> None:
        """Mark Releasing in session now; the pod delete happens at Commit."""
        job = self.ssn.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.RELEASING)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        self.ssn._fire_deallocate(reclaimee)
        self.operations.append(_Operation(Op.EVICT, reclaimee, reason))

    def _commit_evict(self, reclaimee: TaskInfo, reason: str) -> None:
        try:
            self.ssn.cache.evict(reclaimee, reason)
        except Exception:
            log.exception("commit evict failed for %s", reclaimee.key)
            self._unevict(reclaimee)
            raise

    def _unevict(self, reclaimee: TaskInfo) -> None:
        job = self.ssn.jobs.get(reclaimee.job)
        if job is not None:
            job.update_task_status(reclaimee, TaskStatus.RUNNING)
        node = self.ssn.nodes.get(reclaimee.node_name)
        if node is not None:
            node.update_task(reclaimee)
        self.ssn._fire_allocate(reclaimee)

    # -- pipeline -----------------------------------------------------------

    def pipeline(self, task: TaskInfo, hostname: str) -> None:
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PIPELINED)
        task.node_name = hostname
        node = self.ssn.nodes.get(hostname)
        if node is not None:
            node.add_task(task)
        self.ssn._fire_allocate(task)
        self.operations.append(_Operation(Op.PIPELINE, task))

    def _unpipeline(self, task: TaskInfo) -> None:
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PENDING)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            node.remove_task(task)
        task.node_name = ""
        self.ssn._fire_deallocate(task)

    # -- allocate -----------------------------------------------------------

    def allocate(self, task: TaskInfo, hostname: str) -> None:
        self.ssn.cache.allocate_volumes(task, hostname)
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.ALLOCATED)
        task.node_name = hostname
        node = self.ssn.nodes.get(hostname)
        if node is not None:
            node.add_task(task)
        if not self.defer_events:
            self.ssn._fire_allocate(task)
        self.operations.append(_Operation(Op.ALLOCATE, task))

    def _commit_allocate(self, task: TaskInfo) -> None:
        try:
            self.ssn.cache.bind_volumes(task)
            self.ssn.cache.bind(task, task.node_name)
        except Exception:
            log.exception("commit allocate failed for %s", task.key)
            self._unallocate(task)
            raise

    def _unallocate(self, task: TaskInfo, fired: bool = True) -> None:
        revert = getattr(self.ssn.cache, "revert_volumes", None)
        if revert is not None:
            revert(task)  # drop the AllocateVolumes assumption
        job = self.ssn.jobs.get(task.job)
        if job is not None:
            job.update_task_status(task, TaskStatus.PENDING)
        node = self.ssn.nodes.get(task.node_name)
        if node is not None:
            node.remove_task(task)
        task.node_name = ""
        if fired:
            self.ssn._fire_deallocate(task)

    # -- transaction boundary ----------------------------------------------

    def commit(self) -> None:
        """Apply side effects (statement.go:370-388)."""
        if self.defer_events:
            self.ssn._fire_allocate_batch(
                [op.task for op in self.operations
                 if op.name == Op.ALLOCATE])
        for op in self.operations:
            try:
                if op.name == Op.EVICT:
                    self._commit_evict(op.task, op.reason)
                elif op.name == Op.ALLOCATE:
                    self._commit_allocate(op.task)
                # Pipeline has no cache side effect: the promise lives in
                # session/PodGroup state until resources actually free.
            except Exception:
                continue
        self.operations = []

    def discard(self) -> None:
        """Reverse-order undo (statement.go:345-367)."""
        for op in reversed(self.operations):
            if op.name == Op.EVICT:
                self._unevict(op.task)
            elif op.name == Op.PIPELINE:
                self._unpipeline(op.task)
            elif op.name == Op.ALLOCATE:
                # deferred mode never fired the allocate event, so the
                # undo must not fire the deallocate one
                self._unallocate(op.task, fired=not self.defer_events)
        self.operations = []
