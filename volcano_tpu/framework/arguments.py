"""Typed plugin-argument extraction (reference framework/arguments.go)."""

from __future__ import annotations

from typing import Any, Dict


class Arguments(dict):
    """Plugin arguments map with typed getters. Getters keep the caller's
    default when the key is missing or unparsable, like the reference."""

    def get_int(self, key: str, default: int) -> int:
        if key not in self:
            return default
        try:
            return int(self[key])
        except (TypeError, ValueError):
            return default

    def get_float(self, key: str, default: float) -> float:
        if key not in self:
            return default
        try:
            return float(self[key])
        except (TypeError, ValueError):
            return default

    def get_bool(self, key: str, default: bool = False) -> bool:
        if key not in self:
            return default
        v = self[key]
        if isinstance(v, bool):
            return v
        if isinstance(v, str):
            return v.strip().lower() in ("1", "t", "true", "yes", "y")
        return bool(v)
