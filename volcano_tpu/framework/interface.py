"""Action and Plugin interfaces (reference framework/interface.go:20-41)."""

from __future__ import annotations

from typing import Optional


class Action:
    def name(self) -> str:
        raise NotImplementedError

    def initialize(self) -> None:
        pass

    def execute(self, ssn) -> None:
        raise NotImplementedError

    def un_initialize(self) -> None:
        pass

    def resolve_mode(self, ssn, default: str = "solver") -> str:
        """Execution mode for this action: per-action YAML configuration
        ('mode' argument), then the deployment-level --solver-mode
        preference when the conf left the mode implicit, overridden to
        'host' when a plugin demands host-only state tracking (GPU
        sharing card assignment)."""
        from .arguments import Arguments

        mode = default
        configured = False
        for conf in ssn.configurations:
            if conf.name == self.name():
                m = Arguments(conf.arguments).get("mode", None)
                if m is not None:
                    mode, configured = m, True
                else:
                    mode = default
        if not configured:
            pref = getattr(ssn, "solver_mode", None)
            if pref in ("packed", "sharded", "auto"):
                mode = self._preferred_mode(ssn, pref, default)
        if ssn.solver_options.get("force_host_allocate"):
            mode = "host"
        return mode

    @staticmethod
    def _preferred_mode(ssn, pref: str, default: str) -> str:
        """The --solver-mode decision rule. 'packed' keeps the
        single-device solver; 'sharded' always dispatches the node-axis
        shard_map solver over the sharded arena; 'auto' picks sharded
        exactly when the padded problem's device-resident footprint —
        one full upload at the current layout, measured from whichever
        arena served the last session — exceeds the per-device byte
        budget (``--sharded-byte-budget``): when one chip would have to
        hold more resident solver state than the budget allows, shard it
        over the mesh. The first session (no layout measured yet) and a
        zero/unset budget run packed."""
        if pref == "sharded":
            return "sharded"
        if pref == "packed":
            return default
        budget = int(getattr(ssn, "sharded_byte_budget", 0) or 0)
        if budget <= 0:
            return default
        est = 0
        for attr in ("device_cache", "sharded_device_cache"):
            c = getattr(ssn, attr, None)
            if c is not None:
                try:
                    est = max(est, c.full_upload_bytes())
                except Exception:  # noqa: BLE001 — sizing is advisory
                    pass
        return "sharded" if est > budget else default


class Plugin:
    def name(self) -> str:
        raise NotImplementedError

    def on_session_open(self, ssn) -> None:
        raise NotImplementedError

    def on_session_close(self, ssn) -> None:
        raise NotImplementedError


class ValidateResult:
    """Result of a JobValid fn (api/types.go ValidateResult)."""

    __slots__ = ("passed", "reason", "message")

    def __init__(self, passed: bool, reason: str = "", message: str = ""):
        self.passed = passed
        self.reason = reason
        self.message = message
