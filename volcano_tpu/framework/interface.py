"""Action and Plugin interfaces (reference framework/interface.go:20-41)."""

from __future__ import annotations

from typing import Optional


class Action:
    def name(self) -> str:
        raise NotImplementedError

    def initialize(self) -> None:
        pass

    def execute(self, ssn) -> None:
        raise NotImplementedError

    def un_initialize(self) -> None:
        pass

    def resolve_mode(self, ssn, default: str = "solver") -> str:
        """Execution mode for this action: per-action YAML configuration
        ('mode' argument), overridden to 'host' when a plugin demands
        host-only state tracking (GPU sharing card assignment)."""
        from .arguments import Arguments

        mode = default
        for conf in ssn.configurations:
            if conf.name == self.name():
                mode = Arguments(conf.arguments).get("mode", default)
        if ssn.solver_options.get("force_host_allocate"):
            mode = "host"
        return mode


class Plugin:
    def name(self) -> str:
        raise NotImplementedError

    def on_session_open(self, ssn) -> None:
        raise NotImplementedError

    def on_session_close(self, ssn) -> None:
        raise NotImplementedError


class ValidateResult:
    """Result of a JobValid fn (api/types.go ValidateResult)."""

    __slots__ = ("passed", "reason", "message")

    def __init__(self, passed: bool, reason: str = "", message: str = ""):
        self.passed = passed
        self.reason = reason
        self.message = message
