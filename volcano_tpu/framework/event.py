"""Session event handlers (reference framework/event.go:23-32).

Stateful plugins (drf/proportion/predicates) register Allocate/Deallocate
callbacks so their shares stay incrementally consistent with every
assign/unassign inside a session.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from ..api import TaskInfo


@dataclass
class Event:
    task: TaskInfo


@dataclass
class EventHandler:
    allocate_func: Optional[Callable[[Event], None]] = None
    deallocate_func: Optional[Callable[[Event], None]] = None
    # optional bulk form: handlers whose per-task updates are additive
    # (drf/proportion share accounting) can process a whole job's
    # assignments in one call; the session falls back to the per-task fn
    # when absent. Used by the solver-mode replay, where firing 10k
    # individual events dominated the cycle profile. (No deallocate
    # counterpart: deferred statements fire nothing on discard.)
    batch_allocate_func: Optional[Callable[[list], None]] = None
