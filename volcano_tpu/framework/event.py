"""Session event handlers (reference framework/event.go:23-32).

Stateful plugins (drf/proportion/predicates) register Allocate/Deallocate
callbacks so their shares stay incrementally consistent with every
assign/unassign inside a session.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from ..api import TaskInfo


@dataclass
class Event:
    task: TaskInfo


@dataclass
class EventHandler:
    allocate_func: Optional[Callable[[Event], None]] = None
    deallocate_func: Optional[Callable[[Event], None]] = None
