"""open_session / close_session (reference framework/framework.go:30-64)."""

from __future__ import annotations

import logging
import time
from typing import List

from ..conf import Tier
from .arguments import Arguments
from .job_updater import JobUpdater
from .registry import get_plugin_builder
from .session import Session, job_status

log = logging.getLogger(__name__)


def open_session(cache, tiers: List[Tier], configurations=None) -> Session:
    import volcano_tpu.plugins  # noqa: F401  (registers builtin plugins)
    ssn = Session(cache, cache.snapshot())
    ssn.tiers = tiers
    ssn.configurations = configurations or []

    for tier in tiers:
        for opt in tier.plugins:
            builder = get_plugin_builder(opt.name)
            if builder is None:
                log.warning("failed to get plugin %s", opt.name)
                continue
            plugin = builder(Arguments(opt.arguments))
            ssn.plugins[plugin.name()] = plugin
            t0 = time.perf_counter()
            plugin.on_session_open(ssn)
            _metrics_plugin(plugin.name(), "OnSessionOpen", t0)

    # NOTE: the reference's openSession contains a JobValid filter
    # (session.go:121-138), but it runs BEFORE plugins register their
    # jobValidFns, so it never fires; the real filtering happens inside each
    # action (allocate/backfill check ssn.JobValid). We mirror that: no
    # filtering here — enqueue must still see pod-less Pending podgroups.
    return ssn


def close_session(ssn: Session) -> None:
    for name, plugin in ssn.plugins.items():
        t0 = time.perf_counter()
        plugin.on_session_close(ssn)
        _metrics_plugin(name, "OnSessionClose", t0)

    # decision-trace hook: the recorder reads the session AFTER plugins
    # closed (conditions/fit errors final) and BEFORE teardown — this is
    # where pipeline statements and per-job unschedulability summaries
    # enter the sim's golden trace (sim/recorder.py)
    rec = getattr(ssn, "decision_recorder", None)
    if rec is not None:
        try:
            rec.observe_session(ssn)
        except Exception:
            log.exception("decision recorder observe_session failed")

    ju = JobUpdater(ssn)
    ju.update_all()

    ssn.jobs = {}
    ssn.nodes = {}
    ssn.plugins = {}
    ssn.event_handlers = []
    ssn._tier_cache = {}
    for reg in list(ssn.__dict__):
        if reg.endswith("_fns"):
            setattr(ssn, reg, {})


def _metrics_plugin(plugin: str, phase: str, t0: float) -> None:
    from ..metrics import metrics
    metrics.plugin_scheduling_latency.observe(
        time.perf_counter() - t0, labels={"plugin": plugin, "OnSession": phase})
