"""Scheduler framework (reference pkg/scheduler/framework)."""

from .arguments import Arguments  # noqa: F401
from .event import Event, EventHandler  # noqa: F401
from .framework import close_session, open_session  # noqa: F401
from .interface import Action, Plugin, ValidateResult  # noqa: F401
from .job_updater import JobUpdater  # noqa: F401
from .registry import (  # noqa: F401
    get_action, get_plugin_builder, list_actions, list_plugins,
    register_action, register_plugin_builder,
)
from .session import Session, job_status  # noqa: F401
from .statement import Statement  # noqa: F401
