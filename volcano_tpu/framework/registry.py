"""Plugin and action registries (reference framework/plugins.go:21-72)."""

from __future__ import annotations

from typing import Callable, Dict, Optional

_plugin_builders: Dict[str, Callable] = {}
_actions: Dict[str, object] = {}


def register_plugin_builder(name: str, builder: Callable) -> None:
    _plugin_builders[name] = builder


def get_plugin_builder(name: str) -> Optional[Callable]:
    return _plugin_builders.get(name)


def register_action(action) -> None:
    _actions[action.name()] = action


def get_action(name: str):
    if name not in _actions:
        import volcano_tpu.actions  # noqa: F401  (registers builtin actions)
    return _actions.get(name)


def list_plugins():
    return sorted(_plugin_builders)


def list_actions():
    return sorted(_actions)
