"""JobUpdater: push PodGroup status back on session close.

Reference framework/job_updater.go:16-108 fans out over 16 workers and
jitters duplicate condition updates; the TPU build is single-core so the
update loop is sequential, with the same skip-if-unchanged dedup.
"""

from __future__ import annotations

import logging

from .session import job_status

log = logging.getLogger(__name__)


def _conditions_equal(c1, c2) -> bool:
    if len(c1) != len(c2):
        return False
    for a, b in zip(c1, c2):
        # transition_id/time changes alone don't warrant an update
        if (a.type, a.status, a.reason, a.message) != (b.type, b.status,
                                                       b.reason, b.message):
            return False
    return True


def _status_equal(s1, s2) -> bool:
    return (s1.phase == s2.phase and s1.running == s2.running
            and s1.succeeded == s2.succeeded and s1.failed == s2.failed)


class JobUpdater:
    def __init__(self, ssn):
        self.ssn = ssn

    def update_all(self) -> None:
        for job in self.ssn.jobs.values():
            self.update_job(job)

    def update_job(self, job) -> None:
        if job.pod_group is None:
            return
        new = job_status(self.ssn, job)
        old = self.ssn.pod_group_status.get(job.uid)
        update_pg = old is None or not (
            _status_equal(old, new)
            and _conditions_equal(old.conditions, new.conditions))
        try:
            self.ssn.cache.update_job_status(job, update_pg)
        except Exception:
            log.exception("failed to update job status for %s", job.uid)
