"""JobUpdater: push PodGroup status back on session close.

Reference framework/job_updater.go:16-108 fans out over 16 workers with a
skip-if-unchanged dedup. The fan-out matters when status writes go to a
remote control plane (each write is a network round trip); against the
in-memory store it degrades gracefully to near-sequential behind the
store's lock.
"""

from __future__ import annotations

import logging
from concurrent.futures import ThreadPoolExecutor

from .session import job_status

log = logging.getLogger(__name__)

#: jobUpdaterWorker (job_updater.go:17)
JOB_UPDATER_WORKERS = 16

#: lazily created persistent pool shared by all sessions (daemon threads;
#: creating/joining 16 threads per session close would be pure churn)
_POOL = None


def _shared_pool() -> ThreadPoolExecutor:
    global _POOL
    if _POOL is None:
        _POOL = ThreadPoolExecutor(max_workers=JOB_UPDATER_WORKERS,
                                   thread_name_prefix="job-updater")
    return _POOL


# status comparisons use PodGroupStatus.fingerprint() tuples: equal
# fingerprints = no significant change (transition_id/time excluded)


class JobUpdater:
    def __init__(self, ssn, workers: int = JOB_UPDATER_WORKERS):
        self.ssn = ssn
        self.workers = workers

    def update_all(self) -> None:
        jobs = [j for j in self.ssn.jobs.values() if self._dirty(j)]
        # the fan-out only pays for many jobs against a slow control plane;
        # small sessions stay sequential and deterministic
        if len(jobs) <= 4 or self.workers <= 1:
            for job in jobs:
                self.update_job(job)
            return
        # consume the iterator so worker exceptions surface in the logs
        # via update_job's own try/except, not silently in futures
        list(_shared_pool().map(self.update_job, jobs))

    def _dirty(self, job) -> bool:
        """Skip-if-untouched: a READY job whose tasks (since the last
        successful status write — not merely since session open, so
        informer-driven changes between cycles count), conditions, fit
        errors and phase are all unchanged recomputes to an identical
        status, so neither the recompute nor the (diffed-away) write can
        have an effect. Unready jobs always process: update_job_status's
        record_job_status_event posts Unschedulable pod conditions for
        them unconditionally (cache.go:791-826), even when the cycle never
        touched the job (e.g. its queue stayed overused). The reference
        reaches the same end state by diffing before every write
        (job_updater.go:95-100); tracking dirtiness against the
        last-written version also skips the recompute, which dominates at
        thousands of untouched running jobs per cycle."""
        ssn = self.ssn
        if job.uid in ssn._conditions_touched or job.nodes_fit_errors:
            return True
        written = getattr(ssn.cache, "updater_versions", None)
        if written is None or written.get(job.uid) != job.flat_version:
            return True
        old = ssn.pod_group_status.get(job.uid)
        if (old is None or job.pod_group is None
                or old[0] != job.pod_group.status.phase):
            return True
        return not job.ready()

    def update_job(self, job) -> None:
        if job.pod_group is None:
            return
        new = job_status(self.ssn, job)
        old = self.ssn.pod_group_status.get(job.uid)
        update_pg = old is None or old != new.fingerprint()
        try:
            self.ssn.cache.update_job_status(job, update_pg)
        except Exception:
            log.exception("failed to update job status for %s", job.uid)
            return
        # record the version this write reflects: _dirty() compares the
        # next snapshot's version against it, so changes landing between
        # sessions (informer pod updates) re-dirty the job
        versions = getattr(self.ssn.cache, "updater_versions", None)
        if versions is not None:
            versions[job.uid] = job.flat_version
