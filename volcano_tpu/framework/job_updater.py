"""JobUpdater: push PodGroup status back on session close.

Reference framework/job_updater.go:16-108 fans out over 16 workers with a
skip-if-unchanged dedup. The fan-out matters when status writes go to a
remote control plane (each write is a network round trip); against the
in-memory store it degrades gracefully to near-sequential behind the
store's lock.
"""

from __future__ import annotations

import logging
from concurrent.futures import ThreadPoolExecutor

from .session import job_status

log = logging.getLogger(__name__)

#: jobUpdaterWorker (job_updater.go:17)
JOB_UPDATER_WORKERS = 16

#: lazily created persistent pool shared by all sessions (daemon threads;
#: creating/joining 16 threads per session close would be pure churn)
_POOL = None


def _shared_pool() -> ThreadPoolExecutor:
    global _POOL
    if _POOL is None:
        _POOL = ThreadPoolExecutor(max_workers=JOB_UPDATER_WORKERS,
                                   thread_name_prefix="job-updater")
    return _POOL


def _conditions_equal(c1, c2) -> bool:
    if len(c1) != len(c2):
        return False
    for a, b in zip(c1, c2):
        # transition_id/time changes alone don't warrant an update
        if (a.type, a.status, a.reason, a.message) != (b.type, b.status,
                                                       b.reason, b.message):
            return False
    return True


def _status_equal(s1, s2) -> bool:
    return (s1.phase == s2.phase and s1.running == s2.running
            and s1.succeeded == s2.succeeded and s1.failed == s2.failed)


class JobUpdater:
    def __init__(self, ssn, workers: int = JOB_UPDATER_WORKERS):
        self.ssn = ssn
        self.workers = workers

    def update_all(self) -> None:
        jobs = list(self.ssn.jobs.values())
        # the fan-out only pays for many jobs against a slow control plane;
        # small sessions stay sequential and deterministic
        if len(jobs) <= 4 or self.workers <= 1:
            for job in jobs:
                self.update_job(job)
            return
        # consume the iterator so worker exceptions surface in the logs
        # via update_job's own try/except, not silently in futures
        list(_shared_pool().map(self.update_job, jobs))

    def update_job(self, job) -> None:
        if job.pod_group is None:
            return
        new = job_status(self.ssn, job)
        old = self.ssn.pod_group_status.get(job.uid)
        update_pg = old is None or not (
            _status_equal(old, new)
            and _conditions_equal(old.conditions, new.conditions))
        try:
            self.ssn.cache.update_job_status(job, update_pg)
        except Exception:
            log.exception("failed to update job status for %s", job.uid)
