"""Scheduler configuration: actions string + plugin tiers + per-action args.

Mirrors reference pkg/scheduler/conf/scheduler_conf.go:20-76 and the YAML
unmarshalling in pkg/scheduler/util.go:31-95, including the rejection of
hierarchical DRF combined with the proportion plugin.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import yaml


@dataclass
class PluginOption:
    name: str
    # tri-state enables: None means default-on (defaults.go:22-76)
    enabled_job_order: Optional[bool] = None
    enabled_namespace_order: Optional[bool] = None
    enabled_job_ready: Optional[bool] = None
    enabled_job_pipelined: Optional[bool] = None
    enabled_task_order: Optional[bool] = None
    enabled_preemptable: Optional[bool] = None
    enabled_reclaimable: Optional[bool] = None
    enabled_queue_order: Optional[bool] = None
    enabled_predicate: Optional[bool] = None
    enabled_best_node: Optional[bool] = None
    enabled_node_order: Optional[bool] = None
    enabled_target_job: Optional[bool] = None
    enabled_reserved_nodes: Optional[bool] = None
    enabled_job_enqueued: Optional[bool] = None
    arguments: Dict[str, Any] = field(default_factory=dict)


@dataclass
class Tier:
    plugins: List[PluginOption] = field(default_factory=list)


@dataclass
class Configuration:
    """Per-action arguments block (conf/scheduler_conf.go:66-76)."""
    name: str
    arguments: Dict[str, Any] = field(default_factory=dict)


@dataclass
class SchedulerConfiguration:
    actions: List[str] = field(default_factory=list)
    tiers: List[Tier] = field(default_factory=list)
    configurations: List[Configuration] = field(default_factory=list)

    def arg_of_action(self, name: str) -> Optional[Configuration]:
        for c in self.configurations:
            if c.name == name:
                return c
        return None


# Default configuration (util.go defaultSchedulerConf)
DEFAULT_SCHEDULER_CONF = """
actions: "enqueue, allocate, backfill"
tiers:
- plugins:
  - name: priority
  - name: gang
- plugins:
  - name: drf
  - name: predicates
  - name: proportion
  - name: nodeorder
"""

_CAMEL = {
    "enabledJobOrder": "enabled_job_order",
    "enabledNamespaceOrder": "enabled_namespace_order",
    "enabledJobReady": "enabled_job_ready",
    "enabledJobPipelined": "enabled_job_pipelined",
    "enabledTaskOrder": "enabled_task_order",
    "enabledPreemptable": "enabled_preemptable",
    "enabledReclaimable": "enabled_reclaimable",
    "enabledQueueOrder": "enabled_queue_order",
    "enabledPredicate": "enabled_predicate",
    "enabledBestNode": "enabled_best_node",
    "enabledNodeOrder": "enabled_node_order",
    "enabledTargetJob": "enabled_target_job",
    "enabledReservedNodes": "enabled_reserved_nodes",
    "enabledJobEnqueued": "enabled_job_enqueued",
}


def load_scheduler_conf(text: str) -> SchedulerConfiguration:
    """Parse the scheduler YAML. Raises ValueError on the hdrf+proportion
    conflict like the reference (util.go:73-85)."""
    raw = yaml.safe_load(text) or {}
    conf = SchedulerConfiguration()
    actions = raw.get("actions", "")
    conf.actions = [a.strip() for a in actions.split(",") if a.strip()]

    has_hdrf, has_proportion = False, False
    for tier_raw in raw.get("tiers", []) or []:
        tier = Tier()
        for p in tier_raw.get("plugins", []) or []:
            opt = PluginOption(name=p["name"], arguments=dict(p.get("arguments") or {}))
            for yaml_key, attr in _CAMEL.items():
                if yaml_key in p:
                    setattr(opt, attr, bool(p[yaml_key]))
            if opt.name == "drf" and opt.arguments.get("drf.enableHierarchy"):
                has_hdrf = True
            if opt.name == "proportion":
                has_proportion = True
            tier.plugins.append(opt)
        conf.tiers.append(tier)

    if has_hdrf and has_proportion:
        raise ValueError(
            "proportion and drf with hierarchy are incompatible")

    for c in raw.get("configurations", []) or []:
        conf.configurations.append(
            Configuration(name=c["name"], arguments=dict(c.get("arguments") or {})))
    return conf
