"""Scheduler configuration types + YAML parsing (reference pkg/scheduler/conf
+ pkg/scheduler/util.go:31-95)."""

from .scheduler_conf import (  # noqa: F401
    Configuration, PluginOption, SchedulerConfiguration, Tier,
    DEFAULT_SCHEDULER_CONF, load_scheduler_conf,
)
