"""Env-gated debug assertions (reference pkg/scheduler/util/assert/assert.go).

PANIC_ON_ERROR=false demotes assertion failures to logged errors with a
stack trace; the default (like the reference) raises.
"""

from __future__ import annotations

import logging
import os
import traceback

ENV_PANIC_ON_ERROR = "PANIC_ON_ERROR"

log = logging.getLogger(__name__)

_panic_on_error = os.environ.get(ENV_PANIC_ON_ERROR) != "false"


class AssertionFailed(AssertionError):
    pass


def assert_(condition: bool, message: str) -> None:
    if condition:
        return
    if _panic_on_error:
        raise AssertionFailed(message)
    log.error("%s, %s", message, "".join(traceback.format_stack()))


def assertf(condition: bool, fmt: str, *args) -> None:
    if not condition:
        assert_(condition, fmt % args if args else fmt)
