"""Scheduler helpers (reference util/scheduler_helper.go).

The predicate/score fan-out helpers of the reference became device kernels
(volcano_tpu.ops); what remains host-side is victim validation and the
global resource-reservation state shared by elect/reserve/allocate/enqueue.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..api import NodeInfo, TaskInfo


class ResourceReservation:
    """Global reservation state (scheduler_helper.go:252-262)."""

    def __init__(self):
        self.target_job = None
        self.locked_nodes: Dict[str, NodeInfo] = {}

    def reset(self) -> None:
        self.target_job = None
        self.locked_nodes = {}


#: module-level singleton, like the reference's util.Reservation
reservation = ResourceReservation()


#: adaptive feasible-node sampling floors (scheduler_helper.go:50-69 +
#: cmd/scheduler/app/options/options.go:37-40)
MIN_FEASIBLE_NODES_TO_FIND = 100
MIN_PERCENTAGE_OF_NODES_TO_FIND = 5


class NodeSampler:
    """Adaptive feasible-node sampling for the HOST predicate scan
    (scheduler_helper.go:50-128). The device kernel always scores the full
    padded matrix (cheap on TPU), so this only bounds host-loop work on
    large clusters — kept for config parity with the reference. Instance
    state: each scheduler owns its own rotation cursor."""

    def __init__(self, percentage: int = 100):
        self.percentage = max(0, min(int(percentage), 100))
        self.start = 0

    def feasible_nodes_to_find(self, num_nodes: int) -> int:
        """How many feasible nodes a scan needs before it can stop early;
        clamped UP to the reference's floors."""
        if num_nodes <= MIN_FEASIBLE_NODES_TO_FIND \
                or self.percentage >= 100:
            return num_nodes
        pct = max(self.percentage, MIN_PERCENTAGE_OF_NODES_TO_FIND)
        return max(num_nodes * pct // 100, MIN_FEASIBLE_NODES_TO_FIND)

    def plan(self, nodes: List[NodeInfo]):
        """(rotated node list, stop-early count) for one task's scan."""
        n = len(nodes)
        want = self.feasible_nodes_to_find(n)
        if want >= n:
            return nodes, n
        start = self.start % n
        return nodes[start:] + nodes[:start], want

    def advance(self, visited: int, num_nodes: int) -> None:
        """Move the cursor past every node the scan actually visited
        (nextStartNodeIndex: the next scan starts where this one stopped,
        so an infeasible prefix isn't rescanned per task)."""
        if num_nodes:
            self.start = (self.start + visited) % num_nodes


def validate_victims(preemptor: TaskInfo, node: NodeInfo,
                     victims: List[TaskInfo]) -> Optional[str]:
    """Future idle plus victims' resources must fit the preemptor
    (scheduler_helper.go:234-250). Returns an error string or None."""
    if not victims:
        return "no victims"
    future_idle = node.future_idle()
    for victim in victims:
        future_idle.add(victim.resreq)
    if not preemptor.init_resreq.less_equal(future_idle):
        return (f"not enough resources: requested <{preemptor.init_resreq}>, "
                f"but future idle <{future_idle}>")
    return None
