"""Scheduler helpers (reference util/scheduler_helper.go).

The predicate/score fan-out helpers of the reference became device kernels
(volcano_tpu.ops); what remains host-side is victim validation and the
global resource-reservation state shared by elect/reserve/allocate/enqueue.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..api import NodeInfo, TaskInfo


class ResourceReservation:
    """Global reservation state (scheduler_helper.go:252-262)."""

    def __init__(self):
        self.target_job = None
        self.locked_nodes: Dict[str, NodeInfo] = {}

    def reset(self) -> None:
        self.target_job = None
        self.locked_nodes = {}


#: module-level singleton, like the reference's util.Reservation
reservation = ResourceReservation()


def validate_victims(preemptor: TaskInfo, node: NodeInfo,
                     victims: List[TaskInfo]) -> Optional[str]:
    """Future idle plus victims' resources must fit the preemptor
    (scheduler_helper.go:234-250). Returns an error string or None."""
    if not victims:
        return "no victims"
    future_idle = node.future_idle()
    for victim in victims:
        future_idle.add(victim.resreq)
    if not preemptor.init_resreq.less_equal(future_idle):
        return (f"not enough resources: requested <{preemptor.init_resreq}>, "
                f"but future idle <{future_idle}>")
    return None
