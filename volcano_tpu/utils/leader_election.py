"""Leader election on a Lease object (reference cmd/scheduler/app/server.go:
85-145, cmd/controller-manager/app/server.go:98-127).

The reference elects via a client-go resourcelock against the API server;
here the lock is a Lease record in the ClusterStore (the build's API-server
seam), with the same lease-duration/renew-deadline/retry-period contract and
the same observable behavior: exactly one elector runs its callback at a
time, a crashed leader's lease expires and a standby takes over.

``step()`` drives one acquire-or-renew attempt with an injectable clock so
tests are deterministic; ``run()`` is the wall-clock loop.
"""

from __future__ import annotations

import copy
import threading
import time
import uuid
from typing import Callable, Optional

from volcano_tpu.client.store import ConflictError, NotFoundError
# Lease lives with the models so the wire codec can carry it between
# processes (cross-process HA contends on the lease over the networked
# store; codec.py only reconstructs volcano_tpu.models classes)
from volcano_tpu.models import Lease
from volcano_tpu.models.core import LEASE_DURATION  # noqa: F401 — re-export

RENEW_DEADLINE = 10.0   # server.go:51
RETRY_PERIOD = 5.0      # server.go:52


class LeaseLock:
    """Get/create/update a named Lease in the cluster store."""

    def __init__(self, store, name: str):
        self.store = store
        self.name = name

    def get(self) -> Optional[Lease]:
        try:
            # a copy, so the elector's mutations never leak into the store and
            # the carried resource_version acts as a write precondition
            return copy.copy(self.store.get("leases", self.name))
        except Exception:
            return None

    def create_or_update(self, lease: Lease) -> Lease:
        # A lease the elector read as absent (version 0) must go through
        # create so two racing first-acquirers conflict instead of the second
        # overwriting the first via the version-0 update bypass.
        if lease.resource_version:
            return self.store.update("leases", lease)
        return self.store.create("leases", lease)


class LeaderElector:
    """Acquire the lease, keep renewing, report leadership changes."""

    def __init__(self, lock: LeaseLock, identity: Optional[str] = None,
                 lease_duration: float = LEASE_DURATION,
                 renew_deadline: float = RENEW_DEADLINE,
                 retry_period: float = RETRY_PERIOD,
                 on_started_leading: Optional[Callable[[], None]] = None,
                 on_stopped_leading: Optional[Callable[[], None]] = None,
                 clock: Callable[[], float] = time.time):
        # hostname_uuid uniquifier (server.go:108-110)
        self.identity = identity or f"{uuid.uuid4().hex[:8]}_{uuid.uuid4()}"
        self.lock = lock
        self.lease_duration = lease_duration
        self.renew_deadline = renew_deadline
        self.retry_period = retry_period
        self.on_started_leading = on_started_leading
        self.on_stopped_leading = on_stopped_leading
        self.clock = clock
        self.is_leader = False
        self._last_renew = 0.0
        # fencing: the lease_transitions value of OUR acquisition — the
        # write epoch carried by every fenced store write (FencedStore),
        # so the store can reject a deposed leader's late commit even when
        # the same identity later re-acquires (epoch bumps per transition)
        self.fence_epoch: Optional[int] = None

    # -- one protocol step (testable) ---------------------------------------

    def step(self) -> bool:
        """Try to acquire or renew; returns current leadership."""
        now = self.clock()
        lease = self.lock.get()
        if (self.is_leader and lease is not None
                and lease.holder_identity == self.identity
                and now - self._last_renew < self.retry_period):
            # freshly renewed: don't re-write the lease on every call
            return True
        held_by_other = (
            lease is not None and lease.holder_identity
            and lease.holder_identity != self.identity
            and now < lease.renew_time + lease.lease_duration_seconds)
        if held_by_other:
            if self.is_leader:
                self._lose()
            return False

        if self.is_leader and now - self._last_renew > self.renew_deadline:
            # failed to renew within the deadline: step down (the lease may
            # already have been taken over)
            self._lose()
            return False

        new = lease or Lease(name=self.lock.name)
        if new.holder_identity != self.identity:
            new.lease_transitions += 1
            new.acquire_time = now
        new.holder_identity = self.identity
        new.renew_time = now
        new.lease_duration_seconds = self.lease_duration
        try:
            # chaos seam: a crash (or drop) exactly between deciding to
            # renew and committing the renewal — the window where a
            # deposed-leader split brain is born (resilience/faultinject)
            from ..resilience.faultinject import faults
            faults.fire("lease_renew")
            self.lock.create_or_update(new)
        except ConflictError:
            # another elector wrote the lease between our read and our write:
            # the write with the stale resource_version loses (no split brain)
            cur = self.lock.get()
            if cur is not None and cur.holder_identity == self.identity:
                self._last_renew = now
                self.fence_epoch = cur.lease_transitions
                self._win()
                return True
            if self.is_leader:
                self._lose()
            return False
        except Exception:
            return self.is_leader
        self._last_renew = now
        self.fence_epoch = new.lease_transitions
        self._win()
        return True

    def _win(self) -> None:
        if not self.is_leader:
            self.is_leader = True
            if self.on_started_leading is not None:
                self.on_started_leading()

    def _lose(self) -> None:
        self.is_leader = False
        if self.on_stopped_leading is not None:
            self.on_stopped_leading()

    def fencing_token(self) -> Optional[dict]:
        """The token every fenced store write must carry ({lock, holder,
        epoch}; see client.store.FencedStore), or None when this elector
        does not currently believe it leads — FencedStore then fails the
        write closed instead of writing unfenced."""
        if not self.is_leader or self.fence_epoch is None:
            return None
        return {"lock": self.lock.name, "holder": self.identity,
                "epoch": self.fence_epoch}

    def release(self) -> None:
        """Voluntarily give up the lease (clean shutdown)."""
        lease = self.lock.get()
        if lease is not None and lease.holder_identity == self.identity:
            lease.renew_time = 0.0
            lease.holder_identity = ""
            try:
                self.lock.create_or_update(lease)
            except (ConflictError, NotFoundError):
                pass  # already taken over or deleted; nothing to release
        if self.is_leader:
            self._lose()

    # -- wall-clock loop ----------------------------------------------------

    def run(self, stop: threading.Event,
            release_on_stop: bool = True) -> None:
        """Renew until ``stop``; ``release_on_stop=False`` leaves the
        release to the caller — the SIGTERM contract releases only AFTER
        the async bind effectors drained, so a standby cannot take over
        with this leader's binds still in flight."""
        while not stop.is_set():
            self.step()
            stop.wait(self.retry_period)
        if release_on_stop:
            self.release()
