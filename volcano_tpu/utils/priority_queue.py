"""Heap on an injected less-fn (reference util/priority_queue.go:26-95)."""

from __future__ import annotations

import heapq
import itertools
from typing import Any, Callable


class PriorityQueue:
    """Stable heap ordered by a strict less(l, r) -> bool function."""

    def __init__(self, less_fn: Callable[[Any, Any], bool]):
        self._less = less_fn
        self._heap = []
        self._counter = itertools.count()

    def push(self, item) -> None:
        heapq.heappush(self._heap, _Entry(item, next(self._counter), self._less))

    def pop(self):
        if not self._heap:
            return None
        return heapq.heappop(self._heap).item

    def empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)


class KeySortedQueue:
    """PriorityQueue-shaped wrapper over ONE key-based sort.

    Valid only while the ordering keys are frozen (no session mutation
    between pushes and pops) — solver-mode collection and the enqueue
    action qualify; the host allocate loop, whose comparators read live
    shares, does not. Replaces O(n log n) comparator dispatches (each a
    tier walk over plugin fns) with a single C-speed sort."""

    __slots__ = ("_key", "_items", "_sorted", "_pos")

    def __init__(self, key: Callable[[Any], Any]):
        self._key = key
        self._items = []
        self._sorted = False
        self._pos = 0

    def push(self, item) -> None:
        if self._sorted:  # a post-sort push re-opens the list
            self._items = self._items[self._pos:]
            self._sorted = False
            self._pos = 0
        self._items.append(item)

    def pop(self):
        if not self._sorted:
            self._items.sort(key=self._key)
            self._sorted = True
            self._pos = 0
        if self._pos >= len(self._items):
            return None
        item = self._items[self._pos]
        self._pos += 1
        return item

    def empty(self) -> bool:
        return self._pos >= len(self._items)

    def __len__(self) -> int:
        return len(self._items) - self._pos


class _Entry:
    __slots__ = ("item", "seq", "less")

    def __init__(self, item, seq, less):
        self.item = item
        self.seq = seq
        self.less = less

    def __lt__(self, other) -> bool:
        if self.less(self.item, other.item):
            return True
        if self.less(other.item, self.item):
            return False
        return self.seq < other.seq
