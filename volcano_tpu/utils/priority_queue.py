"""Heap on an injected less-fn (reference util/priority_queue.go:26-95)."""

from __future__ import annotations

import heapq
import itertools
from typing import Any, Callable


class PriorityQueue:
    """Stable heap ordered by a strict less(l, r) -> bool function."""

    def __init__(self, less_fn: Callable[[Any, Any], bool]):
        self._less = less_fn
        self._heap = []
        self._counter = itertools.count()

    def push(self, item) -> None:
        heapq.heappush(self._heap, _Entry(item, next(self._counter), self._less))

    def pop(self):
        if not self._heap:
            return None
        return heapq.heappop(self._heap).item

    def empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)


class _Entry:
    __slots__ = ("item", "seq", "less")

    def __init__(self, item, seq, less):
        self.item = item
        self.seq = seq
        self.less = less

    def __lt__(self, other) -> bool:
        if self.less(self.item, other.item):
            return True
        if self.less(other.item, self.item):
            return False
        return self.seq < other.seq
