"""Scheduler utilities (reference pkg/scheduler/util)."""

from .assert_util import AssertionFailed, assert_, assertf  # noqa: F401
from .leader_election import (  # noqa: F401
    LeaderElector, Lease, LeaseLock,
)
from .priority_queue import KeySortedQueue, PriorityQueue  # noqa: F401
from .scheduler_helper import (  # noqa: F401
    NodeSampler, ResourceReservation, reservation, validate_victims,
)
