"""Scheduler utilities (reference pkg/scheduler/util)."""

from .priority_queue import PriorityQueue  # noqa: F401
