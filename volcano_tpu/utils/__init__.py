"""Scheduler utilities (reference pkg/scheduler/util)."""

from .priority_queue import PriorityQueue  # noqa: F401
from .scheduler_helper import (  # noqa: F401
    ResourceReservation, reservation, validate_victims,
)
