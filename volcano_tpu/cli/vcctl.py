"""vcctl-equivalent CLI (reference cmd/cli/vcctl.go + pkg/cli/*).

Commands: job {run,list,view,suspend,resume,delete},
queue {create,delete,operate,list,get}, version. Operates against a
ClusterStore (in production the gRPC sidecar to the control plane; in
tests/dev an in-memory store). Standalone aliases vsub/vjobs/vqueues/
vcancel/vsuspend/vresume map onto the same verbs.
"""

from __future__ import annotations

import argparse
import time
from typing import List, Optional

import yaml

from .. import __version__
from ..client.store import ClusterStore, NotFoundError
from ..models import (
    Action, Command, Job, JobSpec, Queue, QueueSpec, TaskSpec,
)


def _fmt_age(ts: float) -> str:
    s = int(time.time() - ts)
    if s < 60:
        return f"{s}s"
    if s < 3600:
        return f"{s // 60}m"
    return f"{s // 3600}h"


def _list_versioned(cluster, args, kind: str, **kw):
    """List with staleness surfaced: ``(objects, applied_rv)``. Against
    a remote store (primary or replica) the response's ``applied_rv``
    comes back for display and ``--min-rv`` rides through as the
    rv-bounded read (a replica blocks-or-fails until it has applied that
    rv); the in-process store is its own source of truth, so there is
    nothing to bound or report."""
    lv = getattr(cluster, "list_versioned", None)
    if lv is not None:
        return lv(kind, min_rv=getattr(args, "min_rv", None), **kw)
    return cluster.list(kind, **kw), None


def _rv_footer(applied_rv) -> str:
    if applied_rv is None:
        return ""
    return f"\napplied_rv: {applied_rv}"


def _table(headers: List[str], rows: List[List[str]]) -> str:
    widths = [len(h) for h in headers]
    for row in rows:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(str(cell)))
    lines = ["  ".join(h.ljust(widths[i]) for i, h in enumerate(headers))]
    for row in rows:
        lines.append("  ".join(str(c).ljust(widths[i])
                               for i, c in enumerate(row)))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# job commands (pkg/cli/job)
# ---------------------------------------------------------------------------

def job_run(args, cluster: ClusterStore) -> str:
    if args.filename:
        with open(args.filename) as f:
            raw = yaml.safe_load(f)
        job = _job_from_yaml(raw)
    else:
        requests = {}
        for kv in (args.requests or "").split(","):
            if "=" in kv:
                k, v = kv.split("=", 1)
                requests[k.strip()] = v.strip()
        requests.setdefault("cpu", "1")
        requests.setdefault("memory", "1Gi")
        job = Job(
            name=args.name, namespace=args.namespace,
            spec=JobSpec(
                min_available=args.min_available or args.replicas,
                queue=args.queue,
                scheduler_name=args.scheduler,
                tasks=[TaskSpec(name="task", replicas=args.replicas,
                                template={"spec": {"containers": [{
                                    "name": args.name,
                                    "image": args.image,
                                    "requests": requests}]}})]))
    cluster.create("jobs", job)
    return f"run job {job.name} successfully"


def _policies_from_yaml(raw_policies) -> list:
    from ..models import Event, LifecyclePolicy

    out = []
    for p in raw_policies or []:
        exit_code = p.get("exitCode")
        timeout = p.get("timeout")
        out.append(LifecyclePolicy(
            action=Action(p["action"]) if p.get("action") else Action.SYNC_JOB,
            event=Event(p["event"]) if p.get("event") else None,
            events=[Event(e) for e in p.get("events", [])],
            exit_code=int(exit_code) if exit_code is not None else None,
            timeout_seconds=float(timeout) if timeout is not None else None,
        ))
    return out


def _job_from_yaml(raw: dict) -> Job:
    meta = raw.get("metadata", {})
    spec = raw.get("spec", {})
    tasks = []
    for t in spec.get("tasks", []):
        tasks.append(TaskSpec(name=t.get("name", ""),
                              replicas=int(t.get("replicas", 1)),
                              template=t.get("template", {}),
                              policies=_policies_from_yaml(t.get("policies"))))
    kw = {}
    if spec.get("maxRetry") is not None:
        kw["max_retry"] = int(spec["maxRetry"])
    return Job(
        name=meta.get("name", "job"),
        namespace=meta.get("namespace", "default"),
        spec=JobSpec(
            min_available=int(spec.get("minAvailable", 0)),
            queue=spec.get("queue", ""),
            # empty when the YAML names none: the mutate webhook fills
            # the CONTROL PLANE's scheduler name (its --scheduler-name),
            # which the CLI cannot know
            scheduler_name=spec.get("schedulerName", ""),
            tasks=tasks,
            plugins=spec.get("plugins", {}) or {},
            policies=_policies_from_yaml(spec.get("policies")),
            priority_class_name=spec.get("priorityClassName", ""),
            ttl_seconds_after_finished=(
                int(spec["ttlSecondsAfterFinished"])
                if spec.get("ttlSecondsAfterFinished") is not None else None),
            volumes=spec.get("volumes", []) or [],
            **kw,
        ))


def job_list(args, cluster: ClusterStore) -> str:
    jobs, applied_rv = _list_versioned(cluster, args, "jobs",
                                       namespace=args.namespace)
    rows = []
    for j in sorted(jobs, key=lambda x: x.name):
        st = j.status
        replicas = sum(t.replicas for t in j.spec.tasks)
        rows.append([j.name, _fmt_age(j.creation_timestamp),
                     str(replicas), str(j.spec.min_available),
                     st.state.phase.value, str(st.pending), str(st.running),
                     str(st.succeeded), str(st.failed), str(st.retry_count)])
    return _table(["Name", "Age", "Replicas", "Min", "Phase", "Pending",
                   "Running", "Succeeded", "Failed", "RetryCount"],
                  rows) + _rv_footer(applied_rv)


def job_view(args, cluster: ClusterStore) -> str:
    try:
        j = cluster.get("jobs", args.name, args.namespace)
    except NotFoundError:
        return f"Error: job {args.namespace}/{args.name} not found"
    st = j.status
    lines = [
        f"Name:        {j.name}",
        f"Namespace:   {j.namespace}",
        f"Queue:       {j.spec.queue or 'default'}",
        f"Scheduler:   {j.spec.scheduler_name}",
        f"MinAvailable:{j.spec.min_available}",
        f"Phase:       {st.state.phase.value}",
        f"Version:     {st.version}",
        f"RetryCount:  {st.retry_count}",
        "Tasks:",
    ]
    for t in j.spec.tasks:
        lines.append(f"  - {t.name}: replicas={t.replicas}")
    lines.append(f"Status: pending={st.pending} running={st.running} "
                 f"succeeded={st.succeeded} failed={st.failed}")
    return "\n".join(lines)


def _job_command(args, cluster: ClusterStore, action: Action, verb: str) -> str:
    try:
        job = cluster.get("jobs", args.name, args.namespace)
    except NotFoundError:
        return f"Error: job {args.namespace}/{args.name} not found"
    cluster.create("commands", Command(
        name=f"{verb}-{job.name}-{int(time.time() * 1000) % 100000}",
        namespace=job.namespace, action=action,
        target_object={"kind": "Job", "name": job.name, "uid": job.uid}))
    return f"{verb} job {job.name} successfully"


def job_suspend(args, cluster) -> str:
    return _job_command(args, cluster, Action.ABORT_JOB, "suspend")


def job_resume(args, cluster) -> str:
    return _job_command(args, cluster, Action.RESUME_JOB, "resume")


def job_delete(args, cluster) -> str:
    try:
        cluster.delete("jobs", args.name, args.namespace)
    except NotFoundError:
        return f"Error: job {args.namespace}/{args.name} not found"
    return f"delete job {args.name} successfully"


# ---------------------------------------------------------------------------
# queue commands (pkg/cli/queue)
# ---------------------------------------------------------------------------

def apply_file(args, cluster: ClusterStore) -> str:
    """Apply every document of a (multi-doc) YAML file — Jobs, Queues and
    PodGroups, dispatched by `kind` (the kubectl-apply shape the
    reference's examples assume, e.g. example/hierarchical-jobs)."""
    from ..models import PodGroup, PodGroupSpec

    applied = []
    with open(args.filename) as f:
        docs = [d for d in yaml.safe_load_all(f) if d]
    # validate BEFORE applying anything: a bad document must not leave
    # the file half-applied (kubectl validates the whole file first)
    supported = {"Job", "Queue", "PodGroup"}
    bad = [d.get("kind", "Job") for d in docs
           if d.get("kind", "Job") not in supported]
    if bad:
        return (f"unsupported kind(s) {sorted(set(bad))} in "
                f"{args.filename}; nothing applied")
    for raw in docs:
        kind = raw.get("kind", "Job")
        meta = raw.get("metadata", {})
        if kind == "Job":
            obj = _job_from_yaml(raw)
            cluster.apply("jobs", obj)
        elif kind == "Queue":
            spec = raw.get("spec", {})
            obj = Queue(name=meta.get("name", "queue"),
                        annotations=meta.get("annotations", {}) or {},
                        spec=QueueSpec(
                            weight=int(spec.get("weight", 1)),
                            capability=spec.get("capability", {}) or {}))
            cluster.apply("queues", obj)
        elif kind == "PodGroup":
            spec = raw.get("spec", {})
            obj = PodGroup(
                name=meta.get("name", "podgroup"),
                namespace=meta.get("namespace", "default"),
                annotations=meta.get("annotations", {}) or {},
                spec=PodGroupSpec(
                    min_member=int(spec.get("minMember", 1)),
                    queue=spec.get("queue", "default")))
            cluster.apply("podgroups", obj)
        applied.append(f"{kind.lower()}/{meta.get('name', '?')}")
    return "applied " + ", ".join(applied)


def queue_create(args, cluster: ClusterStore) -> str:
    q = Queue(name=args.name, spec=QueueSpec(weight=args.weight))
    cluster.create("queues", q)
    return f"create queue {q.name} successfully"


def queue_list(args, cluster: ClusterStore) -> str:
    queues, applied_rv = _list_versioned(cluster, args, "queues")
    rows = []
    for q in sorted(queues, key=lambda x: x.name):
        rows.append([q.name, str(q.spec.weight), q.status.state.value,
                     str(q.status.inqueue), str(q.status.pending),
                     str(q.status.running), str(q.status.unknown)])
    return _table(["Name", "Weight", "State", "Inqueue", "Pending",
                   "Running", "Unknown"], rows) + _rv_footer(applied_rv)


def queue_get(args, cluster: ClusterStore) -> str:
    try:
        q = cluster.get("queues", args.name)
    except NotFoundError:
        return f"Error: queue {args.name} not found"
    return _table(["Name", "Weight", "State", "Inqueue", "Pending",
                   "Running", "Unknown"],
                  [[q.name, str(q.spec.weight), q.status.state.value,
                    str(q.status.inqueue), str(q.status.pending),
                    str(q.status.running), str(q.status.unknown)]])


def queue_operate(args, cluster: ClusterStore) -> str:
    try:
        q = cluster.get("queues", args.name)
    except NotFoundError:
        return f"Error: queue {args.name} not found"
    if args.action:
        action = (Action.OPEN_QUEUE if args.action == "open"
                  else Action.CLOSE_QUEUE)
        cluster.create("commands", Command(
            name=f"{args.action}-{q.name}-{int(time.time() * 1000) % 100000}",
            namespace="default", action=action,
            target_object={"kind": "Queue", "name": q.name, "uid": q.uid}))
        return f"{args.action} queue {q.name} successfully"
    if args.weight is not None:
        q.spec.weight = args.weight
        cluster.update("queues", q)
        return f"update queue {q.name} successfully"
    return "Error: nothing to do; specify --action or --weight"


def queue_delete(args, cluster: ClusterStore) -> str:
    try:
        cluster.delete("queues", args.name)
    except NotFoundError:
        return f"Error: queue {args.name} not found"
    return f"delete queue {args.name} successfully"


# ---------------------------------------------------------------------------
# status command (store topology + shard-worker liveness)
# ---------------------------------------------------------------------------

def _admission_table(lanes: dict) -> str:
    """Per-lane admission rows (resilience/overload.py stats shape)."""
    rows = []
    for lane in ("system", "control", "bulk", "read"):
        st = lanes.get(lane)
        if st is None:
            continue
        caps = "/".join(
            "inf" if not st.get(k) else str(st.get(k))
            for k in ("max_inflight", "max_queue", "max_streams"))
        reasons = st.get("shed_reasons") or {}
        rows.append([
            lane,
            str(st.get("inflight", 0)), str(st.get("streams", 0)),
            str(st.get("queued", 0)), str(st.get("admitted", 0)),
            str(st.get("sheds", 0)),
            str(st.get("deadline_expired", 0)),
            ",".join(f"{k}:{v}" for k, v in sorted(reasons.items()))
            or "-",
            caps,
        ])
    return _table(
        ["Lane", "Inflight", "Streams", "Queued", "Admitted", "Sheds",
         "DeadlineExp", "ShedReasons", "Limits(i/q/s)"], rows)


def _fmt_rv(rv) -> str:
    if isinstance(rv, dict):
        return ",".join(f"{sh}:{v}" for sh, v in sorted(rv.items()))
    return str(rv)


def _replica_chain_table(rinfo: dict, cluster) -> str:
    """Walk a replica's upstream chain hop by hop (replica_info on each
    parent until the primary answers store_info) and render one row per
    hop — the tree-debugging view: who feeds whom, how far behind, and
    how many re-bootstraps each hop has absorbed."""
    from ..client.remote import RemoteClusterStore
    rows = []

    def add_row(endpoint: str, info: dict) -> None:
        per = info.get("per_shard") or {}
        lag_r = ",".join(str(per[s].get("lag_records"))
                         for s in sorted(per)) or "-"
        lag_s = ",".join(
            "-" if per[s].get("lag_seconds") is None
            else f"{per[s]['lag_seconds']:.1f}"
            for s in sorted(per)) or "-"
        boots = ",".join(
            f"{k}:{v}" for k, v in
            sorted((info.get("bootstraps") or {}).items())) or "-"
        served = ",".join(
            f"{k}:{v}" for k, v in
            sorted((info.get("ship_served") or {}).items())) or "-"
        rows.append([str(info.get("depth", "?")), endpoint,
                     _fmt_rv(info.get("applied_rv")), lag_r, lag_s,
                     boots, served])

    add_row(f"{cluster.host}:{cluster.port}", rinfo)
    token = getattr(cluster, "token", "") or None
    upstream = rinfo.get("upstream")
    hops = 0
    while upstream and hops < 8:  # defensive: a cycle must not spin
        hops += 1
        c = None
        try:
            c = RemoteClusterStore(upstream, token=token,
                                   direct_routing=False,
                                   retry_attempts=1)
            try:
                uinfo = c._request({"op": "replica_info"})
            except Exception:  # noqa: BLE001 — not a replica: primary?
                uinfo = None
            if uinfo and uinfo.get("ok"):
                add_row(upstream, uinfo)
                upstream = uinfo.get("upstream")
                continue
            sinfo = c._request({"op": "store_info"})
            rows.append(["0", upstream, _fmt_rv(sinfo.get("rv")),
                         "-", "-", "-", "primary"])
            upstream = None
        except Exception as e:  # noqa: BLE001 — best-effort rendering
            rows.append(["?", upstream, "unreachable", "-", "-", "-",
                         f"{type(e).__name__}"])
            upstream = None
        finally:
            if c is not None:
                c.close()
    return _table(
        ["Depth", "Endpoint", "AppliedRv", "Lag(rec)", "Lag(s)",
         "Bootstraps", "ShipServed"], rows)


def status_cmd(args, cluster: ClusterStore) -> str:
    """Control-plane store status: shape, durability, rv(s) — for a
    multi-process sharded deployment, the shard map with per-worker
    endpoint, liveness, pid, restart count, uptime and ingest rate —
    and the overload-admission lane table (inflight / queued / sheds /
    deadline expirations per lane; works against plain, sharded, proc
    and replica endpoints alike)."""
    req = getattr(cluster, "_request", None)
    if req is None:
        shards = getattr(cluster, "n_shards", 1)
        durable = getattr(cluster, "data_dir", None) is not None
        return (f"store: in-process, shards={shards}, "
                f"durable={'yes' if durable else 'no'}, "
                f"rv={getattr(cluster, '_rv', 0)}")
    info = req({"op": "store_info"})
    try:
        topo = req({"op": "topology"})
    except Exception:  # noqa: BLE001 — pre-topology server
        topo = {"n_shards": info.get("shards", 1), "endpoints": []}
    rv = info.get("rv")
    lines = [f"store: shards={topo.get('n_shards', 1)}, "
             f"durable={'yes' if info.get('durable') else 'no'}, "
             f"recovered_records={info.get('recovered', 0)}"]
    workers = topo.get("workers") or []
    if workers:
        rows = []
        for w in workers:
            shard = str(w.get("shard"))
            shard_rv = (rv.get(shard) if isinstance(rv, dict)
                        else (rv if shard == "0" else ""))
            rows.append([shard, w.get("endpoint", ""),
                         "up" if w.get("alive") else "DOWN",
                         str(w.get("pid") or "-"),
                         str(w.get("restarts", 0)),
                         str(w.get("uptime_s", "")),
                         str(w.get("events_per_sec", "")),
                         str(shard_rv if shard_rv is not None else "")])
        lines.append(_table(
            ["Shard", "Endpoint", "State", "Pid", "Restarts",
             "Uptime(s)", "Events/s", "Rv"], rows))
    elif isinstance(rv, dict):
        lines.append(_table(
            ["Shard", "Rv"],
            [[sh, str(v)] for sh, v in sorted(rv.items())])
            + "\n(shards share the server process; no direct endpoints)")
    else:
        lines.append(f"rv: {rv}")
    try:
        rinfo = req({"op": "replica_info"})
    except Exception:  # noqa: BLE001 — not a replica endpoint
        rinfo = None
    if rinfo and rinfo.get("ok"):
        lines.append("replica upstream chain (this endpoint first):")
        lines.append(_replica_chain_table(rinfo, cluster))
    try:
        adm = req({"op": "admission_info"})
    except Exception:  # noqa: BLE001 — pre-admission (old) server
        adm = None
    if adm and adm.get("enabled"):
        lines.append("admission (front-door lanes):")
        lines.append(_admission_table(adm.get("lanes") or {}))
        worker_lanes = adm.get("workers") or {}
        for shard in sorted(worker_lanes, key=lambda s: int(s)):
            wl = worker_lanes[shard]
            if not wl:
                lines.append(f"admission shard {shard}: (worker down)")
                continue
            sheds = sum(st.get("sheds", 0) for st in wl.values())
            if sheds:
                lines.append(f"admission shard {shard} "
                             f"(worker gate, {sheds} sheds):")
                lines.append(_admission_table(wl))
        if worker_lanes and not any(
                sum(st.get("sheds", 0) for st in (wl or {}).values())
                for wl in worker_lanes.values()):
            lines.append(f"(each of the {len(worker_lanes)} shard "
                         "workers runs its own gate; no worker sheds)")
    elif adm is not None:
        lines.append("admission: gate disabled")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# sim command (volcano_tpu.sim: trace-driven scheduling-quality harness)
# ---------------------------------------------------------------------------

def sim_cmd(args, cluster: ClusterStore) -> str:
    """Run the deterministic cluster simulator (record / verify / score).
    Self-contained: the sim builds its own virtual cluster, so the
    --server store (if any) is not touched."""
    import json

    from ..sim import replay as sim_replay
    from ..sim.workload import WORKLOAD_PRESETS, Workload, WorkloadSpec

    spec = WorkloadSpec(seed=args.seed, cycles=args.cycles,
                        nodes=args.nodes, arrival_rate=args.rate,
                        fail_fraction=args.fail_fraction)
    conf = None
    if args.trace:
        workload = Workload.load(args.trace)
    elif args.preset:
        workload = WORKLOAD_PRESETS[args.preset](
            seed=args.seed, cycles=args.cycles, nodes=args.nodes)
        # defrag A/B arms share the binpack conf (see sim/__main__.py)
        from ..sim.virtualcluster import BINPACK_CONF
        conf = BINPACK_CONF
    else:
        workload = Workload(spec)
    reschedule = None
    if args.reschedule_interval > 0:
        reschedule = {
            "interval": args.reschedule_interval,
            "max_moves": args.reschedule_max_moves,
            "max_disruption_per_job": args.reschedule_max_disruption,
        }

    if args.verify:
        rep = sim_replay.verify(args.verify, workload=workload,
                                cycles=args.cycles, mode=args.mode,
                                drain=args.drain,
                                solver_mode=args.solver_mode,
                                sharded_byte_budget=args.sharded_byte_budget,
                                scheduler_conf=conf,
                                reschedule=reschedule)
        status = "replay OK (byte-identical)" if rep["ok"] \
            else "replay DIVERGED"
        out = [f"{status}: {rep['cycles']} cycles, digest {rep['digest']}"]
        if rep["divergence"] is not None:
            out.append(json.dumps(rep["divergence"], sort_keys=True))
        return "\n".join(out)

    result = sim_replay.run_sim(workload=workload, cycles=args.cycles,
                                mode=args.mode, drain=args.drain,
                                record_path=args.record,
                                solver_mode=args.solver_mode,
                                sharded_byte_budget=args.sharded_byte_budget,
                                scheduler_conf=conf,
                                reschedule=reschedule)
    sc = result.score
    out = [
        f"sim: {sc['cycles']} cycles, mode={args.mode}, seed={args.seed}",
        f"jobs: {sc['jobs_arrived']} arrived, {sc['jobs_served']} served, "
        f"{sc['jobs_completed']} completed; {sc['pods_bound']} pods bound",
        f"fragmentation: index {sc['fragmentation_index']}, largest free "
        f"slot {sc['largest_free_slot_mean']}; {sc['migrations']} "
        f"migrations (churn {sc['migration_churn']})",
        f"digest: {result.digest}",
    ]
    # the aggregated FitErrors summaries ("x/y tasks unschedulable: ...")
    # from the final cycle — the same strings the recorder traces
    last = result.vc.recorder.last_record() or {}
    for job, msg in sorted((last.get("unschedulable") or {}).items()):
        out.append(f"unschedulable {job}: {msg}")
    if args.record:
        out.append(f"trace recorded to {args.record}")
    out.append(json.dumps({"score": sc}, sort_keys=True))
    return "\n".join(out)


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="vcctl",
                                description="volcano_tpu command line client")
    p.add_argument("--server", "-s", default=None, metavar="HOST:PORT",
                   help="drive a deployed control plane over TCP "
                        "(standalone --serve-store) instead of an "
                        "in-process store")
    p.add_argument("--replica", default=None, metavar="HOST:PORT",
                   help="route READ commands (job list/view, queue "
                        "list/get) to a read replica (standalone "
                        "--serve-replica) instead of the primary; "
                        "output then reports the replica's applied_rv "
                        "so staleness is visible at a glance. Writes "
                        "still go to --server (a replica refuses them)")
    p.add_argument("--min-rv", type=int, default=None, dest="min_rv",
                   metavar="RV",
                   help="rv-bounded read: block until the (replica) "
                        "store has applied this resource_version, fail "
                        "typed if it cannot within the wait budget — "
                        "read-your-writes against an explicitly stale "
                        "read tier")
    p.add_argument("--token", default=None,
                   help="store auth token (default $VOLCANO_STORE_TOKEN)")
    p.add_argument("--tls-ca", default=None, metavar="PEM",
                   help="verify the store server's TLS cert against this "
                        "CA bundle (default $VOLCANO_STORE_CA)")
    sub = p.add_subparsers(dest="group")

    jobp = sub.add_parser("job")
    jsub = jobp.add_subparsers(dest="verb")
    run = jsub.add_parser("run")
    run.add_argument("--name", "-N", default="job")
    run.add_argument("--namespace", "-n", default="default")
    run.add_argument("--image", "-i", default="busybox")
    run.add_argument("--replicas", "-r", type=int, default=1)
    run.add_argument("--min-available", "-m", type=int, default=0,
                     dest="min_available")
    run.add_argument("--requests", default="cpu=1,memory=1Gi")
    run.add_argument("--scheduler", "-S", default="volcano")
    run.add_argument("--queue", "-q", default="")
    run.add_argument("--filename", "-f", default=None)
    for verb in ("list",):
        v = jsub.add_parser(verb)
        v.add_argument("--namespace", "-n", default=None)
    for verb in ("view", "suspend", "resume", "delete"):
        v = jsub.add_parser(verb)
        v.add_argument("--name", "-N", required=True)
        v.add_argument("--namespace", "-n", default="default")

    queuep = sub.add_parser("queue")
    qsub = queuep.add_subparsers(dest="verb")
    qc = qsub.add_parser("create")
    qc.add_argument("--name", "-n", required=True)
    qc.add_argument("--weight", "-w", type=int, default=1)
    qsub.add_parser("list")
    for verb in ("get", "delete"):
        v = qsub.add_parser(verb)
        v.add_argument("--name", "-n", required=True)
    qo = qsub.add_parser("operate")
    qo.add_argument("--name", "-n", required=True)
    qo.add_argument("--weight", "-w", type=int, default=None)
    qo.add_argument("--action", "-a", choices=["open", "close"], default=None)

    applyp = sub.add_parser("apply")
    applyp.add_argument("--filename", "-f", required=True)

    simp = sub.add_parser(
        "sim", help="trace-driven cluster simulator "
                    "(record/replay/score scheduling quality)")
    simp.add_argument("--cycles", type=int, default=100)
    simp.add_argument("--seed", type=int, default=0)
    simp.add_argument("--solver-mode", default=None,
                      choices=["packed", "sharded", "auto"],
                      help="device-solver routing: packed = single-device "
                           "arena, sharded = node-axis shard_map arena, "
                           "auto = shard when the padded problem exceeds "
                           "--sharded-byte-budget bytes per device "
                           "(applies when --mode is left at its default)")
    simp.add_argument("--sharded-byte-budget", type=int,
                      default=256 * 1024 * 1024,
                      help="per-device resident-state budget for "
                           "--solver-mode auto (bytes; default 256 MiB)")
    simp.add_argument("--mode", default="solver",
                      choices=["solver", "host", "sequential", "sharded"])
    simp.add_argument("--nodes", type=int, default=8)
    simp.add_argument("--rate", type=float, default=1.5)
    simp.add_argument("--fail-fraction", type=float, default=0.0,
                      dest="fail_fraction")
    simp.add_argument("--drain", type=int, default=0)
    simp.add_argument("--record", metavar="PATH", default=None)
    simp.add_argument("--verify", metavar="PATH", default=None)
    simp.add_argument("--trace", metavar="PATH", default=None)
    simp.add_argument("--preset", default=None, choices=["fragmented"],
                      help="named seeded workload preset (the fragmented "
                           "500-cycle defrag baseline)")
    simp.add_argument("--reschedule-interval", type=int, default=0,
                      metavar="N",
                      help="enable the global rescheduler: defrag solve "
                           "every N cycles (0 = off)")
    simp.add_argument("--reschedule-max-moves", type=int, default=8,
                      help="migration budget per defrag plan")
    simp.add_argument("--reschedule-max-disruption-per-job", type=int,
                      default=1, dest="reschedule_max_disruption",
                      help="PDB-style per-job disruption cap per plan")

    sub.add_parser(
        "status", help="store topology + shard-worker liveness "
                       "(per-worker pid/restarts/uptime/ingest against "
                       "a multi-process sharded deployment)")

    sub.add_parser("version")
    return p


_DISPATCH = {
    ("job", "run"): job_run,
    ("job", "list"): job_list,
    ("job", "view"): job_view,
    ("job", "suspend"): job_suspend,
    ("job", "resume"): job_resume,
    ("job", "delete"): job_delete,
    ("queue", "create"): queue_create,
    ("queue", "list"): queue_list,
    ("queue", "get"): queue_get,
    ("queue", "operate"): queue_operate,
    ("queue", "delete"): queue_delete,
    ("apply", None): apply_file,
    ("sim", None): sim_cmd,
    ("status", None): status_cmd,
}

#: standalone binary aliases (cmd/cli/{vsub,vjobs,...})
ALIASES = {
    "vsub": ["job", "run"],
    "vjobs": ["job", "list"],
    "vqueues": ["queue", "list"],
    "vcancel": ["job", "delete"],
    "vsuspend": ["job", "suspend"],
    "vresume": ["job", "resume"],
}


#: (group, verb) pairs safe to serve from a read replica
_READ_VERBS = {("job", "list"), ("job", "view"),
               ("queue", "list"), ("queue", "get"),
               ("status", None)}


def main(argv: List[str], cluster: Optional[ClusterStore] = None) -> str:
    if argv and argv[0] in ALIASES:
        argv = ALIASES[argv[0]] + argv[1:]
    args = build_parser().parse_args(argv)
    verb = getattr(args, "verb", None)
    if cluster is None:
        if args.replica and (args.group, verb) in _READ_VERBS:
            # the read tier: same wire protocol, explicit staleness
            from ..client.remote import RemoteClusterStore
            cluster = RemoteClusterStore(args.replica, token=args.token,
                                         tls_ca=args.tls_ca)
        elif args.server:
            # the wire path of cmd/cli/vcctl.go:44-49 (kubeconfig -> API
            # server); here HOST:PORT -> standalone's StoreServer
            from ..client.remote import RemoteClusterStore
            cluster = RemoteClusterStore(args.server, token=args.token,
                                         tls_ca=args.tls_ca)
        elif args.replica and args.group not in (None, "version", "sim"):
            raise SystemExit(
                f"vcctl {args.group} {verb or ''} mutates the cluster; "
                "a replica is read-only — point --server at the primary")
        elif args.token or args.tls_ca:
            # succeeding against a throwaway in-process store while the
            # user thinks they reached a deployed control plane is a trap
            raise SystemExit(
                "--token/--tls-ca require --server HOST:PORT")
        else:
            cluster = ClusterStore()
    if args.group == "version":
        return f"vcctl version {__version__}"
    fn = _DISPATCH.get((args.group, verb))
    if fn is None:
        return build_parser().format_help()
    return fn(args, cluster)
