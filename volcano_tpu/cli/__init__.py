"""CLI (reference cmd/cli vcctl + pkg/cli)."""

from .vcctl import ALIASES, build_parser, main  # noqa: F401
