import sys

from .vcctl import main

if __name__ == "__main__":
    print(main(sys.argv[1:]))
