"""Global rescheduler: periodic device-solved defragmentation with
bounded, fenced migration plans (see reschedule/action.py).

Public surface:

- ``RescheduleAction`` — the scheduler action (registered as
  ``reschedule``; wire it into the conf's actions string or enable it
  with standalone's ``--reschedule-interval``);
- ``build_plan`` / ``MigrationPlan`` / ``MoveCandidate`` — pure plan
  bounding (budget, per-job disruption caps, no-op rejection);
- ``stranded_fraction`` / ``largest_free_slot`` — the host-side
  fragmentation metrics shared with the sim's quality scoring;
- ``MigrationIntentJournal`` / ``reconcile_migration_intents`` — the
  crash-safe wave journal and its takeover reconciliation pass.
"""

from .action import DEFAULTS, RescheduleAction  # noqa: F401
from .intent import (  # noqa: F401
    MigrationIntentJournal, reconcile_migration_intents,
)
from .plan import (  # noqa: F401
    MIGRATION_REASON, MigrationPlan, MoveCandidate, build_plan,
    largest_free_slot, stranded_fraction,
)
