"""The ``reschedule`` action: periodic device-solved defragmentation.

Every allocate cycle only places *pending* work, so a long-running
cluster accumulates placement history no score function ever revisits —
the descheduler problem. This action closes the loop:

1. **snapshot** the running placement from the session's cache mirror:
   every RUNNING, resource-carrying task of a known job, with its
   current node as the incumbent;
2. **solve the full assignment problem on device** by presenting those
   running tasks as schedulable clones against shadow nodes whose
   migratable usage has been freed — the exact packed solver/arena path
   the allocate action uses (ops/solver.py + ops/device_cache.py), with
   the binpack family forced on so the solve is a global re-pack;
3. **diff** the solved placement against the incumbent one and bound it
   into a hole-punch migration plan (reschedule/plan.py): move budget,
   PDB-style per-job disruption caps, target feasibility, and a minimum
   fragmentation-improvement threshold that rejects no-op churn;
4. **execute** the plan as per-source-node eviction waves through the
   fenced Statement machinery, each wave journaled as a migration
   intent (reschedule/intent.py) BEFORE its evictions dispatch, so a
   leader crash mid-plan reconciles to zero lost / zero duplicate binds.

The evicted pods' replacements re-enter as pending work and the normal
allocate binpack places them onto the consolidating targets — eviction
is the only cluster-visible effect, exactly the reference descheduler's
contract, but the *decision* is one device solve instead of per-pod host
heuristics.

Degradation ladder: breaker open => the action skips the cycle outright
(defragmentation is optional work; it must never compete with placement
for a sick device), and a failed solve costs one skipped pass plus one
breaker failure count — never a scheduling gap.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..api import JobInfo, TaskStatus
from ..framework import Action, Arguments
from ..metrics import metrics
from ..resilience.faultinject import faults
from .intent import MigrationIntentJournal
from .plan import MIGRATION_REASON, MigrationPlan, MoveCandidate, build_plan

log = logging.getLogger(__name__)

#: configuration defaults; deployment flags (--reschedule-*) land in
#: cache.reschedule_opts and per-action conf arguments override both
DEFAULTS = {
    "interval": 10,                # run the defrag solve every N cycles
    "max_moves": 8,                # migration budget per plan
    "max_disruption_per_job": 1,   # PDB-style per-job cap per plan
    "min_improvement": 0.01,       # stranded-fraction gain below which a
                                   # plan is rejected as no-op churn
}

#: bounded in-memory plan history (cache.reschedule_log): tests and the
#: reschedule_defrag bench read per-plan budget/cap compliance from here
LOG_LIMIT = 256


class _State:
    """Cross-session rescheduler state, pinned on the SchedulerCache so
    the defrag solve gets the same arena amortization as allocate."""

    def __init__(self):
        self.cycle = 0
        self.flatten_cache = None     # ops.arrays.FlattenCache
        self.device_cache = None      # ops.device_cache.PackedDeviceCache
        self.journal: Optional[MigrationIntentJournal] = None


class RescheduleAction(Action):
    def name(self) -> str:
        return "reschedule"

    # ------------------------------------------------------------------
    # configuration / state plumbing
    # ------------------------------------------------------------------

    def _resolve_opts(self, ssn) -> dict:
        opts = dict(DEFAULTS)
        opts.update(getattr(ssn.cache, "reschedule_opts", None) or {})
        for conf in ssn.configurations:
            if conf.name != self.name():
                continue
            args = Arguments(conf.arguments)
            opts["interval"] = args.get_int(
                "reschedule.interval", opts["interval"])
            opts["max_moves"] = args.get_int(
                "reschedule.maxMoves", opts["max_moves"])
            opts["max_disruption_per_job"] = args.get_int(
                "reschedule.maxDisruptionPerJob",
                opts["max_disruption_per_job"])
            opts["min_improvement"] = args.get_float(
                "reschedule.minImprovement", opts["min_improvement"])
        return opts

    @staticmethod
    def _state(cache) -> _State:
        state = getattr(cache, "reschedule_state", None)
        if state is None:
            state = _State()
            cache.reschedule_state = state
        return state

    @staticmethod
    def _journal(cache, state: _State):
        """Leader-only, like the bind-intent journal: non-HA embeddings
        pay nothing and need no recovery pass."""
        if getattr(cache, "bind_journal", None) is None:
            state.journal = None
            return None
        if state.journal is None:
            state.journal = MigrationIntentJournal(
                cache.fenced_cluster or cache.cluster,
                identity=getattr(cache.bind_journal, "identity", ""))
        return state.journal

    @staticmethod
    def _log_plan(cache, record: dict) -> None:
        log_ = getattr(cache, "reschedule_log", None)
        if log_ is None:
            log_ = cache.reschedule_log = []
        log_.append(record)
        del log_[:-LOG_LIMIT]

    def _skip(self, timing, reason: str) -> None:
        timing["reschedule_skipped"] = reason
        metrics.reschedule_plans_total.inc(labels={"outcome": reason})

    # ------------------------------------------------------------------
    # snapshot: the running placement as a schedulable shadow problem
    # ------------------------------------------------------------------

    def _collect(self, ssn, ref=None) -> List[Tuple[object, List]]:
        """(job, [stored running tasks]) in deterministic order. Host-only
        jobs (GPU sharing / affinity state the device solver cannot
        model) are never migration candidates, and neither are tasks as
        large as the reference shape — a ref-sized incumbent IS the
        fragmentation victim and has nowhere to land while the cluster
        is fragmented, so it stays pinned as fixed node usage."""
        host_only = ssn.solver_options.get("host_only_jobs") or ()
        out = []
        for job in sorted(ssn.jobs.values(),
                          key=lambda j: (j.creation_timestamp or 0.0,
                                         j.uid)):
            if job.pod_group is None or job.queue not in ssn.queues:
                continue
            if job.uid in host_only:
                continue
            running = job.task_status_index.get(TaskStatus.RUNNING, {})
            tasks = [
                t for t in running.values()
                if not t.resreq.is_empty()
                and t.node_name and t.node_name in ssn.nodes
                and ssn.nodes[t.node_name].node is not None
                and (ref is None or t.resreq.milli_cpu < ref.milli_cpu)
            ]
            if tasks:
                tasks.sort(key=lambda t: (t.pod.creation_timestamp or 0.0,
                                          t.uid))
                out.append((job, tasks))
        return out

    @staticmethod
    def _shadow_problem(ssn, job_order, hole=None, ref=None):
        """Clone world: running tasks as PENDING, their usage freed from
        shadow nodes — the 'empty cluster re-pack' formulation. When a
        hole site is pinned, that shadow node's capacity is HAIRCUT by
        the reference shape, so the device solve itself answers the
        defrag question: which tasks overflow the hole node, and can the
        rest of the cluster absorb them (a gang that cannot be fully
        placed reverts and proposes no moves)."""
        shadow_order = []
        shadow_jobs: Dict[str, JobInfo] = {}
        migratable = set()
        for job, tasks in job_order:
            sj = JobInfo(job.uid)
            sj.name, sj.namespace = job.name, job.namespace
            sj.queue, sj.priority = job.queue, job.priority
            sj.priority_class_name = job.priority_class_name
            sj.creation_timestamp = job.creation_timestamp
            sj.pod_group = job.pod_group
            # gang the shadow at full width: the re-pack either keeps the
            # whole running job placed or (on revert) proposes no moves
            sj.min_available = len(tasks)
            clones = []
            for t in tasks:
                c = t.clone()
                c.status = TaskStatus.PENDING
                c.node_name = ""
                sj.add_task_info(c)
                clones.append(c)
                migratable.add(t.key)
            shadow_jobs[sj.uid] = sj
            shadow_order.append((sj, clones))
        shadow_nodes = {}
        for name, ni in ssn.nodes.items():
            sn = ni.clone()
            for key in list(sn.tasks):
                if key in migratable:
                    sn.remove_task(sn.tasks[key])
            if name == hole and ref is not None:
                from ..api import Resource
                cut = Resource(
                    milli_cpu=min(ref.milli_cpu, sn.idle.milli_cpu),
                    memory=min(ref.memory, sn.idle.memory))
                sn.allocatable = sn.allocatable.clone().sub(cut)
                sn.idle = sn.idle.clone().sub(cut)
            shadow_nodes[name] = sn
        tasks_in_order = [c for _, cs in shadow_order for c in cs]
        return shadow_jobs, shadow_nodes, shadow_order, tasks_in_order

    # ------------------------------------------------------------------
    # the device solve (packed solver over a dedicated arena)
    # ------------------------------------------------------------------

    def _solve(self, ssn, state: _State, arr):
        from ..actions.allocate import build_score_inputs
        from ..ops.device_cache import PackedDeviceCache
        from ..ops.solver import (
            COMPACT_KIND_SHIFT, decode_compact, solve_allocate_delta,
            solve_allocate_packed2d,
        )

        params, families = build_score_inputs(ssn, arr)
        if float(params["binpack_weight"]) == 0.0:
            # defrag IS a packing problem: when the session's conf runs
            # spread-style scoring, force a unit binpack objective so the
            # re-pack consolidates instead of reproducing the spread
            params["binpack_weight"] = np.float32(1.0)
            if "binpack" not in families:
                families = tuple(families) + ("binpack",)
        if state.device_cache is None:
            state.device_cache = PackedDeviceCache()
        dc = state.device_cache
        faults.fire("reschedule_dispatch")
        fbuf, ibuf, layout = arr.packed()
        params = dc.params_device(params)
        kind_, payload = dc.plan_delta(fbuf, ibuf, layout)
        kwargs = dict(herd_mode="pack", score_families=families,
                      use_queue_cap=False, use_drf_order=False,
                      use_hdrf_order=False, work_conserving=True)
        if kind_ == "updated":
            f2d, i2d = payload
            res = solve_allocate_packed2d(f2d, i2d, layout, params,
                                          **kwargs)
        else:
            f2d, i2d, fi, fv, ii, iv = payload
            try:
                res, new_f, new_i = solve_allocate_delta(
                    f2d, i2d, fi, fv, ii, iv, layout, params, **kwargs)
            except Exception:
                dc.invalidate()  # donation may have consumed the buffers
                raise
            dc.commit(new_f, new_i)
        if arr.N <= (1 << COMPACT_KIND_SHIFT):
            assigned, kind = decode_compact(res.compact)
        else:
            assigned = np.asarray(res.assigned)
            kind = np.asarray(res.kind)
        from ..actions.allocate import AllocateAction
        AllocateAction._check_solver_output(
            assigned, kind, arr.T, len(arr.nodes_list))
        return assigned.tolist(), kind.tolist()

    # ------------------------------------------------------------------
    # diff + plan + execute
    # ------------------------------------------------------------------

    @staticmethod
    def _ref_shape(ssn):
        """The reference slot the hole must reach: the largest-cpu
        request shape currently running OR waiting — waiting demand is
        exactly what defragmentation makes room for. Returns a Resource
        (cpu + that task's memory) or None when there is no demand."""
        ref = None
        for job in ssn.jobs.values():
            if job.pod_group is None or job.queue not in ssn.queues:
                continue
            for t in job.tasks.values():
                if not t.resreq.is_empty() and (
                        ref is None
                        or t.resreq.milli_cpu > ref.milli_cpu):
                    ref = t.resreq
        return ref

    @staticmethod
    def _choose_hole(ssn, job_order, ref, per_job_cap: int) \
            -> Optional[str]:
        """The hole site, picked host-side BEFORE the solve so the
        shadow haircut and the plan agree: the node with the most free
        CPU (smallest deficit => fewest moves) among nodes that could
        actually reach the reference shape. A node's vacatable capacity
        counts each job's movers only up to the PDB-style per-job
        disruption cap (largest first, matching the plan's selection
        order), and the deficit must fit the other nodes' combined free
        (the displaced movers need landing capacity). None when no node
        qualifies."""
        per_node_job: Dict[str, Dict[str, List[float]]] = {}
        for job, tasks in job_order:
            for t in tasks:
                per_node_job.setdefault(t.node_name, {}) \
                    .setdefault(job.uid, []).append(t.resreq.milli_cpu)
        vacatable: Dict[str, float] = {}
        for node, jobs in per_node_job.items():
            vacatable[node] = sum(
                sum(sorted(cpus, reverse=True)[:per_job_cap])
                for cpus in jobs.values())
        free = {name: ni.idle.milli_cpu
                for name, ni in ssn.nodes.items() if ni.node is not None}
        total_free = sum(free.values())
        best = None
        for name in sorted(free):
            deficit = ref.milli_cpu - free[name]
            if deficit <= 0:
                continue  # execute() already checked; defensive
            if vacatable.get(name, 0.0) < deficit:
                continue  # even a capped full vacate misses the shape
            if total_free - free[name] < deficit:
                continue  # the displaced movers have nowhere to land
            if best is None or free[name] > free[best]:
                best = name
        return best

    @staticmethod
    def _candidates(arr, job_order, assigned, kind) -> List[MoveCandidate]:
        node_names = [n.name for n in arr.nodes_list]
        cands = []
        idx = 0
        for job, tasks in job_order:
            for t in tasks:
                a, k = assigned[idx], kind[idx]
                idx += 1
                if a < 0 or k != 0:
                    continue  # unplaced or pipelined: never a firm move
                target = node_names[a]
                if target == t.node_name:
                    continue
                cands.append(MoveCandidate(
                    key=t.key, namespace=t.namespace, name=t.name,
                    job_uid=job.uid, from_node=t.node_name,
                    to_node=target, cpu=t.resreq.milli_cpu,
                    mem=t.resreq.memory))
        return cands

    def _execute_plan(self, ssn, plan: MigrationPlan, journal) -> int:
        """Per-source-node eviction waves through the fenced Statement
        machinery; each wave journaled before its evictions dispatch. A
        FencedError from the journal aborts the remainder of the plan —
        a deposed leader must not migrate."""
        from ..client.store import FencedError

        waves: Dict[str, List[MoveCandidate]] = {}
        for m in plan.moves:
            waves.setdefault(m.from_node, []).append(m)
        executed = 0
        for source in sorted(waves):
            wave = waves[source]
            if journal is not None:
                try:
                    journal.record(wave)
                except FencedError:
                    log.error("migration-intent journal fenced; abandoning"
                              " the remainder of the plan (%d waves left)",
                              len(waves) - len([s for s in sorted(waves)
                                                if s < source]))
                    break
                except Exception:  # noqa: BLE001 — journal is best-effort
                    log.exception("migration-intent journal write failed; "
                                  "executing the wave without the record")
            faults.fire("migration_commit")
            stmt = ssn.statement()
            n = 0
            for m in wave:
                job = ssn.jobs.get(m.job_uid)
                task = job.tasks.get(m.key) if job is not None else None
                if task is None or task.status != TaskStatus.RUNNING \
                        or task.node_name != m.from_node:
                    continue  # the landscape moved under the plan
                try:
                    stmt.evict(
                        task,
                        f"{MIGRATION_REASON}: defragmentation -> "
                        f"{m.to_node}")
                    n += 1
                except (KeyError, ValueError):
                    log.exception("migration evict failed for %s", m.key)
            stmt.commit()
            executed += n
        return executed

    # ------------------------------------------------------------------
    # the action
    # ------------------------------------------------------------------

    def execute(self, ssn) -> None:
        from ..ops import flatten_snapshot
        from ..ops.arrays import FlattenCache

        timing = ssn.solver_options.setdefault("timing", {})
        cache = ssn.cache
        opts = self._resolve_opts(ssn)
        state = self._state(cache)
        journal = self._journal(cache, state)
        if journal is not None:
            try:
                journal.sweep()
            except Exception:  # noqa: BLE001 — sweep retries next cycle
                log.exception("migration-intent sweep failed")
        state.cycle += 1
        if opts["interval"] <= 0 \
                or (state.cycle - 1) % opts["interval"] != 0:
            timing["reschedule_skipped"] = "interval"
            return
        breaker = getattr(ssn, "breaker", None)
        if breaker is not None and not breaker.allow():
            # degradation ladder: breaker open => skip the cycle; defrag
            # never probes a sick device and never host-falls-back
            self._skip(timing, "skipped_breaker")
            return

        t0 = time.perf_counter()
        # host-side pre-checks BEFORE any device work: the defrag solve
        # only dispatches when the cluster is actually fragmented (the
        # reference shape fits nowhere) and some node can be made to fit
        # it by vacating migratable movers
        ref = self._ref_shape(ssn)
        free = {name: (ni.idle.milli_cpu, ni.idle.memory)
                for name, ni in ssn.nodes.items() if ni.node is not None}
        if ref is None or not free:
            self._skip(timing, "empty")
            return
        if max(v[0] for v in free.values()) >= ref.milli_cpu:
            self._skip(timing, "fits")
            return
        job_order = self._collect(ssn, ref)
        if not job_order:
            self._skip(timing, "empty")
            return
        hole = self._choose_hole(ssn, job_order, ref,
                                 opts["max_disruption_per_job"])
        if hole is None:
            self._skip(timing, "no_hole")
            return
        shadow_jobs, shadow_nodes, shadow_order, tasks_in_order = \
            self._shadow_problem(ssn, job_order, hole=hole, ref=ref)
        if state.flatten_cache is None:
            state.flatten_cache = FlattenCache()
        arr = flatten_snapshot(
            shadow_jobs, shadow_nodes, tasks_in_order,
            queues=ssn.queues, cache=state.flatten_cache,
            grouped=shadow_order)
        try:
            assigned, kind = self._solve(ssn, state, arr)
        except Exception:
            log.exception("reschedule solve failed; skipping this pass")
            if breaker is not None:
                breaker.record_failure()
            if state.device_cache is not None:
                state.device_cache.invalidate()
            self._skip(timing, "solve_failed")
            return
        if breaker is not None:
            breaker.record_success()
        solve_ms = (time.perf_counter() - t0) * 1e3

        cands = self._candidates(arr, job_order, assigned, kind)
        plan = build_plan(
            cands, free,
            max_moves=opts["max_moves"],
            max_disruption_per_job=opts["max_disruption_per_job"],
            min_improvement=opts["min_improvement"],
            ref_cpu=ref.milli_cpu, hole=hole)

        executed = 0
        if plan.rejected is None:
            executed = self._execute_plan(ssn, plan, journal)
            metrics.reschedule_plans_total.inc(
                labels={"outcome": "executed"})
        else:
            metrics.reschedule_plans_total.inc(
                labels={"outcome": f"rejected_{plan.rejected}"})
        metrics.reschedule_moves_total.inc(
            plan.proposed, labels={"stage": "proposed"})
        metrics.reschedule_moves_total.inc(
            len(plan.moves), labels={"stage": "selected"})
        metrics.reschedule_moves_total.inc(
            executed, labels={"stage": "executed"})
        metrics.reschedule_moves_total.inc(
            plan.capped, labels={"stage": "capped"})
        metrics.reschedule_fragmentation.set(
            plan.frag_before, labels={"phase": "pre"})
        metrics.reschedule_fragmentation.set(
            plan.frag_after, labels={"phase": "post"})
        metrics.reschedule_plan_solve_ms.set(solve_ms)
        timing["reschedule_solve_ms"] = solve_ms
        timing["reschedule_moves_proposed"] = float(plan.proposed)
        timing["reschedule_moves_selected"] = float(len(plan.moves))
        timing["reschedule_moves_executed"] = float(executed)
        timing["reschedule_moves_capped"] = float(plan.capped)
        timing["reschedule_frag_pre"] = plan.frag_before
        timing["reschedule_frag_post"] = plan.frag_after
        record = plan.summary()
        record["executed"] = executed
        record["solve_ms"] = round(solve_ms, 3)
        record["budget"] = opts["max_moves"]
        record["per_job_cap"] = opts["max_disruption_per_job"]
        self._log_plan(cache, record)
