"""Crash-safe migration waves: the write-ahead migration-intent journal
and its takeover reconciliation pass.

The rescheduler executes a plan as per-source-node eviction waves through
the fenced Statement machinery. Before a wave's evictions dispatch, the
whole wave is persisted as ONE ``migrationintents`` store object (the PR-5
bind-intent pattern applied to the *eviction* side of a migration), so a
leader crash mid-plan leaves a durable record of exactly what was in
flight.

Reconciliation is deliberately asymmetric to bind recovery
(resilience/recovery.py): a swallowed BIND is re-driven (the gang must
complete as decided), but a swallowed EVICTION is **abandoned** — the
next reschedule pass re-solves against fresh cluster state, and
re-driving a stale eviction could kill a pod whose migration stopped
making sense the moment the landscape changed. Abandon-don't-redrive
means a crash can only under-migrate, never double-evict, and the bind
side of every migration (the replacement pod's placement) already rides
the allocate path's own bind-intent journal. Net: zero lost and zero
duplicate binds across a mid-migration leader kill, proven by
tests/test_failover.py.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import List, Optional

from ..client.store import FencedError, NotFoundError
from ..models import MigrationIntent

log = logging.getLogger(__name__)

#: sweeps an intent survives with unsettled evictions before it is
#: presumed contained/rolled back and dropped (same rationale as
#: recovery.SWEEP_GENERATIONS: async effectors may land a cycle late)
SWEEP_GENERATIONS = 2


class MigrationIntentJournal:
    """Write-ahead journal of decided migration waves. ``cluster`` should
    be the writer's FENCED store handle so a deposed leader cannot
    journal new waves; reads pass through unfenced."""

    def __init__(self, cluster, identity: str = "", clock=time.time):
        self.cluster = cluster
        self.identity = identity
        self.clock = clock
        self._seq = 0
        self._gen = 0
        #: waves THIS process wrote and has not yet confirmed:
        #: (name, gen, moves)
        self._pending: List[tuple] = []

    def record(self, moves) -> Optional[MigrationIntent]:
        """Persist one intent for a decided wave of MoveCandidates.
        A FencedError propagates: a deposed leader must not migrate."""
        quads = [[m.namespace, m.name, m.from_node, m.to_node]
                 for m in moves]
        if not quads:
            return None
        fencing = None
        token_provider = getattr(self.cluster, "_token_provider", None)
        if token_provider is not None:
            fencing = token_provider()
        self._seq += 1
        intent = MigrationIntent(
            name=f"mi-{uuid.uuid4().hex[:8]}-{self._seq}",
            moves=quads,
            holder=(fencing or {}).get("holder", self.identity),
            epoch=int((fencing or {}).get("epoch", 0)),
            created=self.clock(),
        )
        self.cluster.create("migrationintents", intent)
        self._pending.append((intent.name, self._gen, quads))
        try:
            from ..metrics import metrics
            metrics.reschedule_intents_total.inc(
                labels={"event": "recorded"})
        except Exception:  # noqa: BLE001
            pass
        return intent

    def _settled(self, quads) -> bool:
        """A wave is settled once every decided eviction is visible in
        pod truth: the pod is gone, terminating (deletion_timestamp
        stamped), or already replaced off its source node."""
        for ns, name, from_node, _to in quads:
            pod = self.cluster.try_get("pods", name, ns)
            if pod is None or pod.deletion_timestamp is not None:
                continue
            if pod.node_name and pod.node_name != from_node:
                continue  # already rebound elsewhere
            return False
        return True

    def sweep(self) -> int:
        """Confirm-and-delete waves whose evictions all landed, plus
        waves old enough that their statement must have committed or
        discarded. Returns how many cleared."""
        self._gen += 1
        keep, cleared = [], 0
        for name, gen, quads in self._pending:
            try:
                settled = self._settled(quads)
            except Exception:  # noqa: BLE001 — store away: retry next cycle
                log.exception("migration-intent sweep could not read "
                              "pod truth")
                keep.append((name, gen, quads))
                continue
            if self._gen - gen < SWEEP_GENERATIONS and not settled:
                keep.append((name, gen, quads))
                continue
            try:
                self.cluster.delete("migrationintents", name)
            except NotFoundError:
                pass
            except FencedError:
                keep.append((name, gen, quads))
                break  # deposed mid-sweep: recovery cleans up
            except Exception:  # noqa: BLE001 — retry next cycle
                log.exception("migration-intent sweep failed for %s", name)
                keep.append((name, gen, quads))
                continue
            cleared += 1
        self._pending = keep
        if cleared:
            try:
                from ..metrics import metrics
                metrics.reschedule_intents_total.inc(
                    cleared, labels={"event": "confirmed"})
            except Exception:  # noqa: BLE001
                pass
        return cleared


def reconcile_migration_intents(cluster, fencing_token=None) -> dict:
    """The takeover pass (run at leadership acquisition alongside
    reconcile_bind_intents, BEFORE the first cycle).

    Every surviving intent is settled against pod truth per decided
    eviction:

    - pod gone, terminating, or rebound off its source -> **settled**
      (the wave landed; replacements flow through the normal pipeline);
    - pod still running on its source -> **abandoned** (the eviction
      never dispatched; the remainder of the dead leader's plan is
      dropped, never re-driven — see module docstring).

    The intent is deleted afterwards in every case, so the successor
    starts with a clean migration ledger whose decision trace matches
    pod truth exactly.
    """
    token = fencing_token() if callable(fencing_token) else fencing_token
    summary = {"intents": 0, "settled": 0, "abandoned": 0}
    try:
        intents = cluster.list("migrationintents")
    except Exception:  # noqa: BLE001 — store down: retry next acquisition
        log.exception("migration-intent recovery could not list intents")
        raise
    intents.sort(key=lambda i: (i.created, i.name))
    from ..metrics import metrics
    for intent in intents:
        summary["intents"] += 1
        for ns, name, from_node, _to in intent.moves:
            pod = cluster.try_get("pods", name, ns)
            if pod is None or pod.deletion_timestamp is not None \
                    or (pod.node_name and pod.node_name != from_node):
                outcome = "settled"
            else:
                outcome = "abandoned"
            summary[outcome] += 1
            metrics.reschedule_intents_total.inc(
                labels={"event": outcome})
        try:
            cluster.delete("migrationintents", intent.name, fencing=token)
        except NotFoundError:
            pass
    if summary["intents"]:
        log.warning("migration-intent recovery: %s", summary)
    return summary
