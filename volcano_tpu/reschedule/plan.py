"""Migration planning: diff a device-solved defrag placement against the
incumbent one and bound the result into an executable plan.

Pure host-side code — no jax, no session — so every bounding rule
(move budget, PDB-style per-job disruption caps, target feasibility,
no-op rejection) is unit-testable in isolation. The action
(reschedule/action.py) feeds it the solver's assignment and executes
whatever survives.

Selection policy — **hole punching**. Fragmentation hurts exactly when
the cluster's total free capacity would fit the workload's largest
request shape (``ref_cpu``) but no single node does: the big job queues
while free CPU sits stranded as dust. The durable fix is to concentrate
free capacity on ONE node until that shape fits:

1. reject outright when the shape already fits somewhere (``fits``) —
   rescheduling exists to un-do bad history, not to shuffle a healthy
   cluster;
2. otherwise, at the hole site (pinned by the action, which haircuts
   that node's shadow capacity so the device solve itself decides which
   tasks overflow elsewhere — or, unpinned, every node with outbound
   candidates), take candidates smallest-request-first (biggest-first
   as the fallback when budget/caps leave the small movers short) until
   the node's projected free reaches ``ref_cpu``. Each move is charged
   against the budget and its job's disruption cap, and must have a
   LANDING SITE:
   a non-hole node whose projected free (current free + capacity freed
   by already-selected moves) fits the displaced request — the same
   fullest-that-fits choice the allocate pack scoring will make for the
   replacement pod, so a selected move cannot boomerang back into the
   hole it is punching;
3. keep the cheapest achievable hole (fewest moves, then smallest
   deficit) and reject the plan whole when none is achievable
   (``no_hole``) or when the projected stranded-fraction improvement
   falls below ``min_improvement`` (``no_gain``).

One hole per plan: the interval re-runs the solve against fresh state,
so sustained pressure punches holes one bounded, observable plan at a
time instead of thrashing the cluster toward a global optimum that has
churned away by the time the moves land.

Only evictions execute — each displaced pod's replacement re-enters the
normal allocate solve, whose pack-scoring avoids the (now emptiest)
hole node, so the hole survives precisely because the scorer that
caused the fragmentation now defends it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

#: eviction reason prefix: the sim's churn accounting and the decision
#: trace distinguish defrag migrations from preempt/reclaim victims by it
MIGRATION_REASON = "reschedule"


def stranded_fraction(free: Iterable[float], ref: float) -> float:
    """Fraction of free capacity stranded in slots too small to fit a
    reference request ``ref`` (the workload's largest task shape). 0.0 =
    every free slot is usable (or nothing is free); 1.0 = all free
    capacity is dust. The per-cycle ``fragmentation_index`` the sim
    scores is the mean of this over cycles."""
    total = stranded = 0.0
    for f in free:
        total += f
        if f < ref:
            stranded += f
    if total <= 0.0 or ref <= 0.0:
        return 0.0
    return stranded / total


def largest_free_slot(free: Iterable[float]) -> float:
    vals = list(free)
    return max(vals) if vals else 0.0


@dataclass
class MoveCandidate:
    """One task the solved placement wants somewhere else."""

    key: str           # namespace/name
    namespace: str
    name: str
    job_uid: str
    from_node: str
    to_node: str
    cpu: float         # milli-cpu accounting request
    mem: float         # bytes


@dataclass
class MigrationPlan:
    """The bounded, feasibility-checked output of build_plan."""

    moves: List[MoveCandidate] = field(default_factory=list)
    proposed: int = 0          # raw diff size (solved != incumbent)
    capped: int = 0            # candidates cut by budget/caps/feasibility
    hole_node: str = ""        # the node the plan concentrates free on
    frag_before: float = 0.0
    frag_after: float = 0.0    # projected, over the selected moves only
    largest_before: float = 0.0
    largest_after: float = 0.0
    max_disruption: int = 0    # max moves charged to any single job
    rejected: Optional[str] = None  # None = executable

    @property
    def improvement(self) -> float:
        return self.frag_before - self.frag_after

    def summary(self) -> dict:
        return {
            "proposed": self.proposed,
            "selected": len(self.moves),
            "capped": self.capped,
            "hole_node": self.hole_node,
            "frag_before": round(self.frag_before, 6),
            "frag_after": round(self.frag_after, 6),
            "largest_before": self.largest_before,
            "largest_after": self.largest_after,
            "max_disruption": self.max_disruption,
            "rejected": self.rejected,
        }


def _account_target(trial: Dict[str, List[float]], hole: str,
                    cand: MoveCandidate) -> Optional[str]:
    """Where the displaced task can actually land: the fullest non-hole
    node whose projected free fits it — the same pack-scoring choice the
    allocate action will make for the replacement pod. The solver's
    ``to_node`` stays on the candidate as the advisory target (it came
    from a global repack whose OTHER shuffles this plan does not
    execute), but the budget accounting must be self-consistent against
    the projected free vector."""
    best = None
    for n in sorted(trial):
        if n == hole or n == cand.from_node:
            continue
        f = trial[n]
        if f[0] >= cand.cpu and f[1] >= cand.mem \
                and (best is None or f[0] < trial[best][0]):
            best = n
    return best


def build_plan(candidates: Sequence[MoveCandidate],
               free_cpu_mem: Dict[str, Sequence[float]],
               *,
               max_moves: int,
               max_disruption_per_job: int,
               min_improvement: float,
               ref_cpu: float,
               hole: Optional[str] = None) -> MigrationPlan:
    """Bound the raw placement diff into an executable hole-punch plan.

    ``free_cpu_mem``: node -> (free milli-cpu, free mem bytes) NOW;
    ``ref_cpu`` is the reference slot size the hole must reach — the
    largest request shape currently running or waiting, i.e. what defrag
    is trying to make room for. ``hole`` pins the hole site (the action
    chooses it before the solve so the solver's haircut and the plan
    agree); when None every candidate source node is tried and the
    cheapest achievable hole wins.
    """
    plan = MigrationPlan(proposed=len(candidates))
    free = {n: [float(v[0]), float(v[1])]
            for n, v in free_cpu_mem.items()}
    plan.frag_before = stranded_fraction(
        (v[0] for v in free.values()), ref_cpu)
    plan.largest_before = largest_free_slot(v[0] for v in free.values())
    plan.frag_after = plan.frag_before
    plan.largest_after = plan.largest_before

    def _reject(reason: str) -> MigrationPlan:
        plan.rejected = reason
        plan.capped = len(candidates)
        plan.moves = []
        plan.max_disruption = 0
        return plan

    if not candidates:
        return _reject("empty")
    if max_moves <= 0:
        return _reject("budget")
    if ref_cpu <= 0.0:
        return _reject("empty")
    if plan.largest_before >= ref_cpu:
        # the reference shape already fits somewhere: a healthy cluster,
        # nothing for defrag to un-do
        return _reject("fits")

    by_source: Dict[str, List[MoveCandidate]] = {}
    for c in candidates:
        by_source.setdefault(c.from_node, []).append(c)
    # smallest request first: more moves per hole, but each displaced
    # task re-places easily in a fragmented cluster (a small replacement
    # fits almost anywhere; a large one competes with the very shape the
    # hole is for), so the tail cost of a migration stays bounded.
    # biggest-first is the fallback when the budget or the caps leave
    # the small movers short of the deficit.
    ORDERS = (lambda c: (c.cpu, c.key), lambda c: (-c.cpu, c.key))

    # simulate punching the hole at the pinned site (or every candidate
    # node); keep the cheapest achievable one (fewest moves, then
    # smallest deficit)
    sites = [hole] if hole is not None else sorted(by_source)
    best = None
    for site in sites:
        if site not in free or site not in by_source:
            continue
        deficit = ref_cpu - free[site][0]
        if deficit <= 0:
            continue
        for order in ORDERS:
            trial = {n: list(v) for n, v in free.items()}
            jobs: Dict[str, int] = {}
            moves: List[MoveCandidate] = []
            for c in sorted(by_source[site], key=order):
                if trial[site][0] >= ref_cpu or len(moves) >= max_moves:
                    break
                if jobs.get(c.job_uid, 0) >= max_disruption_per_job:
                    continue
                target = _account_target(trial, site, c)
                if target is None:
                    continue  # the displaced task would boomerang back
                trial[c.from_node][0] += c.cpu
                trial[c.from_node][1] += c.mem
                trial[target][0] -= c.cpu
                trial[target][1] -= c.mem
                jobs[c.job_uid] = jobs.get(c.job_uid, 0) + 1
                moves.append(c)
            if trial[site][0] < ref_cpu or not moves:
                continue
            key = (len(moves), deficit, site)
            if best is None or key < best[0]:
                best = (key, site, moves, trial, jobs)
            break  # this site achieved; don't try the fallback order

    if best is None:
        return _reject("no_hole")
    _, hole, moves, trial, jobs = best
    plan.moves = moves
    plan.capped = len(candidates) - len(moves)
    plan.hole_node = hole
    plan.max_disruption = max(jobs.values()) if jobs else 0
    plan.frag_after = stranded_fraction(
        (v[0] for v in trial.values()), ref_cpu)
    plan.largest_after = largest_free_slot(v[0] for v in trial.values())
    if plan.improvement < min_improvement:
        # no-op churn guard: the projected stranded-fraction gain does
        # not pay for the disruption
        return _reject("no_gain")
    return plan
