"""volcano_tpu: a TPU-native batch scheduling framework.

A from-scratch rebuild of the capabilities of yzs981130/volcano (a Kubernetes
batch scheduler written in Go) designed TPU-first: the per-session
allocate/preempt/backfill decision problem is solved as a batched task x node
constraint-satisfaction kernel under jit/vmap on TPU (volcano_tpu.ops), while
a thin Python control plane keeps the reference's semantics (sessions,
statements, plugins, actions, controllers, admission, CLI).

Layout:
  api/          scheduler data model (Resource algebra, Task/Job/Node/Queue infos)
  models/       CRD-shaped domain objects (batch Job, PodGroup, Queue, Command)
  cache/        cluster-state cache + effector seams (Binder/Evictor/...)
  framework/    Session, Statement, plugin/action registries
  actions/      enqueue, allocate, backfill, preempt, reclaim, elect, reserve
  plugins/      gang, drf, proportion, binpack, predicates, nodeorder, priority, ...
  ops/          JAX/TPU kernels: snapshot flattening, feasibility, scoring, solvers
  parallel/     device-mesh sharding of the solver (shard_map over the node axis)
  controllers/  job/queue/podgroup/gc controllers + job plugins (svc, ssh, env)
  webhooks/     admission validate/mutate
  cli/          vcctl-equivalent CLI
  conf/         scheduler configuration (YAML tiers, hot reload)
  metrics/      prometheus-style metrics registry
  utils/        priority queue, helpers
"""

__version__ = "0.1.0"
