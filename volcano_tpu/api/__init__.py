"""Scheduler data model (reference pkg/scheduler/api)."""

from .cluster_info import ClusterInfo  # noqa: F401
from .device_info import (  # noqa: F401
    GPU_INDEX, GPUDevice, PREDICATE_TIME, VOLCANO_GPU_NUMBER,
    VOLCANO_GPU_RESOURCE, add_gpu_index, get_gpu_index, gpu_resource_of_pod,
    predicate_gpu, remove_gpu_index,
)
from .job_info import (  # noqa: F401
    JobInfo, TaskInfo, job_key_of_pod, pod_key,
    get_pod_resource_request, get_pod_resource_without_init_containers,
    status_of_pod,
)
from .node_info import NodeInfo, NodeState  # noqa: F401
from .queue_info import NamespaceCollection, NamespaceInfo, QueueInfo  # noqa: F401
from .resource import (  # noqa: F401
    MIN_MEMORY, MIN_MILLI_CPU, MIN_MILLI_SCALAR,
    Resource, ResourceVocab, parse_quantity,
)
from .types import (  # noqa: F401
    ALLOCATED_STATUSES, DEFAULT_QUEUE, NodePhase, POD_GROUP_ANNOTATION,
    TaskStatus, allocated_status, compare_float,
)
from .unschedule_info import FitError, FitErrors  # noqa: F401
