"""Core enums and callback typedefs (reference pkg/scheduler/api/types.go)."""

from __future__ import annotations

import enum
import itertools

#: Global, never-repeating flatten-version source. Every mutation of a
#: JobInfo/NodeInfo takes a fresh value instead of incrementing a private
#: counter, so a session clone and the live cache object that diverge after
#: the clone can never alias the same (name, flat_version) flatten-cache key
#: — while an unmutated clone still carries its source's version and keeps
#: the cache warm.
_FLAT_VERSION_COUNTER = itertools.count(1)


def next_flat_version() -> int:
    return next(_FLAT_VERSION_COUNTER)


class TaskStatus(enum.IntEnum):
    """Task lifecycle status (types.go:26-58, bitmask-style iota order kept)."""

    PENDING = 1 << 0     # pod not scheduled yet
    ALLOCATED = 1 << 1   # assigned in session, not yet dispatched
    PIPELINED = 1 << 2   # assigned onto releasing resources
    BINDING = 1 << 3     # bind request sent
    BOUND = 1 << 4       # bound to host
    RUNNING = 1 << 5
    RELEASING = 1 << 6   # being evicted/deleted
    SUCCEEDED = 1 << 7
    FAILED = 1 << 8
    UNKNOWN = 1 << 9

    def __str__(self) -> str:  # parity with Go String()
        return self.name.capitalize()


#: Statuses counted as occupying node resources from the scheduler's
#: perspective (api/helpers.go AllocatedStatus).
ALLOCATED_STATUSES = frozenset(
    {TaskStatus.BOUND, TaskStatus.BINDING, TaskStatus.RUNNING, TaskStatus.ALLOCATED}
)


def allocated_status(status: TaskStatus) -> bool:
    return status in ALLOCATED_STATUSES


class NodePhase(enum.IntEnum):
    READY = 1
    NOT_READY = 2

    def __str__(self) -> str:
        return "Ready" if self is NodePhase.READY else "NotReady"


# Annotation keys (apis/scheduling/v1beta1/labels.go:19-33)
POD_GROUP_ANNOTATION = "scheduling.k8s.io/group-name"
HIERARCHY_ANNOTATION = "volcano.sh/hierarchy"
HIERARCHY_WEIGHT_ANNOTATION = "volcano.sh/hierarchy-weights"
NAMESPACE_WEIGHT_KEY = "volcano.sh/namespace.weight"

DEFAULT_QUEUE = "default"


def compare_float(l: float, r: float, epsilon: float = 1e-6) -> int:
    if abs(l - r) < epsilon:
        return 0
    return -1 if l < r else 1
