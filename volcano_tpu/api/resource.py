"""Resource algebra for the TPU-native scheduler.

Reimplements the semantics of the reference's resource model
(pkg/scheduler/api/resource_info.go:30-420) in a form designed for array
flattening: every Resource can be projected onto a fixed-width float32 vector
(``to_vector``) whose axes are [milli_cpu, memory, *scalars-in-vocab-order] so
that task x node resource math runs as dense tensor ops on TPU.

Thresholds mirror the reference (resource_info.go:70-72):
  minMilliCPU = 10, minMemory = 1, minMilliScalarResources = 10.
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Mapping, Optional

import numpy as np

MIN_MILLI_CPU = 10.0
MIN_MEMORY = 1.0
MIN_MILLI_SCALAR = 10.0

GPU_RESOURCE_NAME = "nvidia.com/gpu"

# Resource-list units understood by parse_quantity (k8s resource.Quantity).
_SUFFIXES = {
    "k": 1e3, "M": 1e6, "G": 1e9, "T": 1e12, "P": 1e15, "E": 1e18,
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60,
}


def parse_quantity(q) -> float:
    """Parse a k8s-style quantity ('100m', '2', '1Gi', 1.5) into a float value.

    CPU-style 'm' suffix means milli; binary/decimal suffixes scale bytes.
    """
    if isinstance(q, (int, float)):
        return float(q)
    s = str(q).strip()
    if not s:
        return 0.0
    if s.endswith("m") and s[:-1].replace(".", "", 1).replace("-", "", 1).isdigit():
        return float(s[:-1]) / 1000.0
    for suf in ("Ki", "Mi", "Gi", "Ti", "Pi", "Ei", "k", "M", "G", "T", "P", "E"):
        if s.endswith(suf):
            return float(s[: -len(suf)]) * _SUFFIXES[suf]
    return float(s)


class Resource:
    """Multi-dimensional resource amount.

    milli_cpu is in millicores, memory in bytes, scalars in milli-units
    (mirrors resource_info.go NewResource which calls MilliValue() on scalars).
    ``max_task_num`` is the pods capacity; it is excluded from arithmetic just
    as in the reference (resource_info.go:38-40).
    """

    __slots__ = ("milli_cpu", "memory", "scalars", "max_task_num")

    def __init__(self, milli_cpu: float = 0.0, memory: float = 0.0,
                 scalars: Optional[Dict[str, float]] = None,
                 max_task_num: int = 0):
        self.milli_cpu = float(milli_cpu)
        self.memory = float(memory)
        self.scalars: Dict[str, float] = dict(scalars) if scalars else {}
        self.max_task_num = int(max_task_num)

    # -- constructors -------------------------------------------------------

    @classmethod
    def empty(cls) -> "Resource":
        return cls()

    @classmethod
    def from_resource_list(cls, rl: Mapping[str, object]) -> "Resource":
        """Build from a k8s ResourceList-shaped mapping.

        {'cpu': '2', 'memory': '4Gi', 'pods': 110, 'nvidia.com/gpu': 1}
        Mirrors NewResource (resource_info.go:75-95): cpu -> millicores,
        memory -> bytes, pods -> max_task_num, other scalars -> milli-units.
        """
        r = cls()
        for name, q in rl.items():
            if name == "cpu":
                r.milli_cpu += parse_quantity(q) * 1000.0
            elif name == "memory":
                r.memory += parse_quantity(q)
            elif name == "pods":
                r.max_task_num += int(parse_quantity(q))
            else:
                r.scalars[name] = r.scalars.get(name, 0.0) + parse_quantity(q) * 1000.0
        return r

    def clone(self) -> "Resource":
        r = Resource.__new__(Resource)  # skip __init__'s re-coercions
        r.milli_cpu = self.milli_cpu
        r.memory = self.memory
        r.scalars = dict(self.scalars)
        r.max_task_num = self.max_task_num
        return r

    @classmethod
    def sum_of(cls, items: Iterable["Resource"]) -> "Resource":
        """Sum many Resources with one result object (the bulk replay/bind
        paths aggregate a job's whole wave into a single accounting delta
        instead of a Resource op per task)."""
        r = cls.__new__(cls)
        mc = mem = 0.0
        sc: Dict[str, float] = {}
        for it in items:
            mc += it.milli_cpu
            mem += it.memory
            if it.scalars:
                for k, v in it.scalars.items():
                    sc[k] = sc.get(k, 0.0) + v
        r.milli_cpu = mc
        r.memory = mem
        r.scalars = sc
        r.max_task_num = 0
        return r

    # -- predicates ---------------------------------------------------------

    def is_empty(self) -> bool:
        """True iff every dimension is below its minimum threshold."""
        if not (self.milli_cpu < MIN_MILLI_CPU and self.memory < MIN_MEMORY):
            return False
        return all(v < MIN_MILLI_SCALAR for v in self.scalars.values())

    def is_zero(self, name: str) -> bool:
        if name == "cpu":
            return self.milli_cpu < MIN_MILLI_CPU
        if name == "memory":
            return self.memory < MIN_MEMORY
        if name not in self.scalars:
            return True
        return self.scalars[name] < MIN_MILLI_SCALAR

    # -- arithmetic (in-place, returning self, like the reference) ----------

    def add(self, rr: "Resource") -> "Resource":
        self.milli_cpu += rr.milli_cpu
        self.memory += rr.memory
        for k, v in rr.scalars.items():
            self.scalars[k] = self.scalars.get(k, 0.0) + v
        return self

    def sub(self, rr: "Resource") -> "Resource":
        """Subtract; requires rr.less_equal(self) like the reference assert."""
        if not rr.less_equal(self):
            raise ValueError(
                f"resource is not sufficient to do operation: <{self}> sub <{rr}>")
        self.milli_cpu -= rr.milli_cpu
        self.memory -= rr.memory
        for k, v in rr.scalars.items():
            self.scalars[k] = self.scalars.get(k, 0.0) - v
        return self

    def multi(self, ratio: float) -> "Resource":
        self.milli_cpu *= ratio
        self.memory *= ratio
        for k in self.scalars:
            self.scalars[k] *= ratio
        return self

    scale = multi

    def set_max_resource(self, rr: "Resource") -> None:
        """Element-wise max, in place (resource_info.go SetMaxResource)."""
        self.milli_cpu = max(self.milli_cpu, rr.milli_cpu)
        self.memory = max(self.memory, rr.memory)
        for k, v in rr.scalars.items():
            if v > self.scalars.get(k, 0.0):
                self.scalars[k] = v

    def fit_delta(self, rr: "Resource") -> "Resource":
        """Availability minus request minus threshold for requested dims."""
        if rr.milli_cpu > 0:
            self.milli_cpu -= rr.milli_cpu + MIN_MILLI_CPU
        if rr.memory > 0:
            self.memory -= rr.memory + MIN_MEMORY
        for k, v in rr.scalars.items():
            if v > 0:
                self.scalars[k] = self.scalars.get(k, 0.0) - (v + MIN_MILLI_SCALAR)
        return self

    def diff(self, rr: "Resource"):
        """Returns (increased, decreased) element-wise deltas vs rr."""
        inc, dec = Resource(), Resource()
        def put(target, name, v):
            if name == "cpu":
                target.milli_cpu = v
            elif name == "memory":
                target.memory = v
            else:
                target.scalars[name] = v
        for name, l, r in self._paired(rr):
            if l > r:
                put(inc, name, l - r)
            else:
                put(dec, name, r - l)
        return inc, dec

    def min_dimension_resource(self, rr: "Resource") -> "Resource":
        """Element-wise min, in place over self's dimensions."""
        self.milli_cpu = min(self.milli_cpu, rr.milli_cpu)
        self.memory = min(self.memory, rr.memory)
        for k in self.scalars:
            self.scalars[k] = min(self.scalars[k], rr.scalars.get(k, 0.0))
        return self

    def get(self, name: str) -> float:
        if name == "cpu":
            return self.milli_cpu
        if name == "memory":
            return self.memory
        return self.scalars.get(name, 0.0)

    def set(self, name: str, value: float) -> None:
        if name == "cpu":
            self.milli_cpu = value
        elif name == "memory":
            self.memory = value
        else:
            self.scalars[name] = value

    def resource_names(self):
        return ["cpu", "memory"] + list(self.scalars)

    # -- comparisons --------------------------------------------------------

    def _paired(self, rr: "Resource"):
        names = set(self.scalars) | set(rr.scalars)
        yield ("cpu", self.milli_cpu, rr.milli_cpu)
        yield ("memory", self.memory, rr.memory)
        for n in sorted(names):
            yield (n, self.scalars.get(n, 0.0), rr.scalars.get(n, 0.0))

    def less(self, rr: "Resource") -> bool:
        """Strict less on every dimension (resource_info.go Less)."""
        if not self.milli_cpu < rr.milli_cpu:
            return False
        if not self.memory < rr.memory:
            return False
        if not self.scalars:
            # reference: empty-left passes unless some right scalar is tiny
            return all(v > MIN_MILLI_SCALAR for v in rr.scalars.values())
        if not rr.scalars:
            return False
        return all(self.scalars[k] < rr.scalars.get(k, 0.0) for k in self.scalars)

    def less_equal_strict(self, rr: "Resource") -> bool:
        if self.milli_cpu > rr.milli_cpu or self.memory > rr.memory:
            return False
        return all(v <= rr.scalars.get(k, 0.0) for k, v in self.scalars.items())

    def less_equal(self, rr: "Resource") -> bool:
        """Threshold-tolerant <= (resource_info.go LessEqual): a dimension
        passes if l < r or |l-r| < min-threshold; scalar dims below the
        threshold are ignored entirely. (Comparisons inlined — this is the
        single hottest host function at 10k tasks/cycle.)"""
        l = self.milli_cpu
        r = rr.milli_cpu
        if l >= r and abs(l - r) >= MIN_MILLI_CPU:
            return False
        l = self.memory
        r = rr.memory
        if l >= r and abs(l - r) >= MIN_MEMORY:
            return False
        if self.scalars:
            rs = rr.scalars
            for k, v in self.scalars.items():
                if v <= MIN_MILLI_SCALAR:
                    continue
                r = rs.get(k, 0.0)
                if v >= r and abs(v - r) >= MIN_MILLI_SCALAR:
                    return False
        return True

    def __eq__(self, other) -> bool:
        if not isinstance(other, Resource):
            return NotImplemented
        return all(l == r for _, l, r in self._paired(other))

    def __repr__(self) -> str:
        sc = ", ".join(f"{k}={v:g}" for k, v in sorted(self.scalars.items()))
        return f"Resource(cpu {self.milli_cpu:g}m, memory {self.memory:g}{', ' + sc if sc else ''})"

    # -- array projection (the TPU seam) ------------------------------------

    def to_vector(self, vocab: "ResourceVocab") -> np.ndarray:
        vec = np.zeros(len(vocab), dtype=np.float32)
        vec[0] = self.milli_cpu
        vec[1] = self.memory
        for k, v in self.scalars.items():
            idx = vocab.index(k)
            if idx is not None:
                vec[idx] = v
        return vec

    @classmethod
    def from_vector(cls, vec, vocab: "ResourceVocab") -> "Resource":
        r = cls(float(vec[0]), float(vec[1]))
        for i, name in enumerate(vocab.scalar_names, start=2):
            if float(vec[i]) != 0.0:
                r.scalars[name] = float(vec[i])
        return r


class ResourceVocab:
    """Fixed ordering of resource dimensions for array flattening.

    Axis 0 = cpu (millicores), axis 1 = memory (bytes), axes 2+ = named
    scalar resources in registration order. The per-dimension minimum
    thresholds (used by the device kernels for LessEqual semantics) are
    exposed as a vector too.
    """

    def __init__(self, scalar_names: Iterable[str] = ()):  # noqa: D401
        self.scalar_names: List[str] = list(dict.fromkeys(scalar_names))
        self._index = {n: i + 2 for i, n in enumerate(self.scalar_names)}

    def __len__(self) -> int:
        return 2 + len(self.scalar_names)

    def index(self, name: str) -> Optional[int]:
        return self._index.get(name)

    def add(self, name: str) -> int:
        if name not in self._index:
            self._index[name] = 2 + len(self.scalar_names)
            self.scalar_names.append(name)
        return self._index[name]

    def thresholds(self) -> np.ndarray:
        t = np.full(len(self), MIN_MILLI_SCALAR, dtype=np.float32)
        t[0] = MIN_MILLI_CPU
        t[1] = MIN_MEMORY
        return t

    @classmethod
    def collect(cls, resources: Iterable[Resource]) -> "ResourceVocab":
        v = cls()
        for r in resources:
            for name in r.scalars:
                v.add(name)
        return v
