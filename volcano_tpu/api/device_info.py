"""GPUDevice: per-card GPU-memory accounting for the GPU-sharing predicate.

Reimplements reference pkg/scheduler/api/device_info.go:24-70,
pod_info.go:81-120 and the NodeInfo GPU helpers (node_info.go:148-170,
342-391). Cards are tracked host-side only: per-card feasibility depends on
which card each sharing pod landed on, so it stays a host predicate (the
allocate action drops to host mode when GPU sharing is enabled).
"""

from __future__ import annotations

import time
from typing import Dict

#: extended resource: total sharable GPU memory of a node / pod request
VOLCANO_GPU_RESOURCE = "volcano.sh/gpu-memory"
#: extended resource: number of physical cards on the node
VOLCANO_GPU_NUMBER = "volcano.sh/gpu-number"
#: pod annotation: the card index the scheduler picked
GPU_INDEX = "volcano.sh/gpu-index"
#: pod annotation: when the predicate decision was made
PREDICATE_TIME = "volcano.sh/predicate-time"


def gpu_resource_of_pod(pod) -> int:
    """GPU memory requested by the pod: sum of container *limits* of
    volcano.sh/gpu-memory (device_info.go:55-70)."""
    total = 0
    for c in pod.containers:
        val = (c.get("limits") or {}).get(VOLCANO_GPU_RESOURCE)
        if val is not None:
            total += int(float(val))
    return total


def get_gpu_index(pod) -> int:
    """The card index assigned via annotation, or -1 (pod_info.go:81-97)."""
    value = (pod.annotations or {}).get(GPU_INDEX)
    if value is None:
        return -1
    try:
        return int(value)
    except ValueError:
        return -1


def add_gpu_index(pod, dev_id: int) -> None:
    """Annotate the pod with its card (pod_info.go AddGPUIndexPatch — the
    JSON-patch becomes a direct annotation write against the store)."""
    pod.annotations[PREDICATE_TIME] = str(time.time_ns())
    pod.annotations[GPU_INDEX] = str(dev_id)


def remove_gpu_index(pod) -> None:
    pod.annotations.pop(PREDICATE_TIME, None)
    pod.annotations.pop(GPU_INDEX, None)


class GPUDevice:
    """One physical card: id, memory, and the pods sharing it
    (device_info.go:24-52)."""

    __slots__ = ("id", "memory", "pod_map")

    def __init__(self, dev_id: int, memory: int):
        self.id = dev_id
        self.memory = memory
        self.pod_map: Dict[str, object] = {}  # pod uid -> Pod

    def used_memory(self) -> int:
        used = 0
        for pod in self.pod_map.values():
            if pod.phase in ("Succeeded", "Failed"):
                continue
            used += gpu_resource_of_pod(pod)
        return used

    def clone(self) -> "GPUDevice":
        d = GPUDevice(self.id, self.memory)
        d.pod_map = dict(self.pod_map)
        return d


def predicate_gpu(pod, node_info) -> int:
    """First card with enough idle memory, or -1 (plugins/predicates/gpu.go
    predicateGPU)."""
    request = gpu_resource_of_pod(pod)
    idle = node_info.devices_idle_gpu_memory()
    for dev_id in sorted(idle):
        if idle[dev_id] >= request:
            return dev_id
    return -1
