"""QueueInfo and NamespaceInfo (reference api/{queue_info,namespace_info}.go)."""

from __future__ import annotations

from typing import Dict, Optional

from .types import (
    HIERARCHY_ANNOTATION,
    HIERARCHY_WEIGHT_ANNOTATION,
    NAMESPACE_WEIGHT_KEY,
)

DEFAULT_NAMESPACE_WEIGHT = 1


class QueueInfo:
    """Scheduling view of a Queue CR (queue_info.go:27-77)."""

    __slots__ = ("uid", "name", "weight", "hierarchy", "weights", "queue")

    def __init__(self, queue):
        self.uid = queue.name
        self.name = queue.name
        self.weight = queue.spec.weight
        ann = queue.annotations or {}
        # '/root/sci' and '1/2' style hierarchical path + weights
        self.hierarchy = ann.get(HIERARCHY_ANNOTATION, "")
        self.weights = ann.get(HIERARCHY_WEIGHT_ANNOTATION, "")
        self.queue = queue

    def clone(self) -> "QueueInfo":
        return QueueInfo(self.queue)

    @property
    def reclaimable(self) -> bool:
        """Queues are reclaimable unless explicitly opted out."""
        r = self.queue.spec.reclaimable
        return True if r is None else bool(r)

    @property
    def capability(self):
        return self.queue.spec.capability

    def __repr__(self) -> str:
        return f"Queue({self.name} weight={self.weight})"


class NamespaceInfo:
    """Namespace weight from ResourceQuota annotation (namespace_info.go)."""

    __slots__ = ("name", "weight")

    def __init__(self, name: str, weight: int = DEFAULT_NAMESPACE_WEIGHT):
        self.name = name
        self.weight = weight

    def get_weight(self) -> int:
        return self.weight if self.weight and self.weight > 0 else DEFAULT_NAMESPACE_WEIGHT


class NamespaceCollection:
    """Tracks quota-derived weights per namespace (namespace_info.go:58-135).

    The reference keeps a heap of quota items; we keep the max weight across
    live quotas, which is the observable behavior (Snapshot takes the head)."""

    def __init__(self, name: str):
        self.name = name
        self._quota_weights: Dict[str, int] = {}

    @staticmethod
    def _quota_weight(quota) -> Optional[int]:
        ann = (quota.annotations or {})
        raw = ann.get(NAMESPACE_WEIGHT_KEY)
        if raw is None:
            return None
        try:
            w = int(raw)
        except (TypeError, ValueError):
            return None
        return w if w > 0 else None

    def update(self, quota) -> None:
        w = self._quota_weight(quota)
        self._quota_weights[quota.name] = w if w is not None else DEFAULT_NAMESPACE_WEIGHT

    def delete(self, quota) -> None:
        self._quota_weights.pop(quota.name, None)

    def snapshot(self) -> NamespaceInfo:
        weight = max(self._quota_weights.values(), default=DEFAULT_NAMESPACE_WEIGHT)
        return NamespaceInfo(self.name, weight)
