"""TaskInfo and JobInfo: the scheduler's view of pods and podgroups.

Reimplements reference pkg/scheduler/api/job_info.go:36-377 semantics on top
of the TPU build's Pod/PodGroup model objects (volcano_tpu.models). The
status-indexed task bookkeeping is kept because gang readiness
(Ready/Pipelined) and the snapshot flattening both read it.
"""

from __future__ import annotations

from typing import Dict, Optional

from .resource import Resource
from .types import (
    ALLOCATED_STATUSES,
    POD_GROUP_ANNOTATION,
    TaskStatus,
    allocated_status,
    next_flat_version,
)
from .unschedule_info import FitErrors


def job_key_of_pod(pod) -> str:
    """JobID for a pod: '<ns>/<group-name annotation>' (job_info.go getJobID)."""
    group = (pod.annotations or {}).get(POD_GROUP_ANNOTATION, "")
    if group:
        return f"{pod.namespace}/{group}"
    return ""


def pod_key(pod) -> str:
    return f"{pod.namespace}/{pod.name}"


def container_requests(c) -> dict:
    """A container's resource requests, accepting both the k8s pod-spec
    shape ({"resources": {"requests": ...}} — what job templates and any
    YAML-born pod carry) and the flat {"requests": ...} shorthand the
    in-process builders use. Without the nested form, template-defined
    jobs silently became best-effort."""
    r = c.get("requests")
    if r is None:
        r = (c.get("resources") or {}).get("requests")
    return r or {}


def get_pod_resource_without_init_containers(pod) -> Resource:
    r = Resource()
    for c in pod.containers:
        r.add(Resource.from_resource_list(container_requests(c)))
    return r


def get_pod_resource_request(pod) -> Resource:
    """Max(sum(containers), max(initContainers)) (k8s launch request)."""
    r = get_pod_resource_without_init_containers(pod)
    for c in pod.init_containers:
        r.set_max_resource(
            Resource.from_resource_list(container_requests(c)))
    return r


def status_of_pod(pod) -> TaskStatus:
    """Map pod phase -> TaskStatus (job_info.go getTaskStatus)."""
    phase = pod.phase
    if phase == "Running":
        return TaskStatus.RELEASING if pod.deletion_timestamp else TaskStatus.RUNNING
    if phase == "Pending":
        if pod.deletion_timestamp:
            return TaskStatus.RELEASING
        return TaskStatus.BOUND if pod.node_name else TaskStatus.PENDING
    if phase == "Unknown":
        return TaskStatus.UNKNOWN
    if phase == "Succeeded":
        return TaskStatus.SUCCEEDED
    if phase == "Failed":
        return TaskStatus.FAILED
    return TaskStatus.UNKNOWN


class TaskInfo:
    """Per-pod scheduling record (job_info.go:36-114)."""

    __slots__ = ("uid", "job", "name", "namespace", "resreq", "init_resreq",
                 "node_name", "status", "priority", "volume_ready", "pod",
                 "sig_cache", "key")

    def __init__(self, pod):
        self.uid = pod.uid
        self.job = job_key_of_pod(pod)
        self.name = pod.name
        self.namespace = pod.namespace
        self.node_name = pod.node_name or ""
        self.status = status_of_pod(pod)
        self.priority = pod.priority if pod.priority is not None else 1
        self.volume_ready = False
        self.pod = pod
        self.resreq = get_pod_resource_without_init_containers(pod)
        self.init_resreq = get_pod_resource_request(pod)
        self.sig_cache = None  # memoized predicate signature (ops.arrays)
        # plain attribute, not a property: pod identity is immutable and
        # the replay/bind waves read key several times per task — the
        # f-string + descriptor cost was measurable at a 10k-task burst
        self.key = f"{self.namespace}/{self.name}"

    def clone(self) -> "TaskInfo":
        t = TaskInfo.__new__(TaskInfo)
        t.uid = self.uid
        t.job = self.job
        t.name = self.name
        t.namespace = self.namespace
        t.node_name = self.node_name
        t.status = self.status
        t.priority = self.priority
        t.volume_ready = self.volume_ready
        t.pod = self.pod
        # resreq/init_resreq are read-only after construction (every
        # consumer passes them as the rr side of Resource add/sub or calls
        # pure predicates), so clones share them — the snapshot clone
        # fan-out at 10k tasks is the scheduler's per-cycle host floor
        t.resreq = self.resreq
        t.init_resreq = self.init_resreq
        t.sig_cache = self.sig_cache
        t.key = self.key
        return t

    def __repr__(self) -> str:
        return (f"Task({self.namespace}/{self.name} job={self.job} "
                f"status={self.status} node={self.node_name!r})")


class JobInfo:
    """Job = PodGroup + its tasks (job_info.go:125-377)."""

    def __init__(self, uid: str, pod_group=None):
        self.uid = uid
        self.name = ""
        self.namespace = ""
        self.queue = ""
        self.priority = 0
        self.min_available = 0
        self.pod_group = None
        self.priority_class_name = ""
        self.creation_timestamp = None
        self.schedule_start_timestamp = None  # set by enqueue

        self.tasks: Dict[str, TaskInfo] = {}
        self.task_status_index: Dict[TaskStatus, Dict[str, TaskInfo]] = {}
        # bumped on any task-set/status/spec mutation; the snapshot
        # flattener's per-job block cache keys on it (ops.arrays)
        self.flat_version = 0
        self.allocated = Resource()
        self.total_request = Resource()
        # maintained sum of PENDING tasks' resreq: lets per-cycle plugin
        # opens (proportion's request attr) be O(jobs) instead of O(tasks)
        self.pending_request = Resource()
        self.nodes_fit_errors: Dict[str, FitErrors] = {}
        # Plugin-readiness bookkeeping (job controller plugins)
        self.job = None  # batch Job CR when known

        if pod_group is not None:
            self.set_pod_group(pod_group)

    # -- podgroup binding ---------------------------------------------------

    def set_pod_group(self, pg) -> None:
        self.flat_version = next_flat_version()
        self.name = pg.name
        self.namespace = pg.namespace
        self.queue = pg.spec.queue
        self.priority_class_name = pg.spec.priority_class_name or ""
        self.min_available = pg.spec.min_member
        self.creation_timestamp = pg.creation_timestamp
        self.pod_group = pg

    # -- task bookkeeping ---------------------------------------------------

    def _add_to_index(self, ti: TaskInfo) -> None:
        self.task_status_index.setdefault(ti.status, {})[ti.key] = ti

    def _remove_from_index(self, ti: TaskInfo) -> None:
        bucket = self.task_status_index.get(ti.status)
        if bucket is not None:
            bucket.pop(ti.key, None)
            if not bucket:
                del self.task_status_index[ti.status]

    def add_task_info(self, ti: TaskInfo) -> None:
        self.flat_version = next_flat_version()
        self.tasks[ti.key] = ti
        self._add_to_index(ti)
        if allocated_status(ti.status):
            self.allocated.add(ti.resreq)
        elif ti.status == TaskStatus.PENDING:
            self.pending_request.add(ti.resreq)
        self.total_request.add(ti.resreq)

    def delete_task_info(self, ti: TaskInfo) -> None:
        task = self.tasks.get(ti.key)
        if task is None:
            raise KeyError(f"failed to find task <{ti.key}> in job <{self.uid}>")
        if allocated_status(task.status):
            self.allocated.sub(task.resreq)
        elif task.status == TaskStatus.PENDING:
            self.pending_request.sub(task.resreq)
        self.total_request.sub(task.resreq)
        del self.tasks[task.key]
        self._remove_from_index(task)
        self.flat_version = next_flat_version()

    def update_task_status(self, ti: TaskInfo, status: TaskStatus) -> None:
        """Delete + reinsert keeping index/aggregates consistent
        (job_info.go:207-224). When ti IS the stored object (the hot
        replay/bind path) the reinsert collapses to an index move plus the
        allocated-aggregate delta — total_request is invariant under a
        status change, so the sub/add pair is skipped."""
        stored = self.tasks.get(ti.key)
        if stored is ti:
            old = ti.status
            was = allocated_status(old)
            self._remove_from_index(ti)
            ti.status = status
            self._add_to_index(ti)
            now = allocated_status(status)
            if was and not now:
                self.allocated.sub(ti.resreq)
            elif now and not was:
                self.allocated.add(ti.resreq)
            if old == TaskStatus.PENDING and status != TaskStatus.PENDING:
                self.pending_request.sub(ti.resreq)
            elif status == TaskStatus.PENDING and old != TaskStatus.PENDING:
                self.pending_request.add(ti.resreq)
            self.flat_version = next_flat_version()
            return
        if stored is not None:
            self.delete_task_info(ti)
        ti.status = status
        self.add_task_info(ti)

    def bulk_update_status(self, tasks, status: TaskStatus) -> None:
        """update_task_status over a whole wave in one pass: index entries
        move via bulk dict ops and the allocated/pending aggregates take one
        summed delta per distinct old status instead of a Resource op per
        task. Observable state is identical to the per-task loop; tasks that
        are not the stored objects fall back to update_task_status (after
        the stored-object part). Used by the solver replay and the batched
        bind (a 10k-pod burst pays ~68us of per-task Python through the
        scalar path, VERDICT r3).

        Atomic on failure for the stored-object part: every aggregate
        subtraction is pre-checked with the same tolerant less_equal sub()
        asserts, so a ValueError raises BEFORE any index or aggregate
        mutation — callers demote the wave to the per-task path, which has
        partial-application semantics the Statement can undo."""
        by_old: Dict[TaskStatus, list] = {}
        foreign: list = []
        for ti in tasks:
            if self.tasks.get(ti.key) is ti:
                if ti.status != status:
                    by_old.setdefault(ti.status, []).append(ti)
            else:
                foreign.append(ti)
        if by_old:
            now = allocated_status(status)
            deltas = []
            alloc_sub = []
            pending_sub = []
            for old, group in by_old.items():
                was = allocated_status(old)
                total = None
                if was != now or (old == TaskStatus.PENDING) != (
                        status == TaskStatus.PENDING):
                    total = Resource.sum_of(t.resreq for t in group)
                    if was and not now:
                        alloc_sub.append(total)
                    if old == TaskStatus.PENDING \
                            and status != TaskStatus.PENDING:
                        pending_sub.append(total)
                deltas.append((old, group, total, was))
            # pre-check the COMBINED subtraction per aggregate (groups may
            # share one) so no sub() can assert after mutation started
            if alloc_sub and not Resource.sum_of(
                    alloc_sub).less_equal(self.allocated):
                raise ValueError(
                    f"bulk status change to {status} exceeds job "
                    f"<{self.uid}> allocated aggregate")
            if pending_sub and not Resource.sum_of(
                    pending_sub).less_equal(self.pending_request):
                raise ValueError(
                    f"bulk status change to {status} exceeds job "
                    f"<{self.uid}> pending aggregate")
            new_bucket = self.task_status_index.setdefault(status, {})
            for old, group, total, was in deltas:
                bucket = self.task_status_index.get(old)
                if bucket is not None:
                    for ti in group:
                        bucket.pop(ti.key, None)
                    if not bucket:
                        del self.task_status_index[old]
                for ti in group:
                    ti.status = status
                    new_bucket[ti.key] = ti
                if total is not None:
                    if was and not now:
                        self.allocated.sub(total)
                    elif now and not was:
                        self.allocated.add(total)
                    if old == TaskStatus.PENDING \
                            and status != TaskStatus.PENDING:
                        self.pending_request.sub(total)
                    elif status == TaskStatus.PENDING \
                            and old != TaskStatus.PENDING:
                        self.pending_request.add(total)
            self.flat_version = next_flat_version()
        for ti in foreign:
            self.update_task_status(ti, status)

    # -- gang readiness -----------------------------------------------------

    def ready_task_num(self) -> int:
        """Allocated-status + succeeded + best-effort pending
        (job_info.go:317-335)."""
        occupied = 0
        for status, tasks in self.task_status_index.items():
            if allocated_status(status) or status == TaskStatus.SUCCEEDED:
                occupied += len(tasks)
            elif status == TaskStatus.PENDING:
                occupied += sum(1 for t in tasks.values()
                                if t.init_resreq.is_empty())
        return occupied

    def waiting_task_num(self) -> int:
        return len(self.task_status_index.get(TaskStatus.PIPELINED, {}))

    def valid_task_num(self) -> int:
        occupied = 0
        for status, tasks in self.task_status_index.items():
            if (allocated_status(status)
                    or status in (TaskStatus.SUCCEEDED, TaskStatus.PIPELINED,
                                  TaskStatus.PENDING)):
                occupied += len(tasks)
        return occupied

    def ready(self) -> bool:
        return self.ready_task_num() >= self.min_available

    def pipelined(self) -> bool:
        return self.waiting_task_num() + self.ready_task_num() >= self.min_available

    # -- misc ---------------------------------------------------------------

    def clone(self) -> "JobInfo":
        j = JobInfo(self.uid)
        j.name, j.namespace, j.queue = self.name, self.namespace, self.queue
        j.priority = self.priority
        j.min_available = self.min_available
        j.pod_group = self.pod_group
        j.priority_class_name = self.priority_class_name
        j.creation_timestamp = self.creation_timestamp
        j.schedule_start_timestamp = self.schedule_start_timestamp
        j.job = self.job
        # bulk form of add_task_info: the indexes are rebuilt wholesale and
        # the aggregates copied instead of re-summed per task — the snapshot
        # clone fan-out is the scheduler's per-cycle floor, so this path is
        # deliberately allocation-lean (cache.go:693-742 clones in a
        # 16-goroutine pool for the same reason)
        tasks = {k: ti.clone() for k, ti in self.tasks.items()}
        j.tasks = tasks
        index: Dict[TaskStatus, Dict[str, TaskInfo]] = {}
        for k, ti in tasks.items():
            bucket = index.get(ti.status)
            if bucket is None:
                index[ti.status] = bucket = {}
            bucket[k] = ti
        j.task_status_index = index
        j.allocated = self.allocated.clone()
        j.total_request = self.total_request.clone()
        j.pending_request = self.pending_request.clone()
        # a clone is the same logical state: carry the version so the
        # per-session snapshot clone keeps the flatten cache warm
        j.flat_version = self.flat_version
        return j

    def fit_message(self) -> str:
        reasons = {str(s): len(t) for s, t in self.task_status_index.items()}
        reasons["minAvailable"] = self.min_available
        parts = sorted(f"{v} {k}" for k, v in reasons.items())
        return f"pod group is not ready, {', '.join(parts)}."

    def __repr__(self) -> str:
        return (f"Job({self.namespace}/{self.name} queue={self.queue} "
                f"minAvailable={self.min_available} tasks={len(self.tasks)})")
