"""NodeInfo: per-node resource accounting (reference api/node_info.go:27-392).

The status-dependent Add/Remove accounting is preserved exactly — it is the
ground truth the device arrays (idle / future-idle columns) are flattened
from each session.
"""

from __future__ import annotations

import itertools
from typing import Dict, Optional

from .device_info import (
    GPUDevice, VOLCANO_GPU_NUMBER, VOLCANO_GPU_RESOURCE, get_gpu_index,
    gpu_resource_of_pod,
)
from .job_info import TaskInfo
from .resource import Resource
from .types import NodePhase, TaskStatus, next_flat_version


class NodeState:
    __slots__ = ("phase", "reason")

    def __init__(self, phase: NodePhase = NodePhase.READY, reason: str = ""):
        self.phase = phase
        self.reason = reason


#: distinguishes a deleted-and-recreated node (fresh version counters) from
#: its predecessor in the flatten cache keys
_EPOCH_COUNTER = itertools.count(1)


class NodeInfo:
    """Mutable per-node scheduling state."""

    def __init__(self, node=None):
        self.name = ""
        self.node = None
        self.state = NodeState(NodePhase.NOT_READY, "init")
        self.releasing = Resource()   # being released by terminating tasks
        self.pipelined = Resource()   # promised to pipelined tasks
        self.idle = Resource()
        self.used = Resource()
        self.allocatable = Resource()
        self.capability = Resource()
        self.tasks: Dict[str, TaskInfo] = {}
        self.others: Dict[str, object] = {}
        # GPU sharing: card id -> GPUDevice (node_info.go:148-170)
        self.gpu_devices: Dict[int, GPUDevice] = {}
        # bumped on any accounting mutation; the snapshot flattener's
        # per-node row cache keys on it (ops.arrays)
        self.flat_version = 0
        # bumped only when the node spec changes (set_node): label/taint
        # predicate masks key on this, so binds don't invalidate them
        self.spec_version = 0
        self.flat_epoch = next(_EPOCH_COUNTER)
        if node is not None:
            self.set_node(node)

    # -- node object sync ---------------------------------------------------

    def _check_ready(self, node) -> bool:
        for cond in node.conditions or []:
            if cond.get("type") == "Ready" and cond.get("status") != "True":
                self.state = NodeState(NodePhase.NOT_READY,
                                       "node is not ready")
                return False
        if node.unschedulable:
            self.state = NodeState(NodePhase.NOT_READY, "node is unschedulable")
            return False
        self.state = NodeState(NodePhase.READY)
        return True

    def set_node(self, node) -> None:
        """Rebuild resource views from node.allocatable, replaying held tasks
        (node_info.go:171-210)."""
        self.flat_version = next_flat_version()
        self.spec_version += 1
        if not self._check_ready(node):
            # Keep self.node unset (reference keeps ni.Node nil) so held
            # tasks skip resource accounting until the node turns ready.
            self.name = node.name
            return
        self.name = node.name
        self.node = node
        self.allocatable = Resource.from_resource_list(node.allocatable)
        self.capability = Resource.from_resource_list(node.capacity)
        self.releasing = Resource()
        self.pipelined = Resource()
        self.idle = Resource.from_resource_list(node.allocatable)
        self.used = Resource()
        self._set_gpu_info(node)
        for ti in self.tasks.values():
            self.add_gpu_resource(ti.pod)
            if ti.status == TaskStatus.RELEASING:
                self.idle.sub(ti.resreq)
                self.releasing.add(ti.resreq)
                self.used.add(ti.resreq)
            elif ti.status == TaskStatus.PIPELINED:
                self.pipelined.add(ti.resreq)
            else:
                self.idle.sub(ti.resreq)
                self.used.add(ti.resreq)

    @property
    def ready(self) -> bool:
        return self.state.phase == NodePhase.READY

    def future_idle(self) -> Resource:
        """idle + releasing - pipelined (node_info.go:57-59)."""
        return self.idle.clone().add(self.releasing).sub(self.pipelined)

    # -- GPU sharing (node_info.go:148-170, 342-391) ------------------------

    def _set_gpu_info(self, node) -> None:
        """Per-card devices from capacity volcano.sh/gpu-{memory,number}."""
        self.gpu_devices = {}
        cap = node.capacity or {}
        total = cap.get(VOLCANO_GPU_RESOURCE)
        count = cap.get(VOLCANO_GPU_NUMBER)
        if not total or not count:
            return
        total, count = int(float(total)), int(float(count))
        if count <= 0:
            return
        per_card = total // count
        for i in range(count):
            self.gpu_devices[i] = GPUDevice(i, per_card)

    def devices_idle_gpu_memory(self) -> Dict[int, int]:
        return {dev_id: dev.memory - dev.used_memory()
                for dev_id, dev in self.gpu_devices.items()}

    def add_gpu_resource(self, pod) -> None:
        # empty-dict check first: most nodes have no shared GPUs, and this
        # runs once per task on the replay/bind hot path
        if not self.gpu_devices or gpu_resource_of_pod(pod) <= 0:
            return
        dev = self.gpu_devices.get(get_gpu_index(pod))
        if dev is not None:
            dev.pod_map[pod.uid] = pod

    def sub_gpu_resource(self, pod) -> None:
        if not self.gpu_devices or gpu_resource_of_pod(pod) <= 0:
            return
        dev = self.gpu_devices.get(get_gpu_index(pod))
        if dev is not None:
            dev.pod_map.pop(pod.uid, None)

    # -- task accounting ----------------------------------------------------

    def _allocate_idle(self, ti: TaskInfo) -> None:
        # sub() itself asserts less_equal; wrapping avoids paying the
        # check twice on the hot path
        try:
            self.idle.sub(ti.resreq)
        except ValueError:
            raise ValueError("selected node NotReady")

    def add_task(self, task: TaskInfo) -> None:
        """Status-dependent accounting (node_info.go:224-266). The node keeps
        a clone so later task status flips don't corrupt node counters."""
        if task.node_name and self.name and task.node_name != self.name:
            raise ValueError(
                f"task <{task.key}> already on different node <{task.node_name}>")
        if task.key in self.tasks:
            raise ValueError(f"task <{task.key}> already on node <{self.name}>")
        self.flat_version = next_flat_version()
        ti = task.clone()
        if self.node is not None:
            if ti.status == TaskStatus.RELEASING:
                self._allocate_idle(ti)
                self.releasing.add(ti.resreq)
                self.used.add(ti.resreq)
            elif ti.status == TaskStatus.PIPELINED:
                self.pipelined.add(ti.resreq)
            else:
                self._allocate_idle(ti)
                self.used.add(ti.resreq)
        task.node_name = self.name
        ti.node_name = self.name
        self.tasks[ti.key] = ti
        self.add_gpu_resource(ti.pod)

    def add_tasks_bulk(self, tasks, validated: bool = False) -> None:
        """add_task over a wave with one summed accounting update. Only
        allocated-status tasks qualify (the replay/bind path: ALLOCATED or
        BINDING waves); anything else — or any per-task validation failure,
        or a wave that doesn't fit idle as a whole — falls back to the
        per-task loop so partial-application + raise semantics stay exactly
        add_task's. ``validated=True`` asserts the caller already ran these
        exact checks (Statement.allocate_bulk / SchedulerCache.bind_batch
        validate per node group before any mutation) so they aren't paid
        twice per task on the replay hot path."""
        fast = self.node is not None
        if fast and not validated:
            seen = set()
            for t in tasks:
                if (t.node_name and self.name and t.node_name != self.name) \
                        or t.key in self.tasks or t.key in seen \
                        or t.status in (TaskStatus.RELEASING,
                                        TaskStatus.PIPELINED):
                    fast = False
                    break
                seen.add(t.key)
            if fast and not Resource.sum_of(
                    t.resreq for t in tasks).less_equal(self.idle):
                fast = False
        if not fast:
            for t in tasks:
                self.add_task(t)
            return
        self.flat_version = next_flat_version()
        # fit was checked wave-wide (the same tolerant less_equal sub()
        # asserts); apply the deltas without paying per-dimension checks
        # again
        idle = self.idle
        used = self.used
        name = self.name
        node_tasks = self.tasks
        for task in tasks:
            rr = task.resreq
            idle.milli_cpu -= rr.milli_cpu
            idle.memory -= rr.memory
            used.milli_cpu += rr.milli_cpu
            used.memory += rr.memory
            if rr.scalars:
                isc = idle.scalars
                usc = used.scalars
                for k, v in rr.scalars.items():
                    isc[k] = isc.get(k, 0.0) - v
                    usc[k] = usc.get(k, 0.0) + v
            ti = task.clone()
            task.node_name = name
            ti.node_name = name
            node_tasks[ti.key] = ti
            self.add_gpu_resource(ti.pod)

    def remove_task(self, ti: TaskInfo) -> None:
        task = self.tasks.get(ti.key)
        if task is None:
            raise KeyError(f"failed to find task <{ti.key}> on host <{self.name}>")
        self.flat_version = next_flat_version()
        if self.node is not None:
            if task.status == TaskStatus.RELEASING:
                self.releasing.sub(task.resreq)
                self.idle.add(task.resreq)
                self.used.sub(task.resreq)
            elif task.status == TaskStatus.PIPELINED:
                self.pipelined.sub(task.resreq)
            else:
                self.idle.add(task.resreq)
                self.used.sub(task.resreq)
        del self.tasks[task.key]
        self.sub_gpu_resource(task.pod)

    def update_task(self, ti: TaskInfo) -> None:
        self.remove_task(ti)
        self.add_task(ti)

    def clone(self) -> "NodeInfo":
        n = NodeInfo()
        n.name = self.name
        n.node = self.node
        n.state = NodeState(self.state.phase, self.state.reason)
        n.releasing = self.releasing.clone()
        n.pipelined = self.pipelined.clone()
        n.idle = self.idle.clone()
        n.used = self.used.clone()
        n.allocatable = self.allocatable.clone()
        n.capability = self.capability.clone()
        n.others = dict(self.others)
        n.gpu_devices = {i: d.clone() for i, d in self.gpu_devices.items()}
        # node-held TaskInfo entries are replace-only: add_task stores a
        # private clone and every later change goes through
        # remove_task/update_task (object replacement), never in-place
        # mutation — so clones share the entries. This halves the snapshot
        # clone fan-out, the scheduler's per-cycle host floor.
        n.tasks = dict(self.tasks)
        n.flat_version = self.flat_version
        n.spec_version = self.spec_version
        n.flat_epoch = self.flat_epoch
        return n

    def pods(self):
        return [t.pod for t in self.tasks.values()]

    def __repr__(self) -> str:
        return f"Node({self.name} idle={self.idle} used={self.used})"
