"""Unschedulability bookkeeping (reference api/unschedule_info.go:20-103)."""

from __future__ import annotations

from typing import Dict, List

ALL_NODES_UNAVAILABLE = "all nodes are unavailable"

# Canonical fit-failure reasons (mirrors k8s / reference message strings)
NODE_RESOURCE_FIT_FAILED = "Insufficient resources"
NODE_UNSCHEDULABLE = "node(s) were unschedulable"
NODE_AFFINITY_FAILED = "node(s) didn't match node selector"
TAINT_FAILED = "node(s) had taints that the pod didn't tolerate"
POD_AFFINITY_FAILED = "node(s) didn't match pod affinity/anti-affinity"
NODE_PORTS_FAILED = "node(s) didn't have free ports for the requested pod ports"
GPU_SHARING_FAILED = "no enough gpu memory on single device"
POD_COUNT_FAILED = "node(s) had too many pods"
VOLUME_BINDING_FAILED = "node(s) didn't match the pod's volume node affinity"
PVC_NOT_FOUND = "persistentvolumeclaim not found"


class FitError:
    """Why one task doesn't fit one node."""

    __slots__ = ("task_namespace", "task_name", "node_name", "reasons")

    def __init__(self, task, node_name: str, reasons: List[str]):
        self.task_namespace = task.namespace
        self.task_name = task.name
        self.node_name = node_name
        self.reasons = list(reasons)

    def error(self) -> str:
        return f"task {self.task_namespace}/{self.task_name} on node {self.node_name} fit failed: {', '.join(self.reasons)}"

    __str__ = error


class FitErrors:
    """Per-task collection of per-node fit errors, histogrammed for the
    PodGroup condition message."""

    def __init__(self):
        self.nodes: Dict[str, FitError] = {}
        self.err: str = ""

    def set_node_error(self, node_name: str, fe: FitError) -> None:
        self.nodes[node_name] = fe

    def set_error(self, err: str) -> None:
        self.err = err

    def error(self) -> str:
        if self.err:
            return self.err
        if not self.nodes:
            return ALL_NODES_UNAVAILABLE
        hist: Dict[str, int] = {}
        for fe in self.nodes.values():
            for r in fe.reasons:
                hist[r] = hist.get(r, 0) + 1
        parts = sorted(f"{c} {r}" for r, c in hist.items())
        return f"0/{len(self.nodes)} nodes are available: {', '.join(parts)}."

    __str__ = error
