"""Unschedulability bookkeeping (reference api/unschedule_info.go:20-103)."""

from __future__ import annotations

from typing import Dict, List

ALL_NODES_UNAVAILABLE = "all nodes are unavailable"

# Canonical fit-failure reasons (mirrors k8s / reference message strings)
NODE_RESOURCE_FIT_FAILED = "Insufficient resources"
NODE_UNSCHEDULABLE = "node(s) were unschedulable"
NODE_AFFINITY_FAILED = "node(s) didn't match node selector"
TAINT_FAILED = "node(s) had taints that the pod didn't tolerate"
POD_AFFINITY_FAILED = "node(s) didn't match pod affinity/anti-affinity"
NODE_PORTS_FAILED = "node(s) didn't have free ports for the requested pod ports"
GPU_SHARING_FAILED = "no enough gpu memory on single device"
POD_COUNT_FAILED = "node(s) had too many pods"
VOLUME_BINDING_FAILED = "node(s) didn't match the pod's volume node affinity"
PVC_NOT_FOUND = "persistentvolumeclaim not found"


class FitError:
    """Why one task doesn't fit one node."""

    __slots__ = ("task_namespace", "task_name", "node_name", "reasons")

    def __init__(self, task, node_name: str, reasons: List[str]):
        self.task_namespace = task.namespace
        self.task_name = task.name
        self.node_name = node_name
        self.reasons = list(reasons)

    def error(self) -> str:
        return f"task {self.task_namespace}/{self.task_name} on node {self.node_name} fit failed: {', '.join(self.reasons)}"

    __str__ = error


def aggregate_fit_errors(fit_errors_by_task: Dict[str, "FitErrors"],
                         total_tasks: int) -> str:
    """Aggregate a job's per-task FitErrors into the stable, deduplicated
    summary the reference posts as the PodGroup event message:
    ``"x/y tasks unschedulable: reason (count), ..."``.

    Each task contributes every DISTINCT reason once (a task failing the
    same predicate on 500 nodes counts one, not 500), counts are the
    number of tasks citing the reason, and the ordering is count-desc
    then alphabetical — byte-stable across runs, so the sim recorder can
    put it in golden traces and ``vcctl sim`` can print it verbatim."""
    hist: Dict[str, int] = {}
    for fe in fit_errors_by_task.values():
        if fe.err:
            reasons = {fe.err}
        elif fe.nodes:
            reasons = {r for node_fe in fe.nodes.values()
                       for r in node_fe.reasons}
        else:
            reasons = {ALL_NODES_UNAVAILABLE}
        for r in reasons:
            hist[r] = hist.get(r, 0) + 1
    parts = [f"{r} ({c})"
             for r, c in sorted(hist.items(), key=lambda kv: (-kv[1], kv[0]))]
    return (f"{len(fit_errors_by_task)}/{total_tasks} tasks unschedulable: "
            f"{', '.join(parts)}")


class FitErrors:
    """Per-task collection of per-node fit errors, histogrammed for the
    PodGroup condition message."""

    def __init__(self):
        self.nodes: Dict[str, FitError] = {}
        self.err: str = ""

    def set_node_error(self, node_name: str, fe: FitError) -> None:
        self.nodes[node_name] = fe

    def set_error(self, err: str) -> None:
        self.err = err

    def error(self) -> str:
        if self.err:
            return self.err
        if not self.nodes:
            return ALL_NODES_UNAVAILABLE
        hist: Dict[str, int] = {}
        for fe in self.nodes.values():
            for r in fe.reasons:
                hist[r] = hist.get(r, 0) + 1
        parts = sorted(f"{c} {r}" for r, c in hist.items())
        return f"0/{len(self.nodes)} nodes are available: {', '.join(parts)}."

    __str__ = error
