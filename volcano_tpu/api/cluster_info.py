"""ClusterInfo: the per-session snapshot container (reference api/cluster_info.go)."""

from __future__ import annotations

from typing import Dict

from .job_info import JobInfo
from .node_info import NodeInfo
from .queue_info import NamespaceInfo, QueueInfo


class ClusterInfo:
    def __init__(self):
        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.namespace_info: Dict[str, NamespaceInfo] = {}

    def __repr__(self) -> str:
        return (f"ClusterInfo(jobs={len(self.jobs)} nodes={len(self.nodes)} "
                f"queues={len(self.queues)})")
