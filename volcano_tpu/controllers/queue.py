"""Queue controller (reference pkg/controllers/queue).

Aggregates podgroup phase counts into QueueStatus and runs the
{Open, Closed, Closing, Unknown} state machine driven by spec.state and
Open/CloseQueue commands.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from ..client.store import ClusterStore, NotFoundError
from ..models import Action, PodGroupPhase, Queue, QueueState
from .framework import Controller, ControllerOption

log = logging.getLogger(__name__)


class QueueController(Controller):
    def __init__(self):
        self.cluster: Optional[ClusterStore] = None
        self.queue: List[str] = []  # queue names to sync

    def name(self) -> str:
        return "queue-controller"

    def initialize(self, opt: ControllerOption) -> None:
        self.default_queue = opt.default_queue
        self.cluster = opt.cluster

    def run(self) -> None:
        self.cluster.watch("queues", self._on_queue)
        self.cluster.watch("podgroups", self._on_podgroup)
        self.cluster.watch("commands", self._on_command)

    def _on_queue(self, event, queue: Queue, old) -> None:
        if event != "delete":
            self.queue.append(queue.name)

    def _on_podgroup(self, event, pg, old) -> None:
        queue = pg.spec.queue or self.default_queue
        self.queue.append(queue)

    def _on_command(self, event, cmd, old) -> None:
        if event != "add":
            return
        target = cmd.target_object or {}
        if target.get("kind") != "Queue":
            return
        try:
            self.cluster.delete("commands", cmd.name, cmd.namespace)
        except NotFoundError:
            pass
        queue = self.cluster.try_get("queues", target.get("name", ""))
        if queue is None:
            return
        if cmd.action == Action.OPEN_QUEUE:
            queue.spec.state = QueueState.OPEN
        elif cmd.action == Action.CLOSE_QUEUE:
            queue.spec.state = QueueState.CLOSED
        self.cluster.update("queues", queue)
        self.queue.append(queue.name)

    def process_all(self, max_rounds: int = 4) -> None:
        for _ in range(max_rounds):
            names, self.queue = list(dict.fromkeys(self.queue)), []
            if not names:
                return
            for name in names:
                try:
                    self.sync_queue(name)
                except Exception:
                    log.exception("failed to sync queue %s", name)

    def sync_queue(self, name: str) -> None:
        """queue_controller_action.go:35-84 + state machine."""
        queue = self.cluster.try_get("queues", name)
        if queue is None:
            return
        counts = {"pending": 0, "running": 0, "unknown": 0, "inqueue": 0}
        pgs = self.cluster.list("podgroups")
        has_pgs = False
        for pg in pgs:
            if (pg.spec.queue or self.default_queue) != name:
                continue
            has_pgs = True
            phase = pg.status.phase
            if phase == PodGroupPhase.PENDING:
                counts["pending"] += 1
            elif phase == PodGroupPhase.RUNNING:
                counts["running"] += 1
            elif phase == PodGroupPhase.INQUEUE:
                counts["inqueue"] += 1
            else:
                counts["unknown"] += 1
        desired = queue.spec.state or QueueState.OPEN
        if desired == QueueState.OPEN:
            state = QueueState.OPEN
        elif desired == QueueState.CLOSED:
            # closing while podgroups remain (queue/state machine)
            state = QueueState.CLOSING if has_pgs else QueueState.CLOSED
        else:
            state = QueueState.UNKNOWN

        st = queue.status
        if (st.pending, st.running, st.inqueue, st.unknown, st.state) \
                == (counts["pending"], counts["running"],
                    counts["inqueue"], counts["unknown"], state):
            # no-op sync: writing an identical status would churn the
            # store every controller pass (and re-enqueue this very
            # queue via our own update event — a self-perpetuating write
            # loop), which alone keeps a quiet cluster's event-sourced
            # flatten/ordering from ever reaching their zero-work paths
            return
        st.pending = counts["pending"]
        st.running = counts["running"]
        st.inqueue = counts["inqueue"]
        st.unknown = counts["unknown"]
        st.state = state
        self.cluster.update("queues", queue)
