"""Controllers (reference pkg/controllers).

ControllerManager wires every controller to a ClusterStore and drains them;
the reference runs them under leader election in controller-manager.
"""

from .apis import JobInfo, Request  # noqa: F401
from .framework import (  # noqa: F401
    Controller, ControllerOption, register_controller,
)
from .garbagecollector import GarbageCollector  # noqa: F401
from .job import JobController  # noqa: F401
from .podgroup import PodGroupController  # noqa: F401
from .queue import QueueController  # noqa: F401


class ControllerManager:
    """cmd/controller-manager equivalent: initialize + run all controllers
    against one cluster store; process_all() drains every controller's
    queue (single-core stand-in for the per-controller worker loops)."""

    def __init__(self, cluster, scheduler_name: str = "volcano",
                 worker_num: int = 3):
        self.opt = ControllerOption(cluster=cluster,
                                    scheduler_name=scheduler_name,
                                    worker_num=worker_num)
        self.controllers = [
            JobController(),
            QueueController(),
            PodGroupController(),
            GarbageCollector(),
        ]
        for ctrl in self.controllers:
            ctrl.initialize(self.opt)

    def run(self) -> None:
        for ctrl in self.controllers:
            ctrl.run()

    def process_all(self, rounds: int = 4) -> None:
        for _ in range(rounds):
            for ctrl in self.controllers:
                ctrl.process_all()
