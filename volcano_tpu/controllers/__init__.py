"""Controllers (reference pkg/controllers).

ControllerManager wires every controller to a ClusterStore and drains them;
the reference runs them under leader election in controller-manager.
"""

from .apis import JobInfo, Request  # noqa: F401
from .framework import (  # noqa: F401
    Controller, ControllerOption, register_controller,
)
from .garbagecollector import GarbageCollector  # noqa: F401
from .job import JobController  # noqa: F401
from .kubelet import KubeletStandin  # noqa: F401
from .podgroup import PodGroupController  # noqa: F401
from .queue import QueueController  # noqa: F401


class _WatchCollector:
    """Stands in for the cluster while a controller's run() subscribes:
    records (kind, listener) pairs instead of opening per-kind streams,
    so the manager can open them all as ONE bulk_watch stream. Every
    other attribute forwards to the real cluster."""

    def __init__(self, inner):
        self._inner = inner
        self.subs = []

    def watch(self, kind, listener, replay: bool = True) -> None:
        self.subs.append((kind, listener))

    def __getattr__(self, name):
        return getattr(self._inner, name)


class ControllerManager:
    """cmd/controller-manager equivalent: initialize + run all controllers
    against one cluster store; process_all() drains every controller's
    queue (single-core stand-in for the per-controller worker loops).

    Scale knobs (the sharded-front-door fan-out, ROADMAP item 3):
    ``bulk_watch=True`` collects every controller's subscriptions and
    opens them as ONE bulk_watch stream when the cluster supports it
    (RemoteClusterStore against a store server/router) — one socket and
    batched frames instead of a dozen per-kind streams.
    ``shard_workers=N`` fans the job controller's sync drain out across
    N worker threads partitioned by the job key's store shard, so
    pod-wave ingest overlaps store round trips instead of queueing
    behind one request at a time (pair with the store client's
    ``pool_size``).
    ``read_store=`` moves the controllers onto the read tier (ROADMAP
    item 1): list/watch/bulk_watch are served by that replica surface
    while every mutation keeps flowing to ``cluster`` (the primary,
    fencing untouched), with read-your-writes held via the min_rv
    bound — see client.readtier.ReadTierStore."""

    def __init__(self, cluster, scheduler_name: str = "volcano",
                 default_queue: str = "default", worker_num: int = 3,
                 shard_workers: int = 1, bulk_watch: bool = False,
                 read_store=None):
        if read_store is not None:
            from ..client.readtier import ReadTierStore
            cluster = ReadTierStore(cluster, read_store)
        self.opt = ControllerOption(cluster=cluster,
                                    scheduler_name=scheduler_name,
                                    default_queue=default_queue,
                                    worker_num=worker_num)
        self.shard_workers = max(1, int(shard_workers))
        self.bulk_watch = bool(bulk_watch)
        self.controllers = [
            JobController(),
            QueueController(),
            PodGroupController(),
            KubeletStandin(),
            GarbageCollector(),
        ]
        for ctrl in self.controllers:
            ctrl.initialize(self.opt)

    def run(self) -> None:
        if self.bulk_watch and hasattr(self.opt.cluster, "bulk_watch"):
            subs = []
            for ctrl in self.controllers:
                orig = getattr(ctrl, "cluster", None)
                if orig is None:
                    ctrl.run()
                    continue
                collector = _WatchCollector(orig)
                ctrl.cluster = collector
                try:
                    ctrl.run()
                finally:
                    ctrl.cluster = orig
                subs.extend(collector.subs)
            if subs:
                # one stream for every controller: replays land per kind
                # in subscription order (same net deliveries as the
                # sequential per-controller subscriptions), live events
                # arrive batched
                self.opt.cluster.bulk_watch(subs)
            return
        for ctrl in self.controllers:
            ctrl.run()

    def process_all(self, rounds: int = 4) -> None:
        for _ in range(rounds):
            for ctrl in self.controllers:
                if self.shard_workers > 1 and isinstance(ctrl,
                                                         JobController):
                    ctrl.process_all(parallel=self.shard_workers)
                else:
                    ctrl.process_all()

    def run_with_leader_election(self, stop, lock_name: str = "vc-controller-manager",
                                 identity: str = None) -> None:
        """HA mode (cmd/controller-manager/app/server.go:98-127): only the
        lease holder runs the controllers; a standby takes over when the
        leader's lease expires. Renewal runs on its own thread at the retry
        period; controllers subscribe their watches only once even if
        leadership is lost and regained."""
        import threading
        from ..utils import LeaderElector, LeaseLock

        # lease arbitration always runs against the primary: a standby's
        # takeover decision must never ride a replica's staleness
        write = getattr(self.opt.cluster, "write_store", self.opt.cluster)
        elector = LeaderElector(
            LeaseLock(write, lock_name), identity=identity)
        self._elector = elector
        # fencing: each controller's writes (pod create/delete, job and
        # podgroup status) carry this manager's lease token, so a deposed
        # manager's late reconcile is a FencedError instead of a
        # double-created pod (client.store.FencedStore)
        from ..client.store import FencedStore
        fenced = FencedStore(self.opt.cluster, elector.fencing_token)
        for ctrl in self.controllers:
            if getattr(ctrl, "cluster", None) is self.opt.cluster:
                ctrl.cluster = fenced
        renewer = threading.Thread(target=elector.run, args=(stop,),
                                   name="leader-elector", daemon=True)
        renewer.start()
        subscribed = False
        while not stop.is_set():
            if elector.is_leader:
                if not subscribed:
                    self.run()
                    subscribed = True
                self.process_all(rounds=1)
                stop.wait(0.05)
            else:
                stop.wait(0.05)
        renewer.join(timeout=2 * elector.retry_period)
