"""Job state machine: 8 states x actions (reference controllers/job/state/).

Each state's execute(action) maps bus Actions onto SyncJob/KillJob calls
with a status-update closure deciding the phase transition.
"""

from __future__ import annotations

from typing import Callable, Optional, Set

from ...models import Action, JobPhase
from ...models.batch import DEFAULT_MAX_RETRY

#: pod phases retained on kill
POD_RETAIN_PHASE_NONE: Set[str] = set()
POD_RETAIN_PHASE_SOFT: Set[str] = {"Succeeded", "Failed"}

UpdateStatusFn = Callable[[object], bool]  # JobStatus -> phase changed?


class State:
    def __init__(self, job_info, controller):
        self.job = job_info
        self.controller = controller  # provides sync_job/kill_job

    def execute(self, action: Action) -> None:
        raise NotImplementedError

    # helpers
    def _kill(self, retain, fn: Optional[UpdateStatusFn]) -> None:
        self.controller.kill_job(self.job, retain, fn)

    def _sync(self, fn: Optional[UpdateStatusFn]) -> None:
        self.controller.sync_job(self.job, fn)


def _total_tasks(job) -> int:
    return sum(t.replicas for t in job.spec.tasks)


class PendingState(State):
    def execute(self, action: Action) -> None:
        if action == Action.RESTART_JOB:
            def fn(status):
                status.retry_count += 1
                status.state.phase = JobPhase.RESTARTING
                return True
            self._kill(POD_RETAIN_PHASE_NONE, fn)
        elif action == Action.ABORT_JOB:
            def fn(status):
                status.state.phase = JobPhase.ABORTING
                return True
            self._kill(POD_RETAIN_PHASE_SOFT, fn)
        elif action == Action.COMPLETE_JOB:
            def fn(status):
                status.state.phase = JobPhase.COMPLETING
                return True
            self._kill(POD_RETAIN_PHASE_SOFT, fn)
        elif action == Action.TERMINATE_JOB:
            def fn(status):
                status.state.phase = JobPhase.TERMINATING
                return True
            self._kill(POD_RETAIN_PHASE_SOFT, fn)
        else:
            def fn(status):
                if self.job.job.spec.min_available <= (
                        status.running + status.succeeded + status.failed):
                    status.state.phase = JobPhase.RUNNING
                    return True
                return False
            self._sync(fn)


class RunningState(State):
    def execute(self, action: Action) -> None:
        if action == Action.RESTART_JOB:
            def fn(status):
                status.state.phase = JobPhase.RESTARTING
                status.retry_count += 1
                return True
            self._kill(POD_RETAIN_PHASE_NONE, fn)
        elif action == Action.ABORT_JOB:
            def fn(status):
                status.state.phase = JobPhase.ABORTING
                return True
            self._kill(POD_RETAIN_PHASE_SOFT, fn)
        elif action == Action.TERMINATE_JOB:
            def fn(status):
                status.state.phase = JobPhase.TERMINATING
                return True
            self._kill(POD_RETAIN_PHASE_SOFT, fn)
        elif action == Action.COMPLETE_JOB:
            def fn(status):
                status.state.phase = JobPhase.COMPLETING
                return True
            self._kill(POD_RETAIN_PHASE_SOFT, fn)
        else:
            def fn(status):
                replicas = _total_tasks(self.job.job)
                if replicas == 0:
                    return False
                if status.succeeded + status.failed == replicas:
                    if status.succeeded >= self.job.job.spec.min_available:
                        status.state.phase = JobPhase.COMPLETED
                    else:
                        status.state.phase = JobPhase.FAILED
                    return True
                return False
            self._sync(fn)


class RestartingState(State):
    def execute(self, action: Action) -> None:
        def fn(status):
            max_retry = self.job.job.spec.max_retry or DEFAULT_MAX_RETRY
            if status.retry_count >= max_retry:
                status.state.phase = JobPhase.FAILED
                return True
            total = _total_tasks(self.job.job)
            if total - status.terminating >= status.min_available:
                status.state.phase = JobPhase.PENDING
                return True
            return False
        self._kill(POD_RETAIN_PHASE_NONE, fn)


class AbortingState(State):
    def execute(self, action: Action) -> None:
        if action == Action.RESUME_JOB:
            def fn(status):
                status.state.phase = JobPhase.RESTARTING
                status.retry_count += 1
                return True
            self._kill(POD_RETAIN_PHASE_SOFT, fn)
        else:
            def fn(status):
                if status.terminating or status.pending or status.running:
                    return False
                status.state.phase = JobPhase.ABORTED
                return True
            self._kill(POD_RETAIN_PHASE_SOFT, fn)


class AbortedState(State):
    def execute(self, action: Action) -> None:
        if action == Action.RESUME_JOB:
            def fn(status):
                status.state.phase = JobPhase.RESTARTING
                status.retry_count += 1
                return True
            self._kill(POD_RETAIN_PHASE_SOFT, fn)
        else:
            self._kill(POD_RETAIN_PHASE_SOFT, None)


class TerminatingState(State):
    def execute(self, action: Action) -> None:
        def fn(status):
            if status.terminating or status.pending or status.running:
                return False
            status.state.phase = JobPhase.TERMINATED
            return True
        self._kill(POD_RETAIN_PHASE_SOFT, fn)


class CompletingState(State):
    def execute(self, action: Action) -> None:
        def fn(status):
            if status.terminating or status.pending or status.running:
                return False
            status.state.phase = JobPhase.COMPLETED
            return True
        self._kill(POD_RETAIN_PHASE_SOFT, fn)


class FinishedState(State):
    def execute(self, action: Action) -> None:
        self._kill(POD_RETAIN_PHASE_SOFT, None)


def new_state(job_info, controller) -> State:
    phase = job_info.job.status.state.phase
    mapping = {
        JobPhase.PENDING: PendingState,
        JobPhase.RUNNING: RunningState,
        JobPhase.RESTARTING: RestartingState,
        JobPhase.TERMINATED: FinishedState,
        JobPhase.COMPLETED: FinishedState,
        JobPhase.FAILED: FinishedState,
        JobPhase.TERMINATING: TerminatingState,
        JobPhase.ABORTING: AbortingState,
        JobPhase.ABORTED: AbortedState,
        JobPhase.COMPLETING: CompletingState,
    }
    cls = mapping.get(phase, PendingState)
    return cls(job_info, controller)
