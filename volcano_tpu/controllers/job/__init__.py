"""Job controller (reference pkg/controllers/job)."""

from .controller import JobController, apply_policies  # noqa: F401
from .plugins import EnvPlugin, SSHPlugin, SvcPlugin, get_plugin  # noqa: F401
from .state import (  # noqa: F401
    POD_RETAIN_PHASE_NONE, POD_RETAIN_PHASE_SOFT, new_state,
)
