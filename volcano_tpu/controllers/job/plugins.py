"""Job plugins: env, svc, ssh (reference controllers/job/plugins/).

Hooks: on_pod_create / on_job_add / on_job_delete / on_job_update
(plugins/interface/interface.go:30-44). They make gang-scheduled
distributed workloads wire themselves up: env injects task indices, svc
publishes a hosts table + headless service, ssh provisions a job-scoped
keypair for passwordless MPI.
"""

from __future__ import annotations

import base64
import hashlib
from typing import Callable, Dict, List

from ...client.store import NotFoundError
from ...models import ConfigMap, NetworkPolicy, Secret, Service
from ...models.batch import TASK_SPEC_KEY

CONFIG_MAP_TASK_INDEX_ENV = "VC_TASK_INDEX"
TASK_INDEX_ENV = "VK_TASK_INDEX"


def _task_index(pod) -> str:
    return pod.name.rsplit("-", 1)[-1]


class EnvPlugin:
    """Injects VC_TASK_INDEX / VK_TASK_INDEX env vars
    (plugins/env/env.go:45-85)."""

    def __init__(self, arguments=None, cluster=None):
        self.cluster = cluster

    def name(self) -> str:
        return "env"

    def on_pod_create(self, pod, job) -> None:
        idx = _task_index(pod)
        for c in pod.containers + pod.init_containers:
            envs = c.setdefault("env", [])
            envs.append({"name": TASK_INDEX_ENV, "value": idx})
            envs.append({"name": CONFIG_MAP_TASK_INDEX_ENV, "value": idx})

    def on_job_add(self, job) -> None:
        job.status.controlled_resources["plugin-env"] = "env"

    def on_job_delete(self, job) -> None:
        job.status.controlled_resources.pop("plugin-env", None)

    def on_job_update(self, job) -> None:
        pass


class SvcPlugin:
    """Headless service + hosts ConfigMap (+ optional NetworkPolicy)
    (plugins/svc/svc.go:257-345)."""

    def __init__(self, arguments=None, cluster=None):
        self.cluster = cluster
        self.arguments = arguments or []
        self.disable_network_policy = "--disable-network-policy=true" in (
            arguments or [])

    def name(self) -> str:
        return "svc"

    def _cm_name(self, job) -> str:
        return f"{job.name}-svc"

    def generate_hosts(self, job) -> Dict[str, str]:
        """Per-task FQDN lists: '<jobname>-<task>-<idx>.<jobname>'
        (svc.go:311-345)."""
        hosts = {}
        for ts in job.spec.tasks:
            lines = [f"{job.name}-{ts.name}-{i}.{job.name}"
                     for i in range(ts.replicas)]
            hosts[f"{ts.name}.host"] = "\n".join(lines)
        return hosts

    def on_job_add(self, job) -> None:
        cm = ConfigMap(name=self._cm_name(job), namespace=job.namespace,
                       data=self.generate_hosts(job),
                       owner_references=[{"kind": "Job", "name": job.name,
                                          "uid": job.uid}])
        self.cluster.apply("configmaps", cm)
        svc = Service(name=job.name, namespace=job.namespace,
                      spec={"clusterIP": "None",
                            "selector": {"volcano.sh/job-name": job.name},
                            "ports": [{"name": "placeholder", "port": 1}]},
                      owner_references=[{"kind": "Job", "name": job.name,
                                         "uid": job.uid}])
        self.cluster.apply("services", svc)
        if not self.disable_network_policy:
            # intra-job network isolation: only pods of the same job (or
            # unlabeled infrastructure) may reach the job's pods
            # (svc.go:257-304 CreateNetworkPolicyIfNotExist)
            np_obj = NetworkPolicy(
                name=job.name, namespace=job.namespace,
                spec={
                    "podSelector": {"matchLabels": {
                        "volcano.sh/job-name": job.name}},
                    "ingress": [{"from": [{"podSelector": {"matchLabels": {
                        "volcano.sh/job-name": job.name}}}]}],
                    "policyTypes": ["Ingress"],
                },
                owner_references=[{"kind": "Job", "name": job.name,
                                   "uid": job.uid}])
            self.cluster.apply("networkpolicies", np_obj)
            job.status.controlled_resources["plugin-svc-networkpolicy"] = job.name
        job.status.controlled_resources["plugin-svc"] = "svc"

    def on_pod_create(self, pod, job) -> None:
        # mount the hosts configmap + stable hostname/subdomain
        pod.annotations["volcano.sh/svc-configmap"] = self._cm_name(job)
        pod.annotations["volcano.sh/hostname"] = pod.name
        pod.annotations["volcano.sh/subdomain"] = job.name

    def on_job_delete(self, job) -> None:
        for kind, name in (("configmaps", self._cm_name(job)),
                           ("services", job.name),
                           ("networkpolicies", job.name)):
            try:
                self.cluster.delete(kind, name, job.namespace)
            except NotFoundError:
                pass
        job.status.controlled_resources.pop("plugin-svc", None)
        job.status.controlled_resources.pop("plugin-svc-networkpolicy", None)

    def on_job_update(self, job) -> None:
        cm = self.cluster.try_get("configmaps", self._cm_name(job),
                                  job.namespace)
        if cm is not None:
            cm.data = self.generate_hosts(job)
            self.cluster.update("configmaps", cm)


class SSHPlugin:
    """Job-scoped keypair in a Secret, mounted for passwordless MPI
    (plugins/ssh/ssh.go:64-215). Key material is deterministic test-grade
    (derived from the job UID), not cryptographic — the control-plane shape
    is what matters here; production would call out to a real keygen."""

    def __init__(self, arguments=None, cluster=None):
        self.cluster = cluster

    def name(self) -> str:
        return "ssh"

    def _secret_name(self, job) -> str:
        return f"{job.name}-ssh"

    def on_job_add(self, job) -> None:
        seed = hashlib.sha256(job.uid.encode()).hexdigest()
        private = base64.b64encode(f"ssh-private-{seed}".encode())
        public = base64.b64encode(f"ssh-public-{seed}".encode())
        secret = Secret(
            name=self._secret_name(job), namespace=job.namespace,
            data={"id_rsa": private, "id_rsa.pub": public,
                  "authorized_keys": public,
                  "config": b"StrictHostKeyChecking no\nUserKnownHostsFile /dev/null\n"},
            owner_references=[{"kind": "Job", "name": job.name,
                               "uid": job.uid}])
        self.cluster.apply("secrets", secret)
        job.status.controlled_resources["plugin-ssh"] = "ssh"

    def on_pod_create(self, pod, job) -> None:
        pod.annotations["volcano.sh/ssh-secret"] = self._secret_name(job)

    def on_job_delete(self, job) -> None:
        try:
            self.cluster.delete("secrets", self._secret_name(job),
                                job.namespace)
        except NotFoundError:
            pass
        job.status.controlled_resources.pop("plugin-ssh", None)

    def on_job_update(self, job) -> None:
        pass


_PLUGIN_BUILDERS: Dict[str, Callable] = {
    "env": EnvPlugin,
    "svc": SvcPlugin,
    "ssh": SSHPlugin,
}


def get_plugin(name: str, arguments: List[str], cluster):
    builder = _PLUGIN_BUILDERS.get(name)
    if builder is None:
        return None
    return builder(arguments, cluster)


def register_plugin_builder(name: str, builder: Callable) -> None:
    _PLUGIN_BUILDERS[name] = builder
