"""Job controller (reference pkg/controllers/job/job_controller*.go).

Reconciles batch Jobs: requests from job/pod/podgroup/command watch events
are queued with job-key affinity and drained by process_all(); each request
loads the cached JobInfo, resolves the action via applyPolicies, and runs
the state machine, which calls back into sync_job/kill_job.
"""

from __future__ import annotations

import logging
import random
import time
from typing import Dict, List, Optional, Tuple

from ...api import Resource
from ...api.job_info import container_requests
from ...api.types import POD_GROUP_ANNOTATION
from ...client.store import (
    AdmissionError, ClusterStore, ConflictError, NotFoundError,
)
from ...models import (
    Action, Event, Job, JobPhase, Pod, PodGroup, PodGroupPhase, PodGroupSpec,
)
from ...models.batch import (
    JOB_NAME_KEY, JOB_VERSION_KEY, TASK_SPEC_KEY,
)
from ..apis import JobInfo, Request
from ..cache import JobCache
from ..framework import Controller, ControllerOption
from .plugins import get_plugin
from .state import new_state

log = logging.getLogger(__name__)

MAX_RETRIES = 15          # reference maxRetry (job_controller.go)
RETRY_BASE_S = 0.1        # first backoff delay
RETRY_CAP_S = 30.0        # backoff ceiling


def apply_policies(job: Job, req: Request) -> Action:
    """Action resolution (job_controller_util.go:115-170)."""
    if req.action is not None:
        return req.action
    if req.event == Event.OUT_OF_SYNC:
        return Action.SYNC_JOB
    if req.job_version < job.status.version:
        return Action.SYNC_JOB

    def match(policy) -> bool:
        events = set(policy.events)
        if policy.event is not None:
            events.add(policy.event)
        if events and req.event is not None:
            if req.event in events or Event.ANY in events:
                return True
        if policy.exit_code is not None and policy.exit_code == req.exit_code \
                and req.exit_code != 0:
            return True
        return False

    if req.task_name:
        for task in job.spec.tasks:
            if task.name == req.task_name:
                for policy in task.policies:
                    if match(policy):
                        return policy.action
                break
    for policy in job.spec.policies:
        if match(policy):
            return policy.action
    return Action.SYNC_JOB


class JobController(Controller):
    def __init__(self):
        self.cluster: Optional[ClusterStore] = None
        self.scheduler_name = "volcano"
        self.default_queue = "default"
        self.worker_num = 3
        self.cache = JobCache()
        self.queues: List[List[Request]] = []
        # last observed pod phases: in-memory store objects are shared, so
        # the `old` object of an update event may alias the new one; phase
        # transitions are detected against this map instead
        self._pod_phases: Dict[str, str] = {}
        # last observed (spec fingerprint, phase) per job — status-only
        # updates must NOT re-enqueue OutOfSync or terminal-state jobs would
        # reconcile (and version-bump) forever
        # (job_controller_handler.go:98-103: "we only reconcile job based on
        # Spec ... ignored since no update in 'Spec'")
        self._job_obs: Dict[str, tuple] = {}
        # failed-sync backoff state (reference workqueue rate limiter +
        # maxRetry): consecutive failure count per job key, and the
        # deferred requests waiting out their delay as (not_before, req).
        # Injectable clock/rng keep the schedule testable/deterministic.
        self._retry_counts: Dict[str, int] = {}
        self._deferred: List[Tuple[float, Request]] = []
        self.clock = time.time
        self.retry_rng = random.Random(0)

    def name(self) -> str:
        return "job-controller"

    def initialize(self, opt: ControllerOption) -> None:
        self.cluster = opt.cluster
        self.scheduler_name = opt.scheduler_name
        self.default_queue = opt.default_queue
        self.worker_num = max(opt.worker_num, 1)
        self.queues = [[] for _ in range(self.worker_num)]

    # -- queueing (FNV-style job-key shard affinity) -------------------------

    def _enqueue(self, req: Request) -> None:
        shard = hash(req.key) % self.worker_num
        self.queues[shard].append(req)

    def run(self) -> None:
        c = self.cluster
        c.watch("jobs", self._on_job)
        c.watch("pods", self._on_pod)
        c.watch("podgroups", self._on_podgroup)
        c.watch("commands", self._on_command)

    def _retry_later(self, req: Request) -> None:
        """Schedule a failed request's re-enqueue with capped exponential
        backoff + jitter per job key (reference maxRetry + the workqueue
        rate limiter): immediate unbounded re-enqueues would hot-loop a
        permanently failing sync against the control plane. After
        MAX_RETRIES consecutive failures the request is dropped — the
        next genuine watch event for the job starts a fresh budget."""
        from ...metrics import metrics
        count = self._retry_counts.get(req.key, 0) + 1
        self._retry_counts[req.key] = count
        if count > MAX_RETRIES:
            log.error("giving up on %s after %d failed syncs", req.key,
                      count - 1)
            self._retry_counts.pop(req.key, None)
            return
        delay = min(RETRY_BASE_S * (2 ** (count - 1)), RETRY_CAP_S)
        delay *= 0.5 + self.retry_rng.random()  # jitter: spread the herd
        self._deferred.append((self.clock() + delay, req))
        metrics.job_retry_total.inc(labels={"job_id": req.key})

    def _drain_due_retries(self, batch: Dict[tuple, Request]) -> None:
        """Move deferred retries whose delay elapsed into the batch."""
        if not self._deferred:
            return
        now = self.clock()
        still_waiting = []
        for not_before, req in self._deferred:
            if not_before > now:
                still_waiting.append((not_before, req))
                continue
            dedup = (req.namespace, req.job_name, req.task_name,
                     req.event, req.exit_code, req.action)
            batch.setdefault(dedup, req)
        self._deferred = still_waiting

    def process_all(self, max_rounds: int = 16, parallel: int = 1) -> None:
        """Drain all shards; new requests produced while processing are
        handled in subsequent rounds. Identical requests are deduplicated
        per round (the reference's workqueue add-if-absent semantics) —
        without this, the watch-event feedback from each sync amplifies the
        queue exponentially. A request whose sync raises re-enqueues with
        capped exponential backoff per job key (_retry_later) instead of
        being dropped (or hot-looped).

        ``parallel`` > 1 fans a round's batch out across worker threads
        partitioned by the job key's STORE shard (client/sharded.py
        shard_for — the sharded front door's controller fan-out):
        requests for one job keep their key affinity in one worker,
        while workers whose syncs are store round trips overlap instead
        of queueing behind a single request at a time. Retry-backoff
        bookkeeping stays on the caller thread."""
        for _ in range(max_rounds):
            batch: Dict[tuple, Request] = {}
            for q in self.queues:
                for req in q:
                    dedup = (req.namespace, req.job_name, req.task_name,
                             req.event, req.exit_code, req.action)
                    batch.setdefault(dedup, req)
                q.clear()
            self._drain_due_retries(batch)
            if not batch:
                return
            if parallel <= 1 or len(batch) <= 1:
                for req in batch.values():
                    try:
                        self._process(req)
                    except Exception:
                        log.exception("failed to process request %s", req)
                        self._retry_later(req)
                    else:
                        self._retry_counts.pop(req.key, None)
                continue
            self._process_parallel(batch, parallel)

    def _process_parallel(self, batch: Dict[tuple, Request],
                          parallel: int) -> None:
        import threading

        from ...client.sharded import shard_for

        groups: Dict[int, List[Request]] = {}
        for req in batch.values():
            groups.setdefault(shard_for("jobs", req.key, parallel),
                              []).append(req)
        failed: List[Request] = []
        synced: List[str] = []

        def drain(reqs: List[Request]) -> None:
            for req in reqs:
                try:
                    self._process(req)
                except Exception:  # noqa: BLE001 — retried below
                    log.exception("failed to process request %s", req)
                    failed.append(req)
                else:
                    synced.append(req.key)

        threads = [threading.Thread(target=drain, args=(reqs,),
                                    name=f"job-sync-{shard}")
                   for shard, reqs in groups.items()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for req in failed:
            self._retry_later(req)
        for key in synced:
            self._retry_counts.pop(key, None)

    # -- watch handlers (job_controller_handler.go) ---------------------------

    def _on_job(self, event, job: Job, old) -> None:
        if event == "add":
            self.cache.add(job)
            self._job_obs[job.key] = (repr(job.spec), job.status.state.phase)
            self._enqueue(Request(job.namespace, job.name,
                                  event=Event.OUT_OF_SYNC))
        elif event == "update":
            self.cache.update(job)
            obs = (repr(job.spec), job.status.state.phase)
            if self._job_obs.get(job.key) == obs:
                return
            self._job_obs[job.key] = obs
            self._enqueue(Request(job.namespace, job.name,
                                  event=Event.OUT_OF_SYNC,
                                  job_version=job.status.version))
        else:
            self._job_obs.pop(job.key, None)
            self.cache.delete(job)
            for name, args in (job.spec.plugins or {}).items():
                plugin = get_plugin(name, args, self.cluster)
                if plugin is not None:
                    try:
                        plugin.on_job_delete(job)
                    except Exception:
                        log.exception("plugin %s on_job_delete failed", name)

    def _on_pod(self, event, pod: Pod, old) -> None:
        job_name = (pod.annotations or {}).get(JOB_NAME_KEY)
        if not job_name:
            return
        task_name = (pod.annotations or {}).get(TASK_SPEC_KEY, "")
        version = int((pod.annotations or {}).get(JOB_VERSION_KEY, 0))
        pod_key = f"{pod.namespace}/{pod.name}"
        prev_phase = self._pod_phases.get(pod_key)
        if event == "delete":
            self._pod_phases.pop(pod_key, None)
        else:
            self._pod_phases[pod_key] = pod.phase
        if event == "add":
            self.cache.add_pod(pod)
            self._enqueue(Request(pod.namespace, job_name,
                                  event=Event.OUT_OF_SYNC,
                                  job_version=version))
        elif event == "update":
            self.cache.update_pod(pod)
            if pod.phase == "Failed" and prev_phase != "Failed":
                exit_code = 0
                for cs in pod.container_statuses:
                    term = (cs.get("state") or {}).get("terminated") or {}
                    if term.get("exitCode"):
                        exit_code = int(term["exitCode"])
                        break
                self._enqueue(Request(pod.namespace, job_name,
                                      task_name=task_name,
                                      event=Event.POD_FAILED,
                                      exit_code=exit_code,
                                      job_version=version))
            elif pod.phase == "Succeeded" and prev_phase != "Succeeded":
                if self.cache.task_completed(f"{pod.namespace}/{job_name}",
                                             task_name):
                    self._enqueue(Request(pod.namespace, job_name,
                                          task_name=task_name,
                                          event=Event.TASK_COMPLETED,
                                          job_version=version))
                else:
                    self._enqueue(Request(pod.namespace, job_name,
                                          event=Event.OUT_OF_SYNC,
                                          job_version=version))
            else:
                self._enqueue(Request(pod.namespace, job_name,
                                      event=Event.OUT_OF_SYNC,
                                      job_version=version))
        else:  # delete
            self.cache.delete_pod(pod)
            self._enqueue(Request(pod.namespace, job_name,
                                  task_name=task_name,
                                  event=Event.POD_EVICTED,
                                  job_version=version))

    def _on_podgroup(self, event, pg: PodGroup, old) -> None:
        if event != "update":
            return
        # phase flips (Pending -> Inqueue) unblock pod creation
        job = self.cluster.try_get("jobs", pg.name, pg.namespace)
        if job is not None:
            self._enqueue(Request(pg.namespace, pg.name,
                                  event=Event.OUT_OF_SYNC))

    def _on_command(self, event, cmd, old) -> None:
        if event != "add":
            return
        target = cmd.target_object or {}
        if target.get("kind") != "Job":
            return
        try:
            self.cluster.delete("commands", cmd.name, cmd.namespace)
        except NotFoundError:
            pass
        except ConflictError:
            # FencedError included: a deposed HA manager must neither
            # consume the command nor blow up the watch delivery — the
            # live manager will process it
            return
        self._enqueue(Request(cmd.namespace, target.get("name", ""),
                              action=cmd.action,
                              event=Event.COMMAND_ISSUED))

    # -- request processing (job_controller.go:286-347) ----------------------

    def _process(self, req: Request) -> None:
        ji = self.cache.get(req.key)
        if ji is None or ji.job is None:
            job = self.cluster.try_get("jobs", req.job_name, req.namespace)
            if job is None:
                return
            self.cache.add(job)
            ji = self.cache.get(req.key)
        st = new_state(ji, self)
        action = apply_policies(ji.job, req)
        st.execute(action)

    # -- plugins -------------------------------------------------------------

    def _plugins(self, job: Job):
        out = []
        for name, args in (job.spec.plugins or {}).items():
            plugin = get_plugin(name, args, self.cluster)
            if plugin is not None:
                out.append(plugin)
        return out

    # -- pod construction -----------------------------------------------------

    def _create_job_pod(self, job: Job, task, index: int) -> Pod:
        tmpl = task.template or {}
        spec = tmpl.get("spec", {})
        meta = tmpl.get("metadata", {})
        pod = Pod(
            name=f"{job.name}-{task.name}-{index}",
            namespace=job.namespace,
            containers=[dict(c) for c in spec.get("containers", [])],
            init_containers=[dict(c) for c in spec.get("initContainers", [])],
            node_selector=dict(spec.get("nodeSelector", {})),
            affinity=spec.get("affinity"),
            tolerations=list(spec.get("tolerations", [])),
            scheduler_name=job.spec.scheduler_name or self.scheduler_name,
            priority_class_name=job.spec.priority_class_name,
            labels={**meta.get("labels", {}), JOB_NAME_KEY: job.name},
            annotations={
                **meta.get("annotations", {}),
                TASK_SPEC_KEY: task.name,
                JOB_NAME_KEY: job.name,
                JOB_VERSION_KEY: str(job.status.version),
                POD_GROUP_ANNOTATION: job.name,
            },
        )
        for plugin in self._plugins(job):
            try:
                plugin.on_pod_create(pod, job)
            except Exception:
                log.exception("plugin on_pod_create failed")
        return pod

    def calc_pg_min_resources(self, job: Job) -> Dict[str, str]:
        """Sum the launch requests of the first min_available tasks
        (job_controller_actions.go calcPGMinResources, simplified to spec
        order)."""
        total = Resource()
        remaining = job.spec.min_available
        for task in job.spec.tasks:
            reqs = [container_requests(c) for c in
                    (task.template.get("spec", {}).get("containers", []))]
            per_pod = Resource()
            for r in reqs:
                per_pod.add(Resource.from_resource_list(r))
            n = min(task.replicas, remaining)
            total.add(per_pod.multi(n))
            remaining -= n
            if remaining <= 0:
                break
        out = {"cpu": f"{total.milli_cpu / 1000:g}",
               "memory": f"{total.memory:g}"}
        for k, v in total.scalars.items():
            out[k] = f"{v / 1000:g}"
        return out

    # -- sync / kill (job_controller_actions.go:40-570) -----------------------

    def _initiate(self, job: Job) -> None:
        if job.status.state.phase is None:
            job.status.state.phase = JobPhase.PENDING
        job.status.min_available = job.spec.min_available
        for plugin in self._plugins(job):
            try:
                plugin.on_job_add(job)
            except Exception:
                log.exception("plugin on_job_add failed")
        # PVCs for job volumes
        from ...models import PersistentVolumeClaim
        for i, vol in enumerate(job.spec.volumes or []):
            name = vol.get("volumeClaimName") or f"{job.name}-pvc-{i}"
            if self.cluster.try_get("pvcs", name, job.namespace) is None:
                self.cluster.create("pvcs", PersistentVolumeClaim(
                    name=name, namespace=job.namespace,
                    spec=dict(vol.get("volumeClaim", {}))))
        # PodGroup (created or updated; named after the job)
        pg = self.cluster.try_get("podgroups", job.name, job.namespace)
        if pg is None:
            pg = PodGroup(
                name=job.name, namespace=job.namespace,
                spec=PodGroupSpec(
                    min_member=job.spec.min_available,
                    queue=job.spec.queue or self.default_queue,
                    priority_class_name=job.spec.priority_class_name,
                    min_resources=self.calc_pg_min_resources(job)),
                owner_references=[{"kind": "Job", "name": job.name,
                                   "uid": job.uid}])
            self.cluster.create("podgroups", pg)
        else:
            min_res = self.calc_pg_min_resources(job)
            if (pg.spec.min_member != job.spec.min_available
                    or pg.spec.min_resources != min_res):
                pg.spec.min_member = job.spec.min_available
                pg.spec.min_resources = min_res
                self.cluster.update("podgroups", pg)

    @staticmethod
    def _status_tuple(status):
        return (status.state.phase, status.pending, status.running,
                status.succeeded, status.failed, status.terminating,
                status.unknown, status.version, status.retry_count)

    def _update_counts(self, status, pods_by_task) -> None:
        status.pending = status.running = status.succeeded = 0
        status.failed = status.terminating = status.unknown = 0
        for pods in pods_by_task.values():
            for pod in pods.values():
                if pod.deletion_timestamp:
                    status.terminating += 1
                elif pod.phase == "Pending":
                    status.pending += 1
                elif pod.phase == "Running":
                    status.running += 1
                elif pod.phase == "Succeeded":
                    status.succeeded += 1
                elif pod.phase == "Failed":
                    status.failed += 1
                else:
                    status.unknown += 1

    def sync_job(self, ji: JobInfo, update_status_fn) -> None:
        job = ji.job
        if job.deletion_timestamp is not None:
            return
        self._initiate(job)

        # the pod gate: while the PodGroup is Pending, pod creation waits
        pg = self.cluster.try_get("podgroups", job.name, job.namespace)
        create_allowed = pg is not None and \
            pg.status.phase != PodGroupPhase.PENDING

        desired: Dict[str, Dict[str, object]] = {}
        for task in job.spec.tasks:
            for i in range(task.replicas):
                desired.setdefault(task.name, {})[
                    f"{job.name}-{task.name}-{i}"] = (task, i)

        # create missing, delete surplus (scale down)
        to_create = []
        for task_name, pods in desired.items():
            actual = ji.pods.get(task_name, {})
            for pod_name, (task, i) in pods.items():
                if pod_name not in actual and create_allowed:
                    to_create.append(self._create_job_pod(job, task, i))
        if to_create:
            # one frame / one journal batch for the whole wave (the
            # ROADMAP item-3 bulk ingest seam); per-item results keep
            # the old loop's containment — a rejected pod costs that
            # pod, not the wave
            for pod, res in zip(to_create, self.cluster.bulk_apply(
                    [("pods", pod, "create") for pod in to_create])):
                if isinstance(res, AdmissionError):
                    log.info("pod %s rejected by admission: %s",
                             pod.name, res)
                elif isinstance(res, Exception):
                    log.error("failed to create pod %s: %s",
                              pod.name, res)
        for task_name, actual in list(ji.pods.items()):
            wanted = desired.get(task_name, {})
            for pod_name, pod in list(actual.items()):
                if pod_name not in wanted and pod.deletion_timestamp is None:
                    try:
                        self.cluster.delete("pods", pod_name, job.namespace)
                    except NotFoundError:
                        pass

        # refresh counts from the cache's post-diff view
        ji2 = self.cache.get(job.key)
        before = self._status_tuple(job.status)
        self._update_counts(job.status, ji2.pods if ji2 else {})
        # NOTE: sync never bumps status.version — the reference bumps only in
        # killJob (job_controller_actions.go:92); bumping here version-gates
        # first-generation pods' PodFailed requests to SyncJob and lifecycle
        # policies (RestartJob/AbortJob/...) would never fire.
        if update_status_fn:
            update_status_fn(job.status)
        if self._status_tuple(job.status) != before \
                or self.cluster.try_get("jobs", job.name, job.namespace) is None:
            self.cluster.apply("jobs", job)

    def kill_job(self, ji: JobInfo, retain_phases, update_status_fn) -> None:
        job = ji.job
        if job.deletion_timestamp is not None:
            return
        terminating = 0
        for task_name, pods in list(ji.pods.items()):
            for pod in list(pods.values()):
                if pod.phase in retain_phases:
                    continue
                if pod.deletion_timestamp is not None:
                    terminating += 1
                    continue
                try:
                    self.cluster.delete("pods", pod.name, pod.namespace)
                except NotFoundError:
                    pass
        ji2 = self.cache.get(job.key)
        self._update_counts(job.status, ji2.pods if ji2 else {})
        job.status.terminating = max(job.status.terminating, terminating)
        # "Job version is bumped only when job is killed" — unconditionally,
        # whether or not the phase closure transitions
        # (job_controller_actions.go:90-92).
        job.status.version += 1
        if update_status_fn:
            update_status_fn(job.status)
        self.cluster.apply("jobs", job)
