"""Controller job cache (reference pkg/controllers/cache/cache.go).

jobKey -> JobInfo{Job, Pods[task][podname]} so workers don't re-list.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..models import Job, Pod
from ..models.batch import JOB_NAME_KEY
from .apis import JobInfo


def job_key_of_pod(pod: Pod) -> Optional[str]:
    job_name = (pod.annotations or {}).get(JOB_NAME_KEY)
    if not job_name:
        return None
    return f"{pod.namespace}/{job_name}"


class JobCache:
    def __init__(self):
        self.jobs: Dict[str, JobInfo] = {}

    def get(self, key: str) -> Optional[JobInfo]:
        ji = self.jobs.get(key)
        return ji.clone() if ji is not None else None

    def add(self, job: Job) -> None:
        key = job.key
        if key in self.jobs:
            self.jobs[key].job = job
        else:
            self.jobs[key] = JobInfo(job)

    def update(self, job: Job) -> None:
        self.add(job)

    def delete(self, job: Job) -> None:
        self.jobs.pop(job.key, None)

    def add_pod(self, pod: Pod) -> None:
        key = job_key_of_pod(pod)
        if key is None:
            return
        if key not in self.jobs:
            self.jobs[key] = JobInfo(None)
        self.jobs[key].add_pod(pod)

    def update_pod(self, pod: Pod) -> None:
        self.add_pod(pod)

    def delete_pod(self, pod: Pod) -> None:
        key = job_key_of_pod(pod)
        if key is None:
            return
        ji = self.jobs.get(key)
        if ji is not None:
            ji.delete_pod(pod)
            if ji.job is None and not ji.pods:
                del self.jobs[key]

    def task_completed(self, key: str, task_name: str) -> bool:
        """All pods of the task succeeded (cache.go TaskCompleted)."""
        ji = self.jobs.get(key)
        if ji is None or ji.job is None:
            return False
        pods = ji.pods.get(task_name, {})
        replicas = 0
        for task in ji.job.spec.tasks:
            if task.name == task_name:
                replicas = task.replicas
        if replicas == 0 or not pods:
            return False
        succeeded = sum(1 for p in pods.values() if p.phase == "Succeeded")
        return succeeded >= replicas
