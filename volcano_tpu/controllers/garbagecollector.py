"""Garbage collector (reference controllers/garbagecollector/garbagecollector.go:52-249).

Deletes finished Jobs (Completed/Failed/Terminated) after
ttl_seconds_after_finished expires, cascading to owned resources.
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional

from ..client.store import ClusterStore, NotFoundError
from ..models import Job, JobPhase
from .framework import Controller, ControllerOption

log = logging.getLogger(__name__)

FINISHED_PHASES = {JobPhase.COMPLETED, JobPhase.FAILED, JobPhase.TERMINATED}


def _finish_time(job: Job) -> float:
    return job.status.state.last_transition_time or job.creation_timestamp


class GarbageCollector(Controller):
    def __init__(self):
        self.cluster: Optional[ClusterStore] = None
        self.queue: List[str] = []

    def name(self) -> str:
        return "gc-controller"

    def initialize(self, opt: ControllerOption) -> None:
        self.cluster = opt.cluster

    def run(self) -> None:
        self.cluster.watch("jobs", self._on_job)

    def _on_job(self, event, job: Job, old) -> None:
        if event == "delete":
            return
        if job.spec.ttl_seconds_after_finished is None:
            return
        if job.status.state.phase in FINISHED_PHASES:
            self.queue.append(job.key)

    def process_all(self, now: Optional[float] = None) -> None:
        """Collect expired jobs; `now` injectable for tests."""
        now = now if now is not None else time.time()
        keys, self.queue = list(dict.fromkeys(self.queue)), []
        for key in keys:
            ns, name = key.split("/", 1)
            job = self.cluster.try_get("jobs", name, ns)
            if job is None:
                continue
            if job.status.state.phase not in FINISHED_PHASES:
                continue
            ttl = job.spec.ttl_seconds_after_finished
            if ttl is None:
                continue
            expire_at = _finish_time(job) + ttl
            if now >= expire_at:
                self._cascade_delete(job)
            else:
                self.queue.append(key)  # re-check later

    def _cascade_delete(self, job: Job) -> None:
        # propagate: pods, podgroup, plugin resources owned by the job
        for pod in self.cluster.list("pods", namespace=job.namespace):
            if (pod.annotations or {}).get("volcano.sh/job-name") == job.name:
                try:
                    self.cluster.delete("pods", pod.name, pod.namespace)
                except NotFoundError:
                    pass
        for kind in ("podgroups", "configmaps", "services", "secrets"):
            for obj in self.cluster.list(kind, namespace=job.namespace):
                owners = getattr(obj, "owner_references", []) or []
                if any(o.get("uid") == job.uid for o in owners) \
                        or obj.name == job.name:
                    try:
                        self.cluster.delete(kind, obj.name, job.namespace)
                    except NotFoundError:
                        pass
        try:
            self.cluster.delete("jobs", job.name, job.namespace)
        except NotFoundError:
            pass
