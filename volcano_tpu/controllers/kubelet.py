"""Kubelet stand-in: completes graceful pod termination.

The evictor only *requests* deletion (sets deletion_timestamp and leaves the
pod bound, cache.go:139-169 semantics); in a real cluster the kubelet runs
the grace period and then removes the pod. This framework's ClusterStore IS
the cluster, so the controller-manager runs this stand-in — without it an
evicted pod would stay Releasing forever and the preemptor/reclaimer would
never bind (the freed space stays FutureIdle, never Idle).

No reference counterpart file: the kubelet lives outside volcano's tree.
"""

from __future__ import annotations

import time

from .framework import Controller, ControllerOption


class KubeletStandin(Controller):
    """grace_seconds defaults to the kubelet's 30s termination grace. The
    gap between it and the 1s schedule period matters: evictions must
    outpace the job controller's replacement pods (which re-enter the
    pending pool as soon as the victim is finalized), or a reclaim/preempt
    stand-off between a saturated queue and its claimant never converges —
    the same attrition dynamic a real cluster gets from kubelet timing."""

    def __init__(self, grace_seconds: float = 30.0, clock=time.time):
        # clock is the kubelet's time source: wall clock in a live control
        # plane, the virtual clock in the trace-driven simulator
        # (volcano_tpu.sim.virtualcluster) so termination grace elapses in
        # virtual seconds and runs stay reproducible
        self.grace_seconds = grace_seconds
        self.clock = clock
        self.cluster = None

    def name(self) -> str:
        return "kubelet-standin"

    def initialize(self, opt: ControllerOption) -> None:
        self.cluster = opt.cluster

    def run(self) -> None:
        pass  # no watches: termination is scanned, like kubelet sync loops

    def process_all(self) -> None:
        now = self.clock()
        for pod in list(self.cluster.list("pods")):
            ts = pod.deletion_timestamp
            if ts is None or now < ts + self.grace_seconds:
                continue
            try:
                self.cluster.delete("pods", pod.name, pod.namespace)
            except KeyError:
                pass  # already removed
