"""PodGroup controller (reference pg_controller.go:65-111).

Auto-creates a PodGroup for bare pods that use the volcano scheduler but
carry no group annotation (normal-pod compatibility).
"""

from __future__ import annotations

import logging
from typing import List, Optional

from ..api.types import POD_GROUP_ANNOTATION
from ..client.store import ClusterStore
from ..models import Pod, PodGroup, PodGroupSpec
from .framework import Controller, ControllerOption

log = logging.getLogger(__name__)


class PodGroupController(Controller):
    def __init__(self):
        self.cluster: Optional[ClusterStore] = None
        self.scheduler_name = "volcano"
        self.default_queue = "default"
        self.queue: List[str] = []  # pod keys

    def name(self) -> str:
        return "pg-controller"

    def initialize(self, opt: ControllerOption) -> None:
        self.cluster = opt.cluster
        self.scheduler_name = opt.scheduler_name
        self.default_queue = opt.default_queue

    def run(self) -> None:
        self.cluster.watch("pods", self._on_pod)

    def _on_pod(self, event, pod: Pod, old) -> None:
        if event != "add":
            return
        if pod.scheduler_name != self.scheduler_name:
            return
        if (pod.annotations or {}).get(POD_GROUP_ANNOTATION):
            return
        self.queue.append(f"{pod.namespace}/{pod.name}")

    def process_all(self) -> None:
        keys, self.queue = self.queue, []
        for key in keys:
            ns, name = key.split("/", 1)
            pod = self.cluster.try_get("pods", name, ns)
            if pod is None:
                continue
            try:
                self._ensure_podgroup(pod)
            except Exception:
                log.exception("failed to create podgroup for %s", key)

    def _ensure_podgroup(self, pod: Pod) -> None:
        pg_name = f"podgroup-{pod.uid}"
        if self.cluster.try_get("podgroups", pg_name, pod.namespace) is None:
            owner = pod.owner_references[0] if pod.owner_references else \
                {"kind": "Pod", "name": pod.name, "uid": pod.uid}
            self.cluster.create("podgroups", PodGroup(
                name=pg_name, namespace=pod.namespace,
                spec=PodGroupSpec(min_member=1, queue=self.default_queue,
                                  priority_class_name=pod.priority_class_name),
                owner_references=[owner]))
        pod.annotations[POD_GROUP_ANNOTATION] = pg_name
        self.cluster.update("pods", pod)
