"""Controller request/job-info types (reference pkg/controllers/apis)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from ..models import Action, Event, Job, Pod


@dataclass
class Request:
    namespace: str
    job_name: str
    task_name: str = ""
    event: Optional[Event] = None
    exit_code: int = 0
    action: Optional[Action] = None
    job_version: int = 0

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.job_name}"


class JobInfo:
    """Controller-cache view of a Job: the CR + its pods indexed by task
    (apis/job_info.go:28)."""

    def __init__(self, job: Job):
        self.job = job
        self.pods: Dict[str, Dict[str, Pod]] = {}  # task name -> pod name -> pod

    def clone(self) -> "JobInfo":
        ji = JobInfo(self.job)
        for task, pods in self.pods.items():
            ji.pods[task] = dict(pods)
        return ji

    def add_pod(self, pod: Pod) -> None:
        from ..models.batch import TASK_SPEC_KEY
        task_name = (pod.annotations or {}).get(TASK_SPEC_KEY, "")
        self.pods.setdefault(task_name, {})[pod.name] = pod

    def delete_pod(self, pod: Pod) -> None:
        from ..models.batch import TASK_SPEC_KEY
        task_name = (pod.annotations or {}).get(TASK_SPEC_KEY, "")
        bucket = self.pods.get(task_name)
        if bucket is not None:
            bucket.pop(pod.name, None)
            if not bucket:
                del self.pods[task_name]
