"""Controller framework (reference pkg/controllers/framework)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

from ..client.store import ClusterStore


@dataclass
class ControllerOption:
    cluster: ClusterStore
    scheduler_name: str = "volcano"
    default_queue: str = "default"
    worker_num: int = 3


class Controller:
    def name(self) -> str:
        raise NotImplementedError

    def initialize(self, opt: ControllerOption) -> None:
        raise NotImplementedError

    def run(self) -> None:
        """Subscribe to watches. Single-threaded: work is drained by
        process_all()."""
        raise NotImplementedError

    def process_all(self) -> None:
        """Drain pending work items (the worker loop of the reference)."""
        raise NotImplementedError


_controllers: Dict[str, Controller] = {}


def register_controller(ctrl: Controller) -> None:
    _controllers[ctrl.name()] = ctrl


def for_each_controller(fn) -> None:
    for ctrl in _controllers.values():
        fn(ctrl)


def get_controller(name: str) -> Optional[Controller]:
    return _controllers.get(name)
