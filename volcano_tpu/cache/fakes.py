"""Fake effectors (reference pkg/scheduler/util/test_utils.go:94-160).

Recorded binds/evictions make the whole solve loop hermetic: tests build a
cache, run actions, then compare FakeBinder.binds against expectations.
"""

from __future__ import annotations

from typing import Dict, List


class FakeBinder:
    def __init__(self):
        self.binds: Dict[str, str] = {}   # "ns/pod" -> node
        self.channel: List[str] = []
        # the pod objects themselves, for callers that must resync the
        # cache mirror after a write-free run (Scheduler.shadow_cycle)
        self.bound_pods: List[object] = []

    def bind(self, pod, hostname: str) -> None:
        key = f"{pod.namespace}/{pod.name}"
        self.binds[key] = hostname
        self.channel.append(key)
        self.bound_pods.append(pod)


class FakeEvictor:
    def __init__(self):
        self.evicts: List[str] = []
        self.channel: List[str] = []
        self.evicted_pods: List[object] = []

    def evict(self, pod, reason: str) -> None:
        key = f"{pod.namespace}/{pod.name}"
        self.evicts.append(key)
        self.channel.append(key)
        self.evicted_pods.append(pod)


class RecordingBinder:
    """FakeBinder generalized into a decorator: record every bind like
    FakeBinder does AND forward to an inner binder (``inner=None`` keeps
    pure FakeBinder semantics). ``on_bind(pod, hostname)`` is the sim
    decision-recorder seam — it fires only after the inner binder
    succeeded, so recorded binds are exactly the ones that reached the
    cluster."""

    def __init__(self, inner=None, on_bind=None):
        self.inner = inner
        self.on_bind = on_bind
        self.binds: Dict[str, str] = {}
        self.channel: List[str] = []

    def bind(self, pod, hostname: str) -> None:
        if self.inner is not None:
            self.inner.bind(pod, hostname)
        key = f"{pod.namespace}/{pod.name}"
        self.binds[key] = hostname
        self.channel.append(key)
        if self.on_bind is not None:
            self.on_bind(pod, hostname)


class RecordingEvictor:
    """FakeEvictor as a decorator (see RecordingBinder)."""

    def __init__(self, inner=None, on_evict=None):
        self.inner = inner
        self.on_evict = on_evict
        self.evicts: List[str] = []
        self.channel: List[str] = []

    def evict(self, pod, reason: str) -> None:
        if self.inner is not None:
            self.inner.evict(pod, reason)
        key = f"{pod.namespace}/{pod.name}"
        self.evicts.append(key)
        self.channel.append(key)
        if self.on_evict is not None:
            self.on_evict(pod, reason)


class FakeStatusUpdater:
    def update_pod_condition(self, pod, condition: dict) -> None:
        pass

    def update_pod_group(self, pg) -> None:
        pass


class FakeVolumeBinder:
    def allocate_volumes(self, task, hostname: str) -> None:
        pass

    def bind_volumes(self, task) -> None:
        pass

    def revert_volumes(self, task) -> None:
        pass
