"""Fake effectors (reference pkg/scheduler/util/test_utils.go:94-160).

Recorded binds/evictions make the whole solve loop hermetic: tests build a
cache, run actions, then compare FakeBinder.binds against expectations.
"""

from __future__ import annotations

from typing import Dict, List


class FakeBinder:
    def __init__(self):
        self.binds: Dict[str, str] = {}   # "ns/pod" -> node
        self.channel: List[str] = []

    def bind(self, pod, hostname: str) -> None:
        key = f"{pod.namespace}/{pod.name}"
        self.binds[key] = hostname
        self.channel.append(key)


class FakeEvictor:
    def __init__(self):
        self.evicts: List[str] = []
        self.channel: List[str] = []

    def evict(self, pod, reason: str) -> None:
        key = f"{pod.namespace}/{pod.name}"
        self.evicts.append(key)
        self.channel.append(key)


class FakeStatusUpdater:
    def update_pod_condition(self, pod, condition: dict) -> None:
        pass

    def update_pod_group(self, pg) -> None:
        pass


class FakeVolumeBinder:
    def allocate_volumes(self, task, hostname: str) -> None:
        pass

    def bind_volumes(self, task) -> None:
        pass

    def revert_volumes(self, task) -> None:
        pass
