"""SchedulerCache: the cluster-state mirror behind every session.

Reimplements reference pkg/scheduler/cache/{cache.go:71-855,
event_handlers.go:43-710} against the TPU build's ClusterStore seam instead
of client-go informers. Single-threaded (one host core): effector calls are
synchronous, with the reference's resync-on-failure behavior preserved via an
err-task queue drained at the top of each cycle.
"""

from __future__ import annotations

import logging
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from ..api import (
    ClusterInfo, JobInfo, NamespaceCollection, NodeInfo, QueueInfo, Resource,
    TaskInfo, TaskStatus,
)
from ..api.job_info import job_key_of_pod, pod_key, status_of_pod
from ..models import (
    PodGroup, PodGroupCondition, PodGroupPhase, Queue, QueueSpec,
)
from ..client.store import ClusterStore, ConflictError, NotFoundError
from ..metrics import metrics

log = logging.getLogger(__name__)

#: pod fields a delta watch patch may change while staying on the
#: targeted-update path (apply_pod_delta): none of these move the task
#: to a different job (annotations), change its identity (name/
#: namespace/uid), its resource shape (containers/init_containers), or
#: its owner (scheduler_name) — changes outside this set rebuild the
#: TaskInfo through the generic update ladder
_DELTA_FAST_FIELDS = frozenset((
    "phase", "deletion_timestamp", "node_name", "priority",
    "resource_version", "container_statuses", "conditions", "labels",
))


class DefaultBinder:
    """Writes the binding back to the cluster store (the reference POSTs a
    v1.Binding; the store reflects it into pod.node_name like kubelet+etcd
    would, cache.go:117-131)."""

    def __init__(self, cluster: ClusterStore):
        self.cluster = cluster

    def bind(self, pod, hostname: str) -> None:
        pod.node_name = hostname
        pod.phase = "Running"
        self.cluster.update("pods", pod)


class DefaultEvictor:
    """Sets PodReady=false then requests graceful deletion (cache.go:139-169).

    Deletion is graceful, as in k8s: the pod gets a deletion_timestamp and
    stays bound (task goes Releasing, so the freed space is FutureIdle, not
    Idle) until the kubelet stand-in finalizes the termination and removes
    the pod. Instant removal here would let the victim's replacement pod be
    recreated and re-bound in the very next cycle, starving the
    preemptor/reclaimer forever."""

    def __init__(self, cluster: ClusterStore):
        self.cluster = cluster

    def evict(self, pod, reason: str) -> None:
        pod.conditions = [c for c in pod.conditions if c.get("type") != "Ready"]
        pod.conditions.append({"type": "Ready", "status": "False",
                               "reason": "Evict", "message": reason})
        if pod.deletion_timestamp is None:
            pod.deletion_timestamp = time.time()
        self.cluster.update("pods", pod)


class DefaultStatusUpdater:
    def __init__(self, cluster: ClusterStore):
        self.cluster = cluster

    def update_pod_condition(self, pod, condition: dict) -> None:
        replaced = False
        for i, c in enumerate(pod.conditions):
            if c.get("type") == condition.get("type"):
                if c == condition:
                    # no-op rewrite: an unschedulable pod re-reported with
                    # the SAME condition every cycle would otherwise churn
                    # the store (and every mirror fed by it) per cycle —
                    # exactly the noise that keeps a quiet cluster's
                    # event-sourced flatten from being O(0)
                    return
                pod.conditions[i] = condition
                replaced = True
        if not replaced:
            pod.conditions.append(condition)
        if self.cluster.try_get("pods", pod.name, pod.namespace) is not None:
            self.cluster.update("pods", pod)

    def update_pod_group(self, pg) -> None:
        self.cluster.apply("podgroups", pg)


#: the WaitForFirstConsumer node pin (k8s volume-scheduling annotation)
SELECTED_NODE_ANNOTATION = "volume.kubernetes.io/selected-node"


class DefaultVolumeBinder:
    """WaitForFirstConsumer-style claim Assume/Bind against the cluster
    store (reference pkg/scheduler/cache/cache.go:234-254, which wraps k8s
    volumescheduling's AssumePodVolumes/BindPodVolumes; here the store
    itself plays the PV controller).

    allocate_volumes (statement.go:230-282's AllocateVolumes step) verifies
    every claim the pod references exists and is bindable on the chosen
    node, then records the tentative selection in memory — nothing is
    written. bind_volumes (statement Commit) writes the selected-node pin
    and flips the claim Bound; a write failure raises, and the statement's
    commit handler unwinds + resyncs the task. revert_volumes (statement
    Discard) drops the in-memory assumption."""

    def __init__(self, cluster):
        self.cluster = cluster
        # pod uid -> {(ns, claim): node} — in-flight Assume decisions,
        # visible to later assumes/predicates like volumescheduling's
        # assume cache (two same-session pods sharing a claim must agree);
        # session-scoped: the scheduler drops them at the next snapshot
        self._assumed: Dict[str, Dict[tuple, str]] = {}
        # reverse index for O(1) pin lookups on the predicate hot path
        self._assumed_by_claim: Dict[tuple, str] = {}

    def has_assumed(self) -> bool:
        """Whether any pod holds an in-flight volume assumption — when not,
        bind_volumes is a no-op for every task and batch commits skip the
        per-task calls entirely."""
        return bool(self._assumed)

    def allocate_volumes_batch(self, pairs) -> list:
        """allocate_volumes over [(task, hostname)]; returns
        [(task, hostname, exc)] failures. Volume-less pods (the typical
        burst) skip straight to volume_ready."""
        failures = []
        for task, hostname in pairs:
            if not getattr(task.pod, "volumes", None):
                task.volume_ready = True
                continue
            try:
                self.allocate_volumes(task, hostname)
            except (KeyError, ValueError) as e:
                failures.append((task, hostname, e))
        return failures

    @staticmethod
    def _claims(pod):
        for vol in getattr(pod, "volumes", None) or []:
            ref = (vol.get("persistentVolumeClaim") or {}).get("claimName")
            if ref:
                yield ref

    def missing_claims(self, pod) -> List[str]:
        return [name for name in self._claims(pod)
                if self.cluster.try_get("pvcs", name, pod.namespace) is None]

    def _pinned_node(self, key) -> Optional[str]:
        """Node a claim is pinned to: a written selected-node annotation,
        or any in-flight assumption. None = claim missing."""
        pvc = self.cluster.try_get("pvcs", key[1], key[0])
        if pvc is None:
            return None
        sel = (pvc.annotations or {}).get(SELECTED_NODE_ANNOTATION, "")
        return sel or self._assumed_by_claim.get(key, "")

    def node_ok(self, pod, hostname: str) -> bool:
        """Predicate half (volume-binding filter): every claim must exist
        and be unpinned or pinned to this node."""
        for name in self._claims(pod):
            sel = self._pinned_node((pod.namespace, name))
            if sel is None or (sel and sel != hostname):
                return False
        return True

    def drop_assumptions(self) -> None:
        """Called at snapshot time: assumptions are session-scoped (an
        uncommitted assume from a job that never dispatched must not pin
        the claim forever)."""
        self._assumed.clear()
        self._assumed_by_claim.clear()

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        pod = task.pod
        assumed = {}
        for name in self._claims(pod):
            key = (pod.namespace, name)
            sel = self._pinned_node(key)
            if sel is None:
                raise ValueError(
                    f"pvc <{pod.namespace}/{name}> for task <{task.key}> "
                    "not found")
            if sel and sel != hostname:
                raise ValueError(
                    f"pvc <{pod.namespace}/{name}> is pinned to node "
                    f"<{sel}>, cannot allocate <{task.key}> on <{hostname}>")
            assumed[key] = hostname
        if assumed:
            self._assumed[pod.uid] = assumed
            self._assumed_by_claim.update(assumed)
        task.volume_ready = True

    def _drop_pod(self, pod_uid: str) -> Optional[Dict[tuple, str]]:
        assumed = self._assumed.pop(pod_uid, None)
        if assumed:
            for key in assumed:
                # keep the reverse entry if another in-flight pod still
                # assumes the same claim (same node by construction)
                if not any(key in m for m in self._assumed.values()):
                    self._assumed_by_claim.pop(key, None)
        return assumed

    def bind_volumes(self, task: TaskInfo) -> None:
        pod = task.pod
        assumed = self._drop_pod(pod.uid)
        if not assumed:
            return
        written = []
        try:
            for (ns, name), node in assumed.items():
                pvc = self.cluster.get("pvcs", name, ns)
                sel = (pvc.annotations or {}).get(
                    SELECTED_NODE_ANNOTATION, "")
                if sel and sel != node:
                    raise ValueError(
                        f"pvc <{ns}/{name}> was bound to <{sel}> while "
                        f"assumed on <{node}>")
                prev = (pvc.annotations.get(SELECTED_NODE_ANNOTATION),
                        pvc.phase, pvc.volume_name)
                pvc.annotations[SELECTED_NODE_ANNOTATION] = node
                pvc.phase = "Bound"
                pvc.volume_name = pvc.volume_name or f"pv-{name}"
                self.cluster.update("pvcs", pvc)
                written.append((pvc, prev))
        except Exception:
            # unwind partial multi-claim binds so one stuck claim can't
            # strand the pod half-pinned forever
            for pvc, (prev_sel, prev_phase, prev_vol) in reversed(written):
                if prev_sel is None:
                    pvc.annotations.pop(SELECTED_NODE_ANNOTATION, None)
                else:
                    pvc.annotations[SELECTED_NODE_ANNOTATION] = prev_sel
                pvc.phase = prev_phase
                pvc.volume_name = prev_vol
                try:
                    self.cluster.update("pvcs", pvc)
                except Exception:
                    log.exception("failed to unwind pvc bind for %s",
                                  pvc.name)
            task.volume_ready = False
            raise

    def revert_volumes(self, task: TaskInfo) -> None:
        if self._drop_pod(task.pod.uid) is not None:
            task.volume_ready = False


class SchedulerCache:
    """Mirror of cluster state + effector plumbing."""

    def __init__(self, cluster: Optional[ClusterStore] = None,
                 scheduler_name: str = "volcano",
                 default_queue: str = "default",
                 async_effectors: bool = False):
        self.cluster = cluster if cluster is not None else ClusterStore()
        self.scheduler_name = scheduler_name
        self.default_queue = default_queue
        # async bind/evict dispatch (cache.go:505-512, 559-565 fire the API
        # writes in goroutines with resync-on-failure). Off by default: the
        # in-memory store makes synchronous effects deterministic for tests;
        # turn on when effects go to a remote control plane.
        self._effector_pool = (
            ThreadPoolExecutor(max_workers=4, thread_name_prefix="effector")
            if async_effectors else None)
        self._pending_effects: List = []

        self.jobs: Dict[str, JobInfo] = {}
        self.nodes: Dict[str, NodeInfo] = {}
        self.queues: Dict[str, QueueInfo] = {}
        self.priority_classes: Dict[str, object] = {}
        self.default_priority: int = 0
        self.default_priority_class = None
        self.namespace_collections: Dict[str, NamespaceCollection] = {}

        self.binder = DefaultBinder(self.cluster)
        self.evictor = DefaultEvictor(self.cluster)
        self.status_updater = DefaultStatusUpdater(self.cluster)
        self.volume_binder = DefaultVolumeBinder(self.cluster)

        self._err_tasks: List[TaskInfo] = []
        self._synced = False

        # incremental snapshot-flatten state shared across sessions
        # (ops.arrays.FlattenCache; versions on JobInfo/NodeInfo invalidate).
        # The allocate cache runs EVENT-SOURCED: every watch delivery below
        # forwards a typed delta (feed_event) as it arrives, and the
        # version-gated snapshot-clone seam in _snapshot_locked re-marks
        # whatever it re-cuts, so a scheduling cycle starts with the dirty
        # rows already known and flatten_snapshot patches exactly those —
        # host cost O(events since last cycle), ~zero on a quiet cluster
        from ..ops.arrays import FlattenCache
        from ..ops.device_cache import PackedDeviceCache
        from ..ops.ordering import OrderCache
        self.flatten_cache = FlattenCache()
        self.flatten_cache.enable_events()
        # event-sourced ordering (ops.ordering.OrderCache): the allocate
        # action's namespace/queue/job/task ordering inputs kept warm
        # across sessions, fed from the same delta seam as the flatten
        # ledger below — a cycle's ordering pass patches only event-dirty
        # jobs instead of re-sorting every pending job/task
        self.order_cache = OrderCache()
        # separate caches for preempt/reclaim flattens: each action's task
        # set differs from allocate's AND from the other's, and sharing a
        # cache clobbers the wholesale fast-path key every cycle
        self.evict_flatten_caches = {"preempt": FlattenCache(),
                                     "reclaim": FlattenCache()}
        # device-resident packed solver buffers (delta-shipped per session)
        self.device_cache = PackedDeviceCache()
        # node-axis sharded arena (ops.device_cache.ShardedDeviceCache):
        # built lazily by the allocate action's first sharded session —
        # constructing it eagerly would initialize jax/the mesh for
        # control planes that never dispatch sharded
        self.sharded_device_cache = None
        # --solver-mode preference consumed by Action.resolve_mode: None/
        # "packed" keep per-action conf routing, "sharded" dispatches the
        # shard_map solver, "auto" shards when the padded problem exceeds
        # sharded_byte_budget bytes per device (0 = never auto-shard)
        self.solver_mode = None
        self.sharded_byte_budget = 0
        # optional solver-sidecar client (parallel.sidecar.SidecarSolver):
        # when set, allocate ships snapshots to the solver process instead
        # of running the kernel in-process
        self.sidecar = None
        # compile-and-dispatch pipeline (ops.precompile): the Scheduler
        # installs a BucketPrewarmer here when enabled; pipeline_solver
        # gates the allocate action's dispatch/collect overlap
        self.prewarmer = None
        self.pipeline_solver = True
        # device-path circuit breaker (resilience.CircuitBreaker): the
        # Scheduler installs one; sessions read it for the device -> host
        # oracle degradation ladder in allocate/preempt/reclaim
        self.breaker = None
        # crash-safe HA seams (resilience/recovery.py + client.store
        # FencedStore), both installed by run_with_leader_election and
        # None everywhere else: the write-ahead bind-intent journal
        # (consumed by Statement.commit / flush_bulk_commit) and the
        # fenced store handle the effectors write through once fencing
        # is on
        self.bind_journal = None
        self.fenced_cluster = None
        # global rescheduler (volcano_tpu.reschedule): deployment-level
        # defaults for the reschedule action (--reschedule-* flags; per-
        # action conf arguments override), its cross-session state (cycle
        # counter, dedicated flatten/device caches, migration-intent
        # journal) and the bounded per-plan history the defrag bench and
        # tests read budget/cap compliance from
        self.reschedule_opts = None
        self.reschedule_state = None
        self.reschedule_log = []

        # job uid -> flat_version reflected by the last successful status
        # write; the job updater's skip-if-untouched check compares against
        # this (NOT session open) so inter-session informer changes count
        self.updater_versions: Dict[str, int] = {}
        # version-gated snapshot clone reuse (see _snapshot_locked)
        self._job_clone_cache: Dict[str, JobInfo] = {}
        self._node_clone_cache: Dict[str, NodeInfo] = {}

        self._create_default_queue()

    # -- startup ------------------------------------------------------------

    def _create_default_queue(self) -> None:
        """Reference creates the default queue CR at startup
        (cache.go:270-283). Losing the create race is fine — two HA
        schedulers attaching to one networked store both run this."""
        if self.cluster.try_get("queues", self.default_queue) is None:
            try:
                self.cluster.create(
                    "queues",
                    Queue(name=self.default_queue, spec=QueueSpec(weight=1)))
            except ConflictError:
                pass  # a peer created it between our read and write

    def install_fencing(self, token_provider) -> None:
        """Route every effector write (bind, evict, status update, volume
        pin) through a FencedStore carrying ``token_provider()``'s lease
        token, so the authoritative store — not the writer's own view of
        its leadership — arbitrates split brain (client.store.FencedStore;
        Omega-style optimistic commit fencing). Only effectors still
        pointed at this cache's raw cluster are rewired: fakes and
        recording decorators are left alone. Idempotent."""
        from ..client.store import FencedStore
        if self.fenced_cluster is not None:
            return
        fenced = FencedStore(self.cluster, token_provider)
        self.fenced_cluster = fenced
        for effector in (self.binder, self.evictor, self.status_updater,
                         self.volume_binder):
            if getattr(effector, "cluster", None) is self.cluster:
                effector.cluster = fenced

    def run(self) -> None:
        """Subscribe to the store's watch streams (informer start).
        Idempotent: repeated Scheduler.run() calls must not double-subscribe
        (the reference starts its informer factory once)."""
        if self._synced:
            return
        c = self.cluster
        c.watch("pods", self._on_pod)
        c.watch("nodes", self._on_node)
        c.watch("podgroups", self._on_podgroup)
        c.watch("queues", self._on_queue)
        c.watch("priorityclasses", self._on_priority_class)
        c.watch("resourcequotas", self._on_resource_quota)
        self._synced = True

    def wait_for_cache_sync(self) -> bool:
        return self._synced

    # -- watch dispatch -----------------------------------------------------

    def _feed_flatten(self, kind, event, job=None, node=None):
        """Forward one typed delta to the event-sourced flatten AND
        ordering ledgers (no-op for embeddings that run without the
        caches). One seam, two consumers: the watch hooks and the
        version-gated snapshot-clone catch-all below keep both caches'
        dirty sets complete with a single call site."""
        fc = self.flatten_cache
        if fc is not None:
            fc.feed_event(kind, event, job=job, node=node)
        oc = self.order_cache
        if oc is not None:
            oc.feed_event(kind, event, job=job, node=node)

    def _on_pod(self, event, obj, old, changed=None):
        if obj.scheduler_name == self.scheduler_name:
            key = job_key_of_pod(obj)
            self._feed_flatten("pod", event, job=key,
                               node=obj.node_name or None)
            if old is not None and old.node_name \
                    and old.node_name != obj.node_name:
                self._feed_flatten("pod", event, job=key,
                                   node=old.node_name)
        if event == "add":
            # resync-safe: a watch-resume (or re-list) can replay an add
            # for a pod this mirror already tracks; treating it as an
            # update keeps the node/job accounting single-counted instead
            # of raising out of the delivery (informer AddFunc semantics
            # on a re-listed object)
            if self._stored_task(TaskInfo(obj)) is not None:
                self.update_pod(obj, obj)
            else:
                self.add_pod(obj)
        elif event == "update":
            # a delta watch stream names the changed fields; when they
            # fit the targeted path, skip the full TaskInfo rebuild
            if changed is None or not self.apply_pod_delta(
                    old, obj, changed):
                self.update_pod(old, obj)
        else:
            self.delete_pod(obj)

    # a delta-capable store passes (event, obj, old, changed_fields) —
    # detected via getattr on the bound method (client/remote.py)
    _on_pod.delta_aware = True

    def _on_node(self, event, obj, old):
        # an "add" for an already-known node is a respec in place (no
        # position change); a genuinely new node relays the padded axis
        ev = event
        if event == "add" and obj.name in self.nodes \
                and self.nodes[obj.name].node is not None:
            ev = "update"
        self._feed_flatten("node", ev, node=obj.name)
        if event == "add":
            self.add_node(obj)
        elif event == "update":
            self.update_node(obj)
        else:
            self.delete_node(obj)

    def _on_podgroup(self, event, obj, old):
        self._feed_flatten("podgroup", event,
                           job=f"{obj.namespace}/{obj.name}")
        if event == "delete":
            self.delete_pod_group(obj)
        else:
            self.set_pod_group(obj)

    def _on_queue(self, event, obj, old):
        self._feed_flatten("queue", event)
        if event == "delete":
            self.delete_queue(obj)
        else:
            self.add_queue(obj)

    def _on_priority_class(self, event, obj, old):
        if event == "delete":
            self.delete_priority_class(obj)
        else:
            self.add_priority_class(obj)

    def _on_resource_quota(self, event, obj, old):
        name = obj.namespace
        coll = self.namespace_collections.setdefault(
            name, NamespaceCollection(name))
        if event == "delete":
            coll.delete(obj)
        else:
            coll.update(obj)

    # -- pod/task handlers (event_handlers.go:43-210) ------------------------

    def _get_or_create_job(self, ti: TaskInfo) -> Optional[JobInfo]:
        if not ti.job:
            return None  # bare pod: podgroup controller will wrap it
        if ti.job not in self.jobs:
            self.jobs[ti.job] = JobInfo(ti.job)
        return self.jobs[ti.job]

    def add_task(self, ti: TaskInfo) -> None:
        job = self._get_or_create_job(ti)
        if job is not None:
            job.add_task_info(ti)
        if ti.node_name:
            if ti.node_name not in self.nodes:
                self.nodes[ti.node_name] = NodeInfo()
                self.nodes[ti.node_name].name = ti.node_name
            # Terminated tasks (Succeeded/Failed) hold no node resources
            # (event_handlers.go:69-72 isTerminated gate).
            if ti.status not in (TaskStatus.SUCCEEDED, TaskStatus.FAILED):
                self.nodes[ti.node_name].add_task(ti)

    def add_pod(self, pod) -> None:
        if pod.scheduler_name != self.scheduler_name:
            return
        self.add_task(TaskInfo(pod))

    def delete_task(self, ti: TaskInfo) -> None:
        job_err = node_err = None
        if ti.job and ti.job in self.jobs:
            try:
                self.jobs[ti.job].delete_task_info(ti)
            except KeyError as e:
                job_err = e
        # skip node removal when the node never held the task (terminated
        # tasks aren't added — the isTerminated gate in add_task; the
        # reference logs a spurious error here instead). Membership, not
        # ti.status, is the test: watch deliveries can alias old/new pod
        # objects, and accounting uses the node's stored clone anyway.
        if ti.node_name and ti.node_name in self.nodes:
            node = self.nodes[ti.node_name]
            if ti.key in node.tasks:
                try:
                    node.remove_task(ti)
                except KeyError as e:
                    node_err = e
        if job_err or node_err:
            raise KeyError(f"failed to delete task {ti.key}: {job_err} {node_err}")

    def _stored_task(self, ti: TaskInfo) -> Optional[TaskInfo]:
        """The task as THIS cache knows it. Event objects from a remote
        store are decoded copies, so an update's ``old`` can lag the
        cache's own effector writes (cache.bind set node_name before the
        informer echo arrives); deleting by the stale copy would skip the
        node removal and the re-add would double-place. In-process the
        store shares objects, which masked this."""
        job = self.jobs.get(ti.job)
        if job is None:
            return None
        return job.tasks.get(ti.key)

    def update_pod(self, old_pod, new_pod) -> None:
        if new_pod.scheduler_name != self.scheduler_name:
            return
        old_ti = TaskInfo(old_pod)
        stored = self._stored_task(old_ti)
        try:
            self.delete_task(stored if stored is not None else old_ti)
        except KeyError:
            pass
        self.add_task(TaskInfo(new_pod))

    def apply_pod_delta(self, old_pod, new_pod, changed) -> bool:
        """Targeted update for a delta-watch column patch: ``changed``
        names the pod fields the patch touched. When they all fit the
        safe set, re-place the STORED TaskInfo through the same
        delete_task/add_task seams the generic path uses — identical
        index ordering, aggregate arithmetic and node accounting — but
        without re-deriving a TaskInfo (the resreq parse and status/key
        derivation are the per-event cost this path exists to kill).
        Returns False when the caller must run the generic rebuild."""
        if not _DELTA_FAST_FIELDS.issuperset(changed):
            return False
        if new_pod.scheduler_name != self.scheduler_name:
            return True  # not ours: same early-out as update_pod
        job = self.jobs.get(job_key_of_pod(new_pod))
        stored = job.tasks.get(pod_key(new_pod)) \
            if job is not None else None
        if stored is None:
            # bare pod or a task this mirror never added: the generic
            # ladder owns the odd cases
            return False
        try:
            self.delete_task(stored)
        except KeyError:
            pass
        stored.node_name = new_pod.node_name or ""
        stored.status = status_of_pod(new_pod)
        stored.priority = new_pod.priority \
            if new_pod.priority is not None else 1
        # reset exactly what a fresh TaskInfo(new_pod) would: the
        # rebuilt arm of an A/B run must not observe state this arm
        # carried over
        stored.volume_ready = False
        stored.sig_cache = None
        stored.pod = new_pod
        self.add_task(stored)
        return True

    def delete_pod(self, pod) -> None:
        if pod.scheduler_name != self.scheduler_name:
            return
        ti = TaskInfo(pod)
        stored = self._stored_task(ti)
        try:
            self.delete_task(stored if stored is not None else ti)
        except KeyError as e:
            log.warning("delete_pod: %s", e)
        job = self.jobs.get(ti.job)
        if job is not None and not job.tasks and job.pod_group is None:
            del self.jobs[ti.job]
            self.updater_versions.pop(ti.job, None)
            self._job_clone_cache.pop(ti.job, None)

    # -- node handlers ------------------------------------------------------

    def add_node(self, node) -> None:
        if node.name in self.nodes:
            self.nodes[node.name].set_node(node)
        else:
            ni = NodeInfo(node)
            # preserve tasks recorded before the node object arrived
            self.nodes[node.name] = ni

    update_node = add_node

    def delete_node(self, node) -> None:
        self.nodes.pop(node.name, None)
        self._node_clone_cache.pop(node.name, None)

    # -- podgroup / queue / priorityclass handlers --------------------------

    def set_pod_group(self, pg: PodGroup) -> None:
        key = f"{pg.namespace}/{pg.name}"
        if key not in self.jobs:
            self.jobs[key] = JobInfo(key)
        self.jobs[key].set_pod_group(pg)

    def delete_pod_group(self, pg: PodGroup) -> None:
        key = f"{pg.namespace}/{pg.name}"
        job = self.jobs.get(key)
        if job is None:
            return
        job.pod_group = None
        if not job.tasks:
            del self.jobs[key]
            self.updater_versions.pop(key, None)
            self._job_clone_cache.pop(key, None)

    def add_queue(self, queue: Queue) -> None:
        self.queues[queue.name] = QueueInfo(queue)

    def delete_queue(self, queue: Queue) -> None:
        self.queues.pop(queue.name, None)

    def add_priority_class(self, pc) -> None:
        if pc.global_default:
            self.default_priority = pc.value
            self.default_priority_class = pc
        self.priority_classes[pc.name] = pc

    def delete_priority_class(self, pc) -> None:
        self.priority_classes.pop(pc.name, None)
        if pc.global_default:
            self.default_priority = 0
            self.default_priority_class = None

    # -- resync (cache.go:645-667) ------------------------------------------

    def resync_task(self, task: TaskInfo) -> None:
        self._err_tasks.append(task)

    def process_resync_tasks(self) -> None:
        """Re-sync err tasks from store truth (informer ground truth)."""
        tasks, self._err_tasks = self._err_tasks, []
        for task in tasks:
            pod = self.cluster.try_get("pods", task.name, task.namespace)
            try:
                self.delete_task(task)
            except KeyError:
                pass
            if pod is not None:
                self.add_task(TaskInfo(pod))

    # -- snapshot (cache.go:670-748) ----------------------------------------

    #: kubelet-of-last-resort grace: an evicted pod still carrying its
    #: deletion_timestamp after this long is finalized by the scheduler
    #: cache itself — scheduler-only embeddings (no ControllerManager, so
    #: no KubeletStandin) must still converge after evictions
    EVICTION_FINALIZE_GRACE = 60.0

    def _finalize_expired_evictions(self) -> None:
        now = time.time()
        # materialize: deleting a pod can drop its job from self.jobs via
        # the delete listener while we iterate
        for job in list(self.jobs.values()):
            for task in list(job.task_status_index.get(
                    TaskStatus.RELEASING, {}).values()):
                pod = self.cluster.try_get("pods", task.name,
                                           task.namespace)
                if pod is None or pod.deletion_timestamp is None:
                    continue
                if now - pod.deletion_timestamp \
                        > self.EVICTION_FINALIZE_GRACE:
                    try:
                        self.cluster.delete("pods", pod.name, pod.namespace)
                    except NotFoundError:
                        pass

    def snapshot(self) -> ClusterInfo:
        # Take the store's write lock for the whole clone: async effector
        # threads mutate this cache via store listeners (which run under
        # that lock), so holding it here is the SchedulerCache.Mutex of the
        # reference (cache.go:72, Snapshot locks before cloning).
        with self.cluster.locked():
            self._finalize_expired_evictions()
            return self._snapshot_locked()

    def _snapshot_locked(self) -> ClusterInfo:
        drop = getattr(self.volume_binder, "drop_assumptions", None)
        if drop is not None:
            drop()  # assumptions are session-scoped
        sn = ClusterInfo()
        # Version-gated clone reuse: a clone handed to the PREVIOUS session
        # can serve again iff (a) the cache object hasn't changed since it
        # was cut AND (b) the session didn't mutate the clone — both
        # observable as recorded == cache.flat_version == clone.flat_version
        # (every mutation path bumps the version). This cuts the per-cycle
        # clone fan-out, the scheduler's host floor, to the churned subset —
        # the same delta idea the flatten/device caches use. Contract:
        # sessions on one cache are SEQUENTIAL (the scheduler loop); the
        # reference's snapshot has the same assumption (one runOnce at a
        # time under the scheduler mutex, cache.go:693-742).
        for name, ni in self.nodes.items():
            if not ni.ready:
                continue
            prev = self._node_clone_cache.get(name)
            if prev is not None and prev.flat_version == ni.flat_version \
                    and prev.flat_epoch == ni.flat_epoch:
                sn.nodes[name] = prev
                continue
            # version-gated clone seam doubles as the event feed's
            # catch-all: ANY divergence since the last cycle (a watch
            # delivery, a direct effector mutation, a session-mutated
            # clone) forces a re-cut, and the re-cut marks the row dirty
            # for the event-sourced flatten — so a delta the watch hooks
            # never saw still lands in the ledger before the flatten runs
            self._feed_flatten("node", "resync", node=name)
            clone = ni.clone()
            self._node_clone_cache[name] = clone
            sn.nodes[name] = clone
        for name, qi in self.queues.items():
            sn.queues[name] = qi.clone()
        for name, coll in self.namespace_collections.items():
            sn.namespace_info[name] = coll.snapshot()
        for key, job in self.jobs.items():
            if job.pod_group is None:
                log.info("job %s skipped: scheduling spec undefined", key)
                continue
            if job.queue not in self.queues:
                log.info("job %s skipped: queue %s not found", key, job.queue)
                continue
            prev = self._job_clone_cache.get(key)
            # clone() copies the version and the global counter never
            # repeats, so one comparison covers both cache-side and
            # session-side mutation since the clone was cut
            if prev is None or prev.flat_version != job.flat_version:
                # re-cut ahead: mark the job dirty for the event-sourced
                # flatten (same catch-all as the node seam above)
                self._feed_flatten("job", "resync", job=key)
            if prev is not None and prev.flat_version == job.flat_version:
                clone = prev
                # per-session slates that don't bump the version; the
                # timestamp reset matches fresh-clone-per-cycle semantics
                # (the cache-side job never carries it, so a fresh clone
                # always started from None)
                if clone.nodes_fit_errors:
                    clone.nodes_fit_errors = {}
                clone.schedule_start_timestamp = None
            else:
                clone = job.clone()
                self._job_clone_cache[key] = clone
            # resolve job priority from the PodGroup's priority class
            clone.priority = self.default_priority
            pc = self.priority_classes.get(clone.priority_class_name)
            if pc is not None:
                clone.priority = pc.value
            sn.jobs[key] = clone
        return sn

    # -- effector paths (cache.go:450-578) ----------------------------------

    def _find_job_and_task(self, ti: TaskInfo):
        job = self.jobs.get(ti.job)
        if job is None:
            raise KeyError(f"failed to find Job {ti.job} for Task {ti.key}")
        task = job.tasks.get(ti.key)
        if task is None:
            raise KeyError(f"failed to find task in status {ti.status} by key {ti.key}")
        return job, task

    def bind(self, ti: TaskInfo, hostname: str) -> None:
        job, task = self._find_job_and_task(ti)
        node = self.nodes.get(hostname)
        if node is None:
            raise KeyError(f"failed to bind Task {ti.key} to host {hostname}: "
                           "host does not exist")
        original = task.status
        job.update_task_status(task, TaskStatus.BINDING)
        try:
            node.add_task(task)
        except ValueError:
            job.update_task_status(task, original)
            raise
        start = (job.schedule_start_timestamp
                 or task.pod.creation_timestamp or 0.0)

        def effect():
            self.binder.bind(task.pod, hostname)
            metrics.schedule_attempts.inc(labels={"result": "scheduled"})
            if start:
                metrics.task_scheduling_latency.observe(
                    (time.time() - start) * 1e3)

        def failed():
            metrics.schedule_attempts.inc(labels={"result": "error"})
            self.resync_task(task)

        self._dispatch_effect(effect, failed, f"bind {task.key}")

    def bind_batch(self, tis) -> list:
        """Batched bind(): identical per-task cache state, but one
        accounting pass per (job, node) group and ONE dispatched effect for
        the whole wave — bind() dispatches an effect per task
        (cache.go:450-478's per-goroutine shape), which at a 10k-pod burst
        is most of the replay's host cost. Returns [(ti, exc)] for tasks
        whose cache-side accounting failed, carrying the same exceptions
        bind() would have raised; those tasks get no effect."""
        failures: list = []
        bound: list = []
        starts: list = []
        slow: list = []
        by_node: Dict[str, list] = {}
        last_jobid = None  # statements commit per job: one lookup suffices
        job = None
        seen = set()
        for ti in tis:
            if ti.job != last_jobid:
                job = self.jobs.get(ti.job)
                last_jobid = ti.job
            task = job.tasks.get(ti.key) if job is not None else None
            # duplicates within the wave go per-task: the second bind()
            # raises 'already on node' instead of double-counting
            if task is None or task.key in seen:
                slow.append(ti)
                continue
            seen.add(task.key)
            group = by_node.get(ti.node_name)
            if group is None:
                by_node[ti.node_name] = [(ti, job, task)]
            else:
                group.append((ti, job, task))
        # each node group is validated up front (same checks bind() relies
        # on, whole-group fit included) so the bulk mutators cannot raise
        # mid-wave; invalid groups demote to per-task bind()
        fast_nodes = []
        for hostname, group in by_node.items():
            node = self.nodes.get(hostname)
            ok = node is not None and node.node is not None
            if ok:
                node_tasks = node.tasks
                for _, _, task in group:
                    if task.key in node_tasks or (
                            task.node_name and task.node_name != hostname):
                        ok = False
                        break
            if ok:
                req = group[0][2].resreq if len(group) == 1 \
                    else Resource.sum_of(t.resreq for _, _, t in group)
                ok = req.less_equal(node.idle)
            if ok:
                fast_nodes.append((node, group))
            else:
                # demote the ORIGINAL input objects: bind() re-resolves its
                # own task and the failure tuples must hand callers back
                # what they gave us, never cache-side objects
                slow.extend(ti for ti, _, _ in group)
        by_job: Dict[str, tuple] = {}
        for node, group in fast_nodes:
            for ent3 in group:
                ent = by_job.get(ent3[2].job)
                if ent is None:
                    by_job[ent3[2].job] = (ent3[1], [ent3])
                else:
                    ent[1].append(ent3)
        demoted = set()
        for job, group in by_job.values():
            try:
                # raises BEFORE mutating (aggregates pre-checked): the
                # job's wave demotes to per-task bind() on failure
                job.bulk_update_status([t for _, _, t in group],
                                       TaskStatus.BINDING)
            except (KeyError, ValueError):
                demoted.update(id(t) for _, _, t in group)
                continue
            start = job.schedule_start_timestamp
            for _, _, task in group:
                bound.append(task)
                starts.append(start or task.pod.creation_timestamp or 0.0)
        for node, group in fast_nodes:
            if demoted:
                kept = [e for e in group if id(e[2]) not in demoted]
                slow.extend(e[0] for e in group if id(e[2]) in demoted)
                if not kept:
                    continue
                group = kept
            node.add_tasks_bulk([t for _, _, t in group], validated=True)
        for ti in slow:
            try:
                self.bind(ti, ti.node_name)
            except (KeyError, ValueError) as e:
                failures.append((ti, e))
        if bound:
            def effect():
                ok = 0
                lat = []
                for task, start in zip(bound, starts):
                    try:
                        self.binder.bind(task.pod, task.node_name)
                    except Exception:
                        log.exception("bind %s failed", task.key)
                        metrics.schedule_attempts.inc(
                            labels={"result": "error"})
                        self.resync_task(task)
                        continue
                    ok += 1
                    if start:
                        lat.append((time.time() - start) * 1e3)
                if ok:
                    metrics.schedule_attempts.inc(
                        ok, labels={"result": "scheduled"})
                metrics.task_scheduling_latency.observe_many(lat)

            self._dispatch_effect(effect, lambda: None,
                                  f"bind batch of {len(bound)}")
        return failures

    def evict(self, ti: TaskInfo, reason: str) -> None:
        job, task = self._find_job_and_task(ti)
        node = self.nodes.get(task.node_name)
        if node is None:
            raise KeyError(f"failed to evict Task {ti.key}: host "
                           f"{task.node_name} does not exist")
        original = task.status
        job.update_task_status(task, TaskStatus.RELEASING)
        try:
            node.update_task(task)
        except (ValueError, KeyError):
            job.update_task_status(task, original)
            raise
        self._dispatch_effect(
            lambda: self.evictor.evict(task.pod, reason),
            lambda: self.resync_task(task), f"evict {task.key}")

    def _dispatch_effect(self, effect, failed, what: str) -> None:
        """Run a side-effect against the control plane: inline by default,
        in the effector pool when async (the reference's fire-and-forget
        goroutines with rate-limited resync on failure)."""

        def run():
            try:
                effect()
            except Exception:
                log.exception("%s failed", what)
                failed()

        if self._effector_pool is None:
            run()
        else:
            # prune completed futures so long-running schedulers that never
            # drain explicitly don't accumulate them without bound
            self._pending_effects = [f for f in self._pending_effects
                                     if not f.done()]
            self._pending_effects.append(self._effector_pool.submit(run))

    def wait_for_effects(self) -> None:
        """Drain in-flight async effects (tests / clean shutdown)."""
        pending, self._pending_effects = self._pending_effects, []
        for fut in pending:
            fut.result()

    def allocate_volumes(self, task: TaskInfo, hostname: str) -> None:
        self.volume_binder.allocate_volumes(task, hostname)

    def allocate_volumes_batch(self, pairs) -> list:
        """Batched allocate_volumes; [(task, hostname, exc)] failures."""
        vb = self.volume_binder
        batch = getattr(vb, "allocate_volumes_batch", None)
        if batch is not None:
            return batch(pairs)
        failures = []
        for task, hostname in pairs:
            try:
                vb.allocate_volumes(task, hostname)
            except (KeyError, ValueError) as e:
                failures.append((task, hostname, e))
        return failures

    def bind_volumes(self, task: TaskInfo) -> None:
        self.volume_binder.bind_volumes(task)

    def bind_volumes_batch(self, tasks) -> list:
        """bind_volumes over a wave; returns [(task, exc)] failures. When
        the volume binder reports no in-flight assumptions at all, the
        whole wave is a no-op and the per-task calls are skipped (the
        common case: a 10k-pod burst of volume-less pods)."""
        vb = self.volume_binder
        pending = getattr(vb, "has_assumed", None)
        if pending is not None and not pending():
            return []
        failures = []
        for t in tasks:
            try:
                vb.bind_volumes(t)
            except Exception as e:  # noqa: BLE001 — mirrors bind failure path
                failures.append((t, e))
        return failures

    def revert_volumes(self, task: TaskInfo) -> None:
        revert = getattr(self.volume_binder, "revert_volumes", None)
        if revert is not None:
            revert(task)

    def task_unschedulable(self, task: TaskInfo, message: str) -> None:
        """Write the Unschedulable pod condition (cache.go:590-612)."""
        metrics.schedule_attempts.inc(labels={"result": "unschedulable"})
        self.status_updater.update_pod_condition(task.pod, {
            "type": "PodScheduled", "status": "False",
            "reason": "Unschedulable", "message": message,
        })

    # -- job status writes (cache.go:760-855) -------------------------------

    def update_job_status(self, job: JobInfo, update_pg: bool = True) -> JobInfo:
        if update_pg and job.pod_group is not None:
            pg = job.pod_group
            pg.status.running = len(
                job.task_status_index.get(TaskStatus.RUNNING, {}))
            pg.status.succeeded = len(
                job.task_status_index.get(TaskStatus.SUCCEEDED, {}))
            pg.status.failed = len(
                job.task_status_index.get(TaskStatus.FAILED, {}))
            self.status_updater.update_pod_group(pg)
        self.record_job_status_event(job)
        return job

    def record_job_status_event(self, job: JobInfo) -> None:
        """Propagate per-task fit errors into pod conditions for
        unschedulable jobs (cache.go:791-826)."""
        if job.pod_group is None or job.ready():
            return
        base_msg = job.fit_message()
        for task in job.task_status_index.get(TaskStatus.PENDING, {}).values():
            fit_errors = job.nodes_fit_errors.get(task.key)
            msg = base_msg if fit_errors is None else fit_errors.error()
            try:
                self.task_unschedulable(task, msg)
            except Exception:
                log.exception("failed to update unschedulable condition for %s",
                              task.key)

    def string(self) -> str:
        return (f"SchedulerCache(jobs={len(self.jobs)} nodes={len(self.nodes)} "
                f"queues={len(self.queues)})")
