"""Cluster-state cache + effector seams (reference pkg/scheduler/cache)."""

from .cache import (  # noqa: F401
    DefaultBinder, DefaultEvictor, DefaultStatusUpdater, DefaultVolumeBinder,
    SchedulerCache,
)
from .fakes import (  # noqa: F401
    FakeBinder, FakeEvictor, FakeStatusUpdater, FakeVolumeBinder,
    RecordingBinder, RecordingEvictor,
)
from .interface import Binder, Cache, Evictor, StatusUpdater, VolumeBinder  # noqa: F401
