"""Device-resident packed solver arena with chunked delta upload.

The tunnel to a remote TPU is latency- and bandwidth-expensive: re-shipping
the full packed snapshot (~0.5 MB at 10k tasks / 2k nodes) every session
costs ~100 ms, while the cluster typically changes a few rows per cycle.
This cache keeps the two packed buffers (ops.arrays.SnapshotArrays.packed)
resident on device ACROSS scheduling sessions and ships only the chunks
whose bytes changed since the previous session, applied with a donated
in-place scatter — the TPU-native analog of the reference's informer
deltas (client-go list-watch keeps the scheduler's mirror warm instead of
re-listing the cluster, pkg/scheduler/cache/cache.go:319-402).

Arena contract (what survives what):

- **Chunked packed buffers** (``_dev_f``/``_dev_i``): device-resident
  across sessions; donated into the fused solve each dispatch. Lost on
  ``invalidate()``/``reset()`` — a donated dispatch that failed at
  readback has already consumed them.
- **Score params** (``params_device``): device-resident across sessions,
  NEVER donated — they survive a collect failure and are re-validated
  (not re-uploaded) on the next session via ``invalidate()``'s suspect
  flag. Only content changes or actual device-side deletion re-pin them.
- **Host mirror** (``_host_f``/``_host_i``): host memory; survives
  ``invalidate()`` untouched (it is rebuilt by the full re-ship anyway)
  and exists so per-session diffs are chunk-exact.

Accounting (``last_shipped_bytes``, ``arena_hit_rate`` …) feeds the
``volcano_arena_*`` metrics, ``Scheduler.last_cycle_timing`` and the
bench's bytes-shipped-per-session artifact fields.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def _pow2_bucket(n: int) -> int:
    """Strict powers of two, deliberately NOT ops.arrays.bucket (whose
    quarter-steps minimize padding): dirty-chunk counts vary every session,
    so the scatter kernel wants the fewest possible compiled variants."""
    b = 1
    while b < n:
        b <<= 1
    return b


_APPLY = None  # lazily created singleton so the jit caches across sessions


def _scatter(dev, idx, vals):
    global _APPLY
    if _APPLY is None:
        import jax
        _APPLY = jax.jit(lambda d, i, v: d.at[i].set(v), donate_argnums=(0,))
    return _APPLY(dev, idx, vals)


class PackedDeviceCache:
    """update(fbuf, ibuf, layout) -> (f2d, i2d) device arrays [C, chunk].

    First call (or any layout/shape change) ships everything; later calls
    diff against the previously shipped host copy chunk-wise and scatter
    only dirty chunks. Chunk-index uploads are bucketed to powers of two so
    the scatter kernel compiles a handful of times, not per session.
    """

    def __init__(self, chunk: int = 512):
        self.chunk = chunk
        self._host_f: Optional[np.ndarray] = None  # padded copy, [Cf*chunk]
        self._host_i: Optional[np.ndarray] = None
        self._dev_f = None                         # [Cf, chunk] on device
        self._dev_i = None
        self._layout = None
        self._params_blob = None
        self._params_dev = None
        #: device buffers untrusted (collect failure after a donated
        #: dispatch): next session full-ships and re-validates params
        self._params_suspect = False
        # previous mirror buffers recycled as diff scratch (the diff
        # allocated two full-buffer copies per session before)
        self._scratch_f: Optional[np.ndarray] = None
        self._scratch_i: Optional[np.ndarray] = None
        # -- arena accounting (diagnostics + volcano_arena_* metrics) ----
        self.last_shipped_chunks = 0
        self.last_shipped_bytes = 0     # wire bytes of the last delta/ship
        self.last_full_ship = False
        self.sessions = 0               # update/plan_delta calls
        self.full_ships = 0             # sessions that re-shipped everything
        self.delta_sessions = 0         # sessions that shipped a delta
        self.invalidations = 0          # soft resets (collect failures)
        self.params_repins = 0          # device params re-uploaded
        self.total_shipped_bytes = 0

    # -- arena introspection -------------------------------------------

    @property
    def arena_hit_rate(self) -> float:
        """Fraction of sessions served by a delta against the resident
        arena (1.0 = never re-shipped after the first session)."""
        if not self.sessions:
            return 0.0
        return self.delta_sessions / self.sessions

    def full_upload_bytes(self) -> int:
        """Wire cost of one full padded-buffer upload at the current
        layout (the denominator of the <10%-of-full acceptance check)."""
        if self._host_f is None or self._host_i is None:
            return 0
        return int(self._host_f.nbytes + self._host_i.nbytes)

    def reset(self) -> None:
        """Hard reset: drop the mirror, the device-resident state AND the
        pinned params so the next session rebuilds everything. Used when
        the HOST-side mirror itself may have desynced from the device (a
        partial scatter failure mid-apply) — after that, nothing this
        object remembers can be trusted."""
        self._host_f = self._host_i = None
        self._dev_f = self._dev_i = None
        self._layout = None
        self._params_blob = None
        self._params_dev = None
        self._params_suspect = False

    def invalidate(self) -> None:
        """Soft reset after an async-collect failure: by the time the
        error surfaced, a donated dispatch had already consumed the
        chunked buffers, so they are gone — but the score params were
        NEVER donated and usually survive, and the host mirror is host
        memory. Drop exactly what the donation poisoned: the next session
        full-ships the chunked buffers (one expensive upload, not a
        permanent cold path) and re-validates the pinned params in place
        instead of re-uploading them."""
        self._dev_f = self._dev_i = None
        self._layout = None  # forces the full re-ship
        self._params_suspect = True
        self.invalidations += 1

    # -- shared mirror maintenance (update + plan_delta flows) ----------

    def _full_ship(self, fbuf, ibuf, layout, cf: int, ci: int):
        """(Re)establish the host mirror and device buffers wholesale."""
        import jax

        c = self.chunk
        hf = np.zeros(cf * c, np.float32)
        hf[:fbuf.size] = fbuf
        hi = np.zeros(ci * c, np.int32)
        hi[:ibuf.size] = ibuf
        self._host_f, self._host_i = hf, hi
        self._dev_f = jax.device_put(hf.reshape(cf, c))
        self._dev_i = jax.device_put(hi.reshape(ci, c))
        self._layout = layout
        self.last_shipped_chunks = cf + ci
        self._account(cf + ci, hf.nbytes + hi.nbytes, full=True)

    def _account(self, chunks: int, wire_bytes: int, full: bool) -> None:
        self.sessions += 1
        self.last_shipped_chunks = int(chunks)
        self.last_shipped_bytes = int(wire_bytes)
        self.last_full_ship = bool(full)
        self.total_shipped_bytes += int(wire_bytes)
        if full:
            self.full_ships += 1
        else:
            self.delta_sessions += 1

    def _needs_full_ship(self, layout, cf: int, ci: int) -> bool:
        c = self.chunk
        return (self._layout != layout or self._host_f is None
                or self._host_f.size != cf * c
                or self._host_i.size != ci * c)

    def _diff(self, fbuf, ibuf, cf: int, ci: int):
        """Pad new content into mirror-shaped buffers and locate dirty
        chunks: (f2, i2, df, di). Does NOT update the mirror (see
        _commit_mirror). The padded buffers come from the scratch pool —
        the previous session's mirror, recycled — so a steady session
        allocates no full-size arrays."""
        c = self.chunk
        f2, i2 = self._scratch_f, self._scratch_i
        if f2 is None or f2.size != cf * c:
            f2 = np.zeros(cf * c, np.float32)
        else:
            f2[fbuf.size:] = 0.0
        if i2 is None or i2.size != ci * c:
            i2 = np.zeros(ci * c, np.int32)
        else:
            i2[ibuf.size:] = 0
        self._scratch_f = self._scratch_i = None
        f2[:fbuf.size] = fbuf
        i2[:ibuf.size] = ibuf
        df = np.nonzero((f2.reshape(cf, c)
                         != self._host_f.reshape(cf, c)).any(axis=1))[0]
        di = np.nonzero((i2.reshape(ci, c)
                         != self._host_i.reshape(ci, c)).any(axis=1))[0]
        return f2, i2, df, di

    def _commit_mirror(self, f2, i2) -> None:
        """Adopt the diffed buffers as the new mirror; the old mirror
        becomes next session's diff scratch."""
        self._scratch_f, self._scratch_i = self._host_f, self._host_i
        self._host_f, self._host_i = f2, i2

    def update(self, fbuf: np.ndarray, ibuf: np.ndarray,
               layout) -> Tuple[object, object]:
        c = self.chunk
        cf = -(-max(fbuf.size, 1) // c)
        ci = -(-max(ibuf.size, 1) // c)
        if self._needs_full_ship(layout, cf, ci):
            self._full_ship(fbuf, ibuf, layout, cf, ci)
            return self._dev_f, self._dev_i

        f2, i2, df, di = self._diff(fbuf, ibuf, cf, ci)
        try:
            new_f = self._apply(self._dev_f, df, f2.reshape(cf, c))
            new_i = self._apply(self._dev_i, di, i2.reshape(ci, c))
        except Exception:
            # a partial scatter (or a donated-buffer loss) would desync the
            # device copy from the host mirror: drop everything so the next
            # session re-ships in full instead of solving on stale data
            self.reset()
            raise
        self._dev_f, self._dev_i = new_f, new_i
        self._commit_mirror(f2, i2)
        self._account(df.size + di.size,
                      self._scatter_wire_bytes(df, di), full=False)
        return self._dev_f, self._dev_i

    def _scatter_wire_bytes(self, df, di) -> int:
        """Wire bytes of the separate-scatter path: each dirty set is
        padded to a power of two (padded chunks repeat real content but
        still cross the wire)."""
        c = self.chunk
        nf = _pow2_bucket(df.size) if df.size else 0
        ni = _pow2_bucket(di.size) if di.size else 0
        return (nf + ni) * c * 4 + (nf + ni) * 4

    @staticmethod
    def _apply(dev, idx, host2d):
        if idx.size == 0:
            return dev
        k = _pow2_bucket(idx.size)
        # pad with repeats of the first dirty chunk: duplicate scatter
        # indices write the same value, so the pad is a no-op
        pad = np.full(k, idx[0], np.int32)
        pad[:idx.size] = idx.astype(np.int32)
        return _scatter(dev, pad, host2d[pad])

    # ------------------------------------------------------------------
    # fused-dispatch flow: plan the delta, let the SOLVE jit apply it
    # (ops.solver.solve_allocate_delta), then commit the returned buffers
    # ------------------------------------------------------------------

    #: fixed delta-slot count for the fused dispatch: the chunk-index
    #: shape is part of the fused solve's jit signature, so EVERY distinct
    #: size would compile another full-solve executable (~tens of seconds
    #: each on TPU). One fixed size = exactly one fused variant; sessions
    #: dirtying more chunks fall back to the separate-scatter path (still
    #: zero new solve compiles — packed2d is its own single variant).
    FUSED_SLOTS = 16

    def plan_delta(self, fbuf: np.ndarray, ibuf: np.ndarray, layout):
        """Diff against the host mirror WITHOUT dispatching the solve.

        Returns (kind, payload):
        - ("fused", (f2d, i2d, f_idx, f_vals, i_idx, i_vals)) — at most
          FUSED_SLOTS dirty chunks: feed solve_allocate_delta, which
          scatters inside the solve dispatch; the caller must commit()
          the returned (donated) buffers, and on a dispatch failure call
          invalidate() so the next session re-ships the chunked buffers
          in full (reset() only if the host mirror itself is suspect).
        - ("updated", (f2d, i2d)) — more dirty chunks than FUSED_SLOTS:
          the scatters were applied here (reusing the diff already
          computed), feed the non-fused solve_allocate_packed2d.

        On the first call (or a layout change) the full buffers are
        device_put and a no-op fused delta (chunk 0 rewritten with
        identical bytes) is returned, so the caller has one code path.
        """
        c = self.chunk
        cf = -(-max(fbuf.size, 1) // c)
        ci = -(-max(ibuf.size, 1) // c)
        k = self.FUSED_SLOTS
        if self._needs_full_ship(layout, cf, ci):
            self._full_ship(fbuf, ibuf, layout, cf, ci)
            zero = np.zeros(k, np.int32)
            return "fused", (
                self._dev_f, self._dev_i,
                zero, np.broadcast_to(
                    self._host_f.reshape(cf, c)[0], (k, c)).copy(),
                zero, np.broadcast_to(
                    self._host_i.reshape(ci, c)[0], (k, c)).copy())

        f2, i2, df, di = self._diff(fbuf, ibuf, cf, ci)
        if df.size == 0 and di.size == 0:
            # unchanged snapshot: solve straight off the resident buffers
            # (non-donating packed2d) — zero wire bytes instead of a
            # no-op fused payload of FUSED_SLOTS chunks
            self._scratch_f, self._scratch_i = f2, i2
            self._account(0, 0, full=False)
            return "updated", (self._dev_f, self._dev_i)
        if int(df.size) > k or int(di.size) > k:
            # too many dirty chunks for the fused variant: apply the
            # scatters now (reusing this diff) and let the caller run the
            # non-fused solve
            try:
                new_f = self._apply(self._dev_f, df, f2.reshape(cf, c))
                new_i = self._apply(self._dev_i, di, i2.reshape(ci, c))
            except Exception:
                self.reset()
                raise
            self._dev_f, self._dev_i = new_f, new_i
            self._commit_mirror(f2, i2)
            self._account(df.size + di.size,
                          self._scatter_wire_bytes(df, di), full=False)
            return "updated", (self._dev_f, self._dev_i)
        f_idx = self._pad_idx(df, k)
        i_idx = self._pad_idx(di, k)
        fv = f2.reshape(cf, c)[f_idx]
        iv = i2.reshape(ci, c)[i_idx]
        self._commit_mirror(f2, i2)
        # fused wire cost: both value blocks always ship k chunks (the
        # fixed jit signature), plus the two index vectors
        self._account(df.size + di.size,
                      fv.nbytes + iv.nbytes + f_idx.nbytes + i_idx.nbytes,
                      full=False)
        return "fused", (self._dev_f, self._dev_i, f_idx, fv, i_idx, iv)

    @staticmethod
    def _pad_idx(idx: np.ndarray, k: int) -> np.ndarray:
        """Chunk indices padded to k (duplicates write identical values so
        the pad is a no-op scatter)."""
        pad = np.full(k, idx[0] if idx.size else 0, np.int32)
        pad[:idx.size] = idx.astype(np.int32)
        return pad

    def commit(self, f2d, i2d) -> None:
        """Store the buffers returned by solve_allocate_delta (the inputs
        were donated and are now invalid)."""
        self._dev_f, self._dev_i = f2d, i2d

    # ------------------------------------------------------------------
    # device-resident score params: the per-session params dict is a few
    # small arrays ([N] node_static dominates, ~8 KB at 2k nodes) that
    # almost never change between cycles — re-uploading them every
    # dispatch wastes tunnel bandwidth on the critical path. Cache the
    # device copies and re-put only when the content bytes change, when a
    # suspect flag (collect failure) finds a device copy actually dead,
    # or after a hard reset.
    # ------------------------------------------------------------------

    @staticmethod
    def _params_alive(dev_params: Optional[dict]) -> bool:
        """Whether every pinned device array still holds live buffers.
        Donation never touches these, so after a collect failure they are
        normally intact; an actual device restart deletes them."""
        if not dev_params:
            return False
        try:
            for v in dev_params.values():
                is_deleted = getattr(v, "is_deleted", None)
                if is_deleted is not None and is_deleted():
                    return False
        except Exception:  # noqa: BLE001 — treat any doubt as dead
            return False
        return True

    def params_device(self, params: dict) -> dict:
        import jax

        def _ent(k, v):
            # delimited key + dtype + shape + content: without these two
            # distinct params dicts whose concatenated bytes happen to
            # line up (or whose arrays share bytes but not shape/dtype)
            # could collide and serve stale device params
            a = np.asarray(v)
            return b"\0".join((k.encode(), str(a.dtype).encode(),
                               repr(a.shape).encode(), a.tobytes())) + b"\1"

        blob = b"".join(_ent(k, v) for k, v in sorted(params.items()))
        if blob == self._params_blob:
            if not self._params_suspect:
                return self._params_dev
            # re-validate the pinned copies after a collect failure:
            # content unchanged AND buffers alive -> keep them resident
            if self._params_alive(self._params_dev):
                self._params_suspect = False
                return self._params_dev
        self._params_dev = {k: jax.device_put(np.asarray(v))
                            for k, v in params.items()}
        self._params_blob = blob
        self._params_suspect = False
        self.params_repins += 1
        return self._params_dev
