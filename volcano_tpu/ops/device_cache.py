"""Device-resident packed solver arena with chunked delta upload.

The tunnel to a remote TPU is latency- and bandwidth-expensive: re-shipping
the full packed snapshot (~0.5 MB at 10k tasks / 2k nodes) every session
costs ~100 ms, while the cluster typically changes a few rows per cycle.
This cache keeps the two packed buffers (ops.arrays.SnapshotArrays.packed)
resident on device ACROSS scheduling sessions and ships only the chunks
whose bytes changed since the previous session, applied with a donated
in-place scatter — the TPU-native analog of the reference's informer
deltas (client-go list-watch keeps the scheduler's mirror warm instead of
re-listing the cluster, pkg/scheduler/cache/cache.go:319-402).

Arena contract (what survives what):

- **Chunked packed buffers** (``_dev_f``/``_dev_i``): device-resident
  across sessions; donated into the fused solve each dispatch. Lost on
  ``invalidate()``/``reset()`` — a donated dispatch that failed at
  readback has already consumed them.
- **Score params** (``params_device``): device-resident across sessions,
  NEVER donated — they survive a collect failure and are re-validated
  (not re-uploaded) on the next session via ``invalidate()``'s suspect
  flag. Only content changes or actual device-side deletion re-pin them.
- **Host mirror** (``_host_f``/``_host_i``): host memory; survives
  ``invalidate()`` untouched (it is rebuilt by the full re-ship anyway)
  and exists so per-session diffs are chunk-exact.

Accounting (``last_shipped_bytes``, ``arena_hit_rate`` …) feeds the
``volcano_arena_*`` metrics, ``Scheduler.last_cycle_timing`` and the
bench's bytes-shipped-per-session artifact fields.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def _pow2_bucket(n: int) -> int:
    """Strict powers of two, deliberately NOT ops.arrays.bucket (whose
    quarter-steps minimize padding): dirty-chunk counts vary every session,
    so the scatter kernel wants the fewest possible compiled variants."""
    b = 1
    while b < n:
        b <<= 1
    return b


_APPLY = None  # lazily created singleton so the jit caches across sessions
_APPLY_KEEP = None  # non-donating variant (sharded arena: buffers alias)


def _scatter(dev, idx, vals):
    global _APPLY
    if _APPLY is None:
        import jax
        _APPLY = jax.jit(lambda d, i, v: d.at[i].set(v), donate_argnums=(0,))
    return _APPLY(dev, idx, vals)


def _scatter_keep(dev, idx, vals):
    """Non-donating chunk scatter: the sharded arena's per-device shard
    buffers are aliased by the previously assembled global array (an
    in-flight pipelined solve may still read it), so donation would
    poison a live session's inputs."""
    global _APPLY_KEEP
    if _APPLY_KEEP is None:
        import jax
        _APPLY_KEEP = jax.jit(lambda d, i, v: d.at[i].set(v))
    return _APPLY_KEEP(dev, idx, vals)


class PackedDeviceCache:
    """update(fbuf, ibuf, layout) -> (f2d, i2d) device arrays [C, chunk].

    First call (or any layout/shape change) ships everything; later calls
    diff against the previously shipped host copy chunk-wise and scatter
    only dirty chunks. Chunk-index uploads are bucketed to powers of two so
    the scatter kernel compiles a handful of times, not per session.
    """

    def __init__(self, chunk: int = 512):
        self.chunk = chunk
        self._host_f: Optional[np.ndarray] = None  # padded copy, [Cf*chunk]
        self._host_i: Optional[np.ndarray] = None
        self._dev_f = None                         # [Cf, chunk] on device
        self._dev_i = None
        self._layout = None
        self._params_blob = None
        self._params_dev = None
        #: device buffers untrusted (collect failure after a donated
        #: dispatch): next session full-ships and re-validates params
        self._params_suspect = False
        # previous mirror buffers recycled as diff scratch (the diff
        # allocated two full-buffer copies per session before)
        self._scratch_f: Optional[np.ndarray] = None
        self._scratch_i: Optional[np.ndarray] = None
        # -- arena accounting (diagnostics + volcano_arena_* metrics) ----
        self.last_shipped_chunks = 0
        self.last_shipped_bytes = 0     # wire bytes of the last delta/ship
        self.last_full_ship = False
        self.sessions = 0               # update/plan_delta calls
        self.full_ships = 0             # sessions that re-shipped everything
        self.delta_sessions = 0         # sessions that shipped a delta
        self.invalidations = 0          # soft resets (collect failures)
        self.params_repins = 0          # device params re-uploaded
        self.total_shipped_bytes = 0

    # -- arena introspection -------------------------------------------

    @property
    def arena_hit_rate(self) -> float:
        """Fraction of sessions served by a delta against the resident
        arena (1.0 = never re-shipped after the first session)."""
        if not self.sessions:
            return 0.0
        return self.delta_sessions / self.sessions

    def full_upload_bytes(self) -> int:
        """Wire cost of one full padded-buffer upload at the current
        layout (the denominator of the <10%-of-full acceptance check)."""
        if self._host_f is None or self._host_i is None:
            return 0
        return int(self._host_f.nbytes + self._host_i.nbytes)

    def reset(self) -> None:
        """Hard reset: drop the mirror, the device-resident state AND the
        pinned params so the next session rebuilds everything. Used when
        the HOST-side mirror itself may have desynced from the device (a
        partial scatter failure mid-apply) — after that, nothing this
        object remembers can be trusted."""
        self._host_f = self._host_i = None
        self._dev_f = self._dev_i = None
        self._layout = None
        self._params_blob = None
        self._params_dev = None
        self._params_suspect = False

    def invalidate(self) -> None:
        """Soft reset after an async-collect failure: by the time the
        error surfaced, a donated dispatch had already consumed the
        chunked buffers, so they are gone — but the score params were
        NEVER donated and usually survive, and the host mirror is host
        memory. Drop exactly what the donation poisoned: the next session
        full-ships the chunked buffers (one expensive upload, not a
        permanent cold path) and re-validates the pinned params in place
        instead of re-uploading them."""
        self._dev_f = self._dev_i = None
        self._layout = None  # forces the full re-ship
        self._params_suspect = True
        self.invalidations += 1

    # -- shared mirror maintenance (update + plan_delta flows) ----------

    def _full_ship(self, fbuf, ibuf, layout, cf: int, ci: int):
        """(Re)establish the host mirror and device buffers wholesale."""
        import jax

        c = self.chunk
        hf = np.zeros(cf * c, np.float32)
        hf[:fbuf.size] = fbuf
        hi = np.zeros(ci * c, np.int32)
        hi[:ibuf.size] = ibuf
        self._host_f, self._host_i = hf, hi
        self._dev_f = jax.device_put(hf.reshape(cf, c))
        self._dev_i = jax.device_put(hi.reshape(ci, c))
        self._layout = layout
        self.last_shipped_chunks = cf + ci
        self._account(cf + ci, hf.nbytes + hi.nbytes, full=True)

    def _account(self, chunks: int, wire_bytes: int, full: bool) -> None:
        self.sessions += 1
        self.last_shipped_chunks = int(chunks)
        self.last_shipped_bytes = int(wire_bytes)
        self.last_full_ship = bool(full)
        self.total_shipped_bytes += int(wire_bytes)
        if full:
            self.full_ships += 1
        else:
            self.delta_sessions += 1

    def _needs_full_ship(self, layout, cf: int, ci: int) -> bool:
        c = self.chunk
        return (self._layout != layout or self._host_f is None
                or self._host_f.size != cf * c
                or self._host_i.size != ci * c)

    def _diff(self, fbuf, ibuf, cf: int, ci: int):
        """Pad new content into mirror-shaped buffers and locate dirty
        chunks: (f2, i2, df, di). Does NOT update the mirror (see
        _commit_mirror). The padded buffers come from the scratch pool —
        the previous session's mirror, recycled — so a steady session
        allocates no full-size arrays."""
        c = self.chunk
        f2, i2 = self._scratch_f, self._scratch_i
        if f2 is None or f2.size != cf * c:
            f2 = np.zeros(cf * c, np.float32)
        else:
            f2[fbuf.size:] = 0.0
        if i2 is None or i2.size != ci * c:
            i2 = np.zeros(ci * c, np.int32)
        else:
            i2[ibuf.size:] = 0
        self._scratch_f = self._scratch_i = None
        f2[:fbuf.size] = fbuf
        i2[:ibuf.size] = ibuf
        df = np.nonzero((f2.reshape(cf, c)
                         != self._host_f.reshape(cf, c)).any(axis=1))[0]
        di = np.nonzero((i2.reshape(ci, c)
                         != self._host_i.reshape(ci, c)).any(axis=1))[0]
        return f2, i2, df, di

    def _commit_mirror(self, f2, i2) -> None:
        """Adopt the diffed buffers as the new mirror; the old mirror
        becomes next session's diff scratch."""
        self._scratch_f, self._scratch_i = self._host_f, self._host_i
        self._host_f, self._host_i = f2, i2

    def update(self, fbuf: np.ndarray, ibuf: np.ndarray,
               layout) -> Tuple[object, object]:
        c = self.chunk
        cf = -(-max(fbuf.size, 1) // c)
        ci = -(-max(ibuf.size, 1) // c)
        if self._needs_full_ship(layout, cf, ci):
            self._full_ship(fbuf, ibuf, layout, cf, ci)
            return self._dev_f, self._dev_i

        f2, i2, df, di = self._diff(fbuf, ibuf, cf, ci)
        try:
            new_f = self._apply(self._dev_f, df, f2.reshape(cf, c))
            new_i = self._apply(self._dev_i, di, i2.reshape(ci, c))
        except Exception:
            # a partial scatter (or a donated-buffer loss) would desync the
            # device copy from the host mirror: drop everything so the next
            # session re-ships in full instead of solving on stale data
            self.reset()
            raise
        self._dev_f, self._dev_i = new_f, new_i
        self._commit_mirror(f2, i2)
        self._account(df.size + di.size,
                      self._scatter_wire_bytes(df, di), full=False)
        return self._dev_f, self._dev_i

    def _scatter_wire_bytes(self, df, di) -> int:
        """Wire bytes of the separate-scatter path: each dirty set is
        padded to a power of two (padded chunks repeat real content but
        still cross the wire)."""
        c = self.chunk
        nf = _pow2_bucket(df.size) if df.size else 0
        ni = _pow2_bucket(di.size) if di.size else 0
        return (nf + ni) * c * 4 + (nf + ni) * 4

    @staticmethod
    def _apply(dev, idx, host2d):
        if idx.size == 0:
            return dev
        k = _pow2_bucket(idx.size)
        # pad with repeats of the first dirty chunk: duplicate scatter
        # indices write the same value, so the pad is a no-op
        pad = np.full(k, idx[0], np.int32)
        pad[:idx.size] = idx.astype(np.int32)
        return _scatter(dev, pad, host2d[pad])

    # ------------------------------------------------------------------
    # fused-dispatch flow: plan the delta, let the SOLVE jit apply it
    # (ops.solver.solve_allocate_delta), then commit the returned buffers
    # ------------------------------------------------------------------

    #: fixed delta-slot count for the fused dispatch: the chunk-index
    #: shape is part of the fused solve's jit signature, so EVERY distinct
    #: size would compile another full-solve executable (~tens of seconds
    #: each on TPU). One fixed size = exactly one fused variant; sessions
    #: dirtying more chunks fall back to the separate-scatter path (still
    #: zero new solve compiles — packed2d is its own single variant).
    FUSED_SLOTS = 16

    def plan_delta(self, fbuf: np.ndarray, ibuf: np.ndarray, layout):
        """Diff against the host mirror WITHOUT dispatching the solve.

        Returns (kind, payload):
        - ("fused", (f2d, i2d, f_idx, f_vals, i_idx, i_vals)) — at most
          FUSED_SLOTS dirty chunks: feed solve_allocate_delta, which
          scatters inside the solve dispatch; the caller must commit()
          the returned (donated) buffers, and on a dispatch failure call
          invalidate() so the next session re-ships the chunked buffers
          in full (reset() only if the host mirror itself is suspect).
        - ("updated", (f2d, i2d)) — more dirty chunks than FUSED_SLOTS:
          the scatters were applied here (reusing the diff already
          computed), feed the non-fused solve_allocate_packed2d.

        On the first call (or a layout change) the full buffers are
        device_put and a no-op fused delta (chunk 0 rewritten with
        identical bytes) is returned, so the caller has one code path.
        """
        c = self.chunk
        cf = -(-max(fbuf.size, 1) // c)
        ci = -(-max(ibuf.size, 1) // c)
        k = self.FUSED_SLOTS
        if self._needs_full_ship(layout, cf, ci):
            self._full_ship(fbuf, ibuf, layout, cf, ci)
            zero = np.zeros(k, np.int32)
            return "fused", (
                self._dev_f, self._dev_i,
                zero, np.broadcast_to(
                    self._host_f.reshape(cf, c)[0], (k, c)).copy(),
                zero, np.broadcast_to(
                    self._host_i.reshape(ci, c)[0], (k, c)).copy())

        f2, i2, df, di = self._diff(fbuf, ibuf, cf, ci)
        if df.size == 0 and di.size == 0:
            # unchanged snapshot: solve straight off the resident buffers
            # (non-donating packed2d) — zero wire bytes instead of a
            # no-op fused payload of FUSED_SLOTS chunks
            self._scratch_f, self._scratch_i = f2, i2
            self._account(0, 0, full=False)
            return "updated", (self._dev_f, self._dev_i)
        if int(df.size) > k or int(di.size) > k:
            # too many dirty chunks for the fused variant: apply the
            # scatters now (reusing this diff) and let the caller run the
            # non-fused solve
            try:
                new_f = self._apply(self._dev_f, df, f2.reshape(cf, c))
                new_i = self._apply(self._dev_i, di, i2.reshape(ci, c))
            except Exception:
                self.reset()
                raise
            self._dev_f, self._dev_i = new_f, new_i
            self._commit_mirror(f2, i2)
            self._account(df.size + di.size,
                          self._scatter_wire_bytes(df, di), full=False)
            return "updated", (self._dev_f, self._dev_i)
        f_idx = self._pad_idx(df, k)
        i_idx = self._pad_idx(di, k)
        fv = f2.reshape(cf, c)[f_idx]
        iv = i2.reshape(ci, c)[i_idx]
        self._commit_mirror(f2, i2)
        # fused wire cost: both value blocks always ship k chunks (the
        # fixed jit signature), plus the two index vectors
        self._account(df.size + di.size,
                      fv.nbytes + iv.nbytes + f_idx.nbytes + i_idx.nbytes,
                      full=False)
        return "fused", (self._dev_f, self._dev_i, f_idx, fv, i_idx, iv)

    @staticmethod
    def _pad_idx(idx: np.ndarray, k: int) -> np.ndarray:
        """Chunk indices padded to k (duplicates write identical values so
        the pad is a no-op scatter)."""
        pad = np.full(k, idx[0] if idx.size else 0, np.int32)
        pad[:idx.size] = idx.astype(np.int32)
        return pad

    def commit(self, f2d, i2d) -> None:
        """Store the buffers returned by solve_allocate_delta (the inputs
        were donated and are now invalid)."""
        self._dev_f, self._dev_i = f2d, i2d

    # ------------------------------------------------------------------
    # device-resident score params: the per-session params dict is a few
    # small arrays ([N] node_static dominates, ~8 KB at 2k nodes) that
    # almost never change between cycles — re-uploading them every
    # dispatch wastes tunnel bandwidth on the critical path. Cache the
    # device copies and re-put only when the content bytes change, when a
    # suspect flag (collect failure) finds a device copy actually dead,
    # or after a hard reset.
    # ------------------------------------------------------------------

    @staticmethod
    def _params_alive(dev_params: Optional[dict]) -> bool:
        """Whether every pinned device array still holds live buffers.
        Donation never touches these, so after a collect failure they are
        normally intact; an actual device restart deletes them."""
        if not dev_params:
            return False
        try:
            for v in dev_params.values():
                is_deleted = getattr(v, "is_deleted", None)
                if is_deleted is not None and is_deleted():
                    return False
        except Exception:  # noqa: BLE001 — treat any doubt as dead
            return False
        return True

    def _put_params(self, params: dict) -> dict:
        """Device placement for the pinned score params; the sharded
        arena subclass overrides this to shard node_static along the
        mesh and replicate the scalars."""
        import jax

        return {k: jax.device_put(np.asarray(v)) for k, v in params.items()}

    def params_device(self, params: dict) -> dict:
        def _ent(k, v):
            # delimited key + dtype + shape + content: without these two
            # distinct params dicts whose concatenated bytes happen to
            # line up (or whose arrays share bytes but not shape/dtype)
            # could collide and serve stale device params
            a = np.asarray(v)
            return b"\0".join((k.encode(), str(a.dtype).encode(),
                               repr(a.shape).encode(), a.tobytes())) + b"\1"

        blob = b"".join(_ent(k, v) for k, v in sorted(params.items()))
        if blob == self._params_blob:
            if not self._params_suspect:
                return self._params_dev
            # re-validate the pinned copies after a collect failure:
            # content unchanged AND buffers alive -> keep them resident
            if self._params_alive(self._params_dev):
                self._params_suspect = False
                return self._params_dev
        self._params_dev = self._put_params(params)
        self._params_blob = blob
        self._params_suspect = False
        self.params_repins += 1
        return self._params_dev


# ---------------------------------------------------------------------------
# node-axis-sharded arena: the D>1 steady-state analog of the cache above
# ---------------------------------------------------------------------------

#: packed keys whose LEADING axis is the node axis — sharded along the
#: mesh 'n' axis by the sharded arena (parallel.sharded_solver in_specs
#: use P("n", ...) for exactly these)
NODE_AXIS_KEYS = frozenset({
    "node_idle", "node_extra_future", "node_used", "node_alloc",
    "node_npods", "node_max_pods", "node_valid",
})

#: node axis SECOND: [S, N] predicate-signature masks are stored per
#: shard as [S, N/D] and transposed back on device (P(None, "n"))
NODE_COL_KEYS = frozenset({"sig_masks"})


def split_packed_layout(layout, n_shards: int):
    """Split a ``SnapshotArrays.packed()`` layout into the replicated part
    (task/job/queue/misc arrays, placed once per device) and the per-shard
    node part (node-axis arrays, one slice of N/n_shards rows per mesh
    device). Offsets are re-accumulated per part, so each part is its own
    dense flat buffer; per-shard shapes replace the node axis with
    N/n_shards. Returns ``(rep_layout, node_layout)`` — both in the same
    sorted-key order as the input, so byte layouts are deterministic.

    Pure layout arithmetic (no arrays touched): the bucket prewarmer uses
    it to predict the sharded arena's next-bucket jit signatures exactly
    like predict_next_layout does for the packed path.
    """
    rep, node = [], []
    rf = ri = nf = ni = 0
    for key, kind, _off, _size, shape in layout:
        if key in NODE_AXIS_KEYS:
            n = shape[0]
            if n % n_shards:
                raise ValueError(
                    f"node axis {n} does not divide {n_shards} shards")
            pshape = (n // n_shards,) + tuple(shape[1:])
        elif key in NODE_COL_KEYS:
            n = shape[1]
            if n % n_shards:
                raise ValueError(
                    f"node axis {n} does not divide {n_shards} shards")
            pshape = (shape[0], n // n_shards)
        else:
            size = 1
            for s in shape:
                size *= s
            if kind == "f":
                rep.append((key, kind, rf, size, shape))
                rf += size
            else:
                rep.append((key, kind, ri, size, shape))
                ri += size
            continue
        size = 1
        for s in pshape:
            size *= s
        if kind == "f":
            node.append((key, kind, nf, size, pshape))
            nf += size
        else:
            node.append((key, kind, ni, size, pshape))
            ni += size
    return tuple(rep), tuple(node)


def _part_sizes(part_layout) -> Tuple[int, int]:
    """(flat f32 length, flat i32 length) of one split-layout part."""
    nf = max((off + size for _k, kind, off, size, _s in part_layout
              if kind == "f"), default=0)
    ni = max((off + size for _k, kind, off, size, _s in part_layout
              if kind != "f"), default=0)
    return nf, ni


class ShardedDeviceCache(PackedDeviceCache):
    """The device-resident arena for D>1 sharded solves.

    Same contract as PackedDeviceCache — host mirror diffs, dirty-chunk
    deltas, pinned score params, soft ``invalidate()`` — but the resident
    state is laid out for the node-axis ``shard_map`` solver
    (``parallel.solve_allocate_sharded_arena``):

    - **node-axis arrays** live as one chunked buffer pair PER MESH
      DEVICE (committed single-device arrays assembled zero-copy into a
      global ``[D, C, chunk]`` array with ``NamedSharding(mesh, P("n"))``
      at dispatch time). A dirty node row ships only to the shard that
      owns it — the per-device scatter executes on that device alone;
    - **task/job/queue arrays** live as one replicated chunked buffer
      pair (``NamedSharding(mesh, P())``), delta-updated in place: the
      host ships each dirty chunk once and the runtime fans it out;
    - **score params** are pinned with the solver's shardings
      (node_static split along 'n', scalars replicated), re-validated in
      place after a collect failure exactly like the packed arena.

    ``update(fbuf, ibuf, layout)`` -> ``(f_rep, i_rep, f_node, i_node,
    rep_layout, node_layout)``: the six dispatch inputs of
    ``solve_allocate_sharded_arena``. Accounting adds ``last_shard_bytes``
    (wire bytes per shard for the last session) on top of the inherited
    ``volcano_arena_*`` counters; a zero-dirty session returns the
    resident arrays and ships 0 bytes to every shard.
    """

    def __init__(self, mesh, chunk: int = 512):
        super().__init__(chunk)
        self.mesh = mesh
        self.D = int(mesh.devices.size)
        self._rep_layout = None
        self._node_layout = None
        # host mirrors: rep flat [Crf*c]/[Cri*c]; node [D, Cnf*c]/[D, Cni*c]
        self._host_rep_f = self._host_rep_i = None
        self._host_node_f = self._host_node_i = None
        # device state: rep = global replicated arrays; node = per-device
        # committed [1, Cn, chunk] arrays (assembled on demand)
        self._dev_rep_f = self._dev_rep_i = None
        self._dev_node_f = self._dev_node_i = None
        #: wire bytes shipped to each shard by the last session (node
        #: slices + this shard's copy of the replicated delta)
        self.last_shard_bytes = [0] * self.D

    # -- placement helpers ---------------------------------------------

    def _sharding(self, along_n: bool):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(self.mesh, P("n") if along_n else P()), jax

    def _put_params(self, params: dict) -> dict:
        ns_n, jax = self._sharding(True)
        ns_rep, _ = self._sharding(False)
        return {k: jax.device_put(
                    np.asarray(v), ns_n if k == "node_static" else ns_rep)
                for k, v in params.items()}

    # -- lifecycle ------------------------------------------------------

    def reset(self) -> None:
        super().reset()
        self._rep_layout = self._node_layout = None
        self._host_rep_f = self._host_rep_i = None
        self._host_node_f = self._host_node_i = None
        self._dev_rep_f = self._dev_rep_i = None
        self._dev_node_f = self._dev_node_i = None

    def invalidate(self) -> None:
        """Soft reset after a failed sharded session: the sharded solve
        never donates, but a mesh-path failure leaves the device-side
        state untrusted (a shard's scatter may have landed while another
        shard's was lost) — drop the resident buffers, full-ship next
        session, and re-validate the pinned params in place."""
        super().invalidate()
        self._rep_layout = self._node_layout = None
        self._dev_rep_f = self._dev_rep_i = None
        self._dev_node_f = self._dev_node_i = None

    def full_upload_bytes(self) -> int:
        if self._host_rep_f is None or self._host_node_f is None:
            return 0
        return int(self._host_rep_f.nbytes + self._host_rep_i.nbytes
                   + self._host_node_f.nbytes + self._host_node_i.nbytes)

    # -- host-side packing ---------------------------------------------

    def _pack_split(self, fbuf, ibuf, layout, rep_layout, node_layout,
                    out_rep_f, out_rep_i, out_node_f, out_node_i) -> None:
        """Scatter the global packed buffers into the split mirrors:
        replicated keys copy through; node keys slice one row-block (or
        sig_masks column-block) per shard."""
        goff = {k: (off, size, shape) for k, off, size, shape in
                ((k, off, size, shape)
                 for k, _kind, off, size, shape in layout)}
        D = self.D
        for key, kind, off, size, shape in rep_layout:
            g_off, g_size, _ = goff[key]
            src = fbuf if kind == "f" else ibuf
            dst = out_rep_f if kind == "f" else out_rep_i
            dst[off:off + size] = src[g_off:g_off + g_size]
        for key, kind, off, size, pshape in node_layout:
            g_off, g_size, g_shape = goff[key]
            src = fbuf if kind == "f" else ibuf
            dst = out_node_f if kind == "f" else out_node_i
            g = src[g_off:g_off + g_size].reshape(g_shape)
            if key in NODE_COL_KEYS:
                nl = pshape[1]
                for d in range(D):
                    dst[d, off:off + size] = \
                        g[:, d * nl:(d + 1) * nl].ravel()
            else:
                nl = pshape[0]
                for d in range(D):
                    dst[d, off:off + size] = \
                        g[d * nl:(d + 1) * nl].ravel()

    # -- the session entry ---------------------------------------------

    def update(self, fbuf: np.ndarray, ibuf: np.ndarray, layout):
        import jax

        c, D = self.chunk, self.D
        if self._layout != layout or self._rep_layout is None:
            rep_layout, node_layout = split_packed_layout(layout, D)
        else:
            rep_layout, node_layout = self._rep_layout, self._node_layout
        rf, ri = _part_sizes(rep_layout)
        nf, ni = _part_sizes(node_layout)
        crf = -(-max(rf, 1) // c)
        cri = -(-max(ri, 1) // c)
        cnf = -(-max(nf, 1) // c)
        cni = -(-max(ni, 1) // c)

        if (self._layout != layout or self._host_rep_f is None
                or self._host_rep_f.size != crf * c
                or self._host_node_f.shape != (D, cnf * c)):
            # full ship: (re)build mirrors and place every shard
            hrf = np.zeros(crf * c, np.float32)
            hri = np.zeros(cri * c, np.int32)
            hnf = np.zeros((D, cnf * c), np.float32)
            hni = np.zeros((D, cni * c), np.int32)
            self._pack_split(fbuf, ibuf, layout, rep_layout, node_layout,
                             hrf, hri, hnf, hni)
            self._host_rep_f, self._host_rep_i = hrf, hri
            self._host_node_f, self._host_node_i = hnf, hni
            ns_rep, _ = self._sharding(False)
            self._dev_rep_f = jax.device_put(hrf.reshape(crf, c), ns_rep)
            self._dev_rep_i = jax.device_put(hri.reshape(cri, c), ns_rep)
            devs = list(self.mesh.devices.flat)
            self._dev_node_f = [
                jax.device_put(hnf[d].reshape(1, cnf, c), devs[d])
                for d in range(D)]
            self._dev_node_i = [
                jax.device_put(hni[d].reshape(1, cni, c), devs[d])
                for d in range(D)]
            self._layout = layout
            self._rep_layout, self._node_layout = rep_layout, node_layout
            rep_bytes = hrf.nbytes + hri.nbytes
            self.last_shard_bytes = [
                int(hnf[d].nbytes + hni[d].nbytes + rep_bytes)
                for d in range(D)]
            self._account(crf + cri + D * (cnf + cni),
                          rep_bytes + hnf.nbytes + hni.nbytes, full=True)
            return self._assembled(rep_layout, node_layout)

        # delta path: diff the split mirrors chunk-wise
        srf = np.zeros(crf * c, np.float32)
        sri = np.zeros(cri * c, np.int32)
        snf = np.zeros((D, cnf * c), np.float32)
        sni = np.zeros((D, cni * c), np.int32)
        self._pack_split(fbuf, ibuf, layout, rep_layout, node_layout,
                         srf, sri, snf, sni)
        drf = np.nonzero((srf.reshape(crf, c)
                          != self._host_rep_f.reshape(crf, c))
                         .any(axis=1))[0]
        dri = np.nonzero((sri.reshape(cri, c)
                          != self._host_rep_i.reshape(cri, c))
                         .any(axis=1))[0]
        chunks = drf.size + dri.size
        rep_bytes = self._scatter_wire_bytes(drf, dri)
        if drf.size:
            self._dev_rep_f = self._apply_keep(
                self._dev_rep_f, drf, srf.reshape(crf, c))
        if dri.size:
            self._dev_rep_i = self._apply_keep(
                self._dev_rep_i, dri, sri.reshape(cri, c))
        shard_bytes = [0] * D
        for d in range(D):
            dnf = np.nonzero((snf[d].reshape(cnf, c)
                              != self._host_node_f[d].reshape(cnf, c))
                             .any(axis=1))[0]
            dni = np.nonzero((sni[d].reshape(cni, c)
                              != self._host_node_i[d].reshape(cni, c))
                             .any(axis=1))[0]
            if dnf.size:
                self._dev_node_f[d] = self._apply_keep(
                    self._dev_node_f[d], dnf, snf[d].reshape(cnf, c),
                    leading=True)
            if dni.size:
                self._dev_node_i[d] = self._apply_keep(
                    self._dev_node_i[d], dni, sni[d].reshape(cni, c),
                    leading=True)
            chunks += dnf.size + dni.size
            shard_bytes[d] = self._scatter_wire_bytes(dnf, dni)
        if chunks:
            self._host_rep_f, self._host_rep_i = srf, sri
            self._host_node_f, self._host_node_i = snf, sni
        self.last_shard_bytes = [
            int(b + (rep_bytes if chunks else 0)) for b in shard_bytes]
        self._account(chunks, rep_bytes + sum(shard_bytes), full=False)
        return self._assembled(rep_layout, node_layout)

    @staticmethod
    def _apply_keep(dev, idx, host2d, leading: bool = False):
        """Non-donating dirty-chunk scatter (see _scatter_keep); executes
        on the committed device of ``dev``, so a clean shard receives
        nothing. ``leading``: dev is a per-device [1, C, chunk] slab."""
        k = _pow2_bucket(idx.size)
        pad = np.full(k, idx[0], np.int32)
        pad[:idx.size] = idx.astype(np.int32)
        if leading:
            return _scatter_keep(dev[0], pad, host2d[pad])[None]
        return _scatter_keep(dev, pad, host2d[pad])

    def _assembled(self, rep_layout, node_layout):
        """Zero-copy global views over the resident shards: the node
        slabs become one [D, C, chunk] array sharded along 'n'."""
        import jax

        c, D = self.chunk, self.D
        ns_n, _ = self._sharding(True)
        cnf = self._dev_node_f[0].shape[1]
        cni = self._dev_node_i[0].shape[1]
        f_node = jax.make_array_from_single_device_arrays(
            (D, cnf, c), ns_n, self._dev_node_f)
        i_node = jax.make_array_from_single_device_arrays(
            (D, cni, c), ns_n, self._dev_node_i)
        return (self._dev_rep_f, self._dev_rep_i, f_node, i_node,
                rep_layout, node_layout)
