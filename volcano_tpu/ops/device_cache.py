"""Device-resident packed solver buffers with chunked delta upload.

The tunnel to a remote TPU is latency- and bandwidth-expensive: re-shipping
the full packed snapshot (~0.5 MB at 10k tasks / 2k nodes) every session
costs ~100 ms, while the cluster typically changes a few rows per cycle.
This cache keeps the two packed buffers (ops.arrays.SnapshotArrays.packed)
resident on device and ships only the chunks whose bytes changed since the
previous session, applied with a donated in-place scatter — the TPU-native
analog of the reference's informer deltas (client-go list-watch keeps the
scheduler's mirror warm instead of re-listing the cluster,
pkg/scheduler/cache/cache.go:319-402).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def _pow2_bucket(n: int) -> int:
    """Strict powers of two, deliberately NOT ops.arrays.bucket (whose
    quarter-steps minimize padding): dirty-chunk counts vary every session,
    so the scatter kernel wants the fewest possible compiled variants."""
    b = 1
    while b < n:
        b <<= 1
    return b


_APPLY = None  # lazily created singleton so the jit caches across sessions


def _scatter(dev, idx, vals):
    global _APPLY
    if _APPLY is None:
        import jax
        _APPLY = jax.jit(lambda d, i, v: d.at[i].set(v), donate_argnums=(0,))
    return _APPLY(dev, idx, vals)


class PackedDeviceCache:
    """update(fbuf, ibuf, layout) -> (f2d, i2d) device arrays [C, chunk].

    First call (or any layout/shape change) ships everything; later calls
    diff against the previously shipped host copy chunk-wise and scatter
    only dirty chunks. Chunk-index uploads are bucketed to powers of two so
    the scatter kernel compiles a handful of times, not per session.
    """

    def __init__(self, chunk: int = 512):
        self.chunk = chunk
        self._host_f: Optional[np.ndarray] = None  # padded copy, [Cf*chunk]
        self._host_i: Optional[np.ndarray] = None
        self._dev_f = None                         # [Cf, chunk] on device
        self._dev_i = None
        self._layout = None
        self.last_shipped_chunks = 0               # diagnostics

    def reset(self) -> None:
        """Drop the mirror AND the device-resident state so the next
        session re-ships everything. Called on any scatter/dispatch
        failure here, and by the allocate action's collect path when an
        async solve error surfaces at readback time — by then a donated
        dispatch has already commit()ed buffers that no longer hold valid
        data, so everything device-side (cached score params included: the
        same fault that killed the solve may have killed their backing
        buffers) must be treated as lost."""
        self._host_f = self._host_i = None
        self._dev_f = self._dev_i = None
        self._layout = None
        self._params_blob = None
        self._params_dev = None

    # -- shared mirror maintenance (update + plan_delta flows) ----------

    def _full_ship(self, fbuf, ibuf, layout, cf: int, ci: int):
        """(Re)establish the host mirror and device buffers wholesale."""
        import jax

        c = self.chunk
        hf = np.zeros(cf * c, np.float32)
        hf[:fbuf.size] = fbuf
        hi = np.zeros(ci * c, np.int32)
        hi[:ibuf.size] = ibuf
        self._host_f, self._host_i = hf, hi
        self._dev_f = jax.device_put(hf.reshape(cf, c))
        self._dev_i = jax.device_put(hi.reshape(ci, c))
        self._layout = layout
        self.last_shipped_chunks = cf + ci

    def _needs_full_ship(self, layout, cf: int, ci: int) -> bool:
        c = self.chunk
        return (self._layout != layout or self._host_f is None
                or self._host_f.size != cf * c
                or self._host_i.size != ci * c)

    def _diff(self, fbuf, ibuf, cf: int, ci: int):
        """Pad new content into mirror-shaped buffers and locate dirty
        chunks: (f2, i2, df, di). Does NOT update the mirror."""
        c = self.chunk
        f2 = np.zeros_like(self._host_f)
        f2[:fbuf.size] = fbuf
        i2 = np.zeros_like(self._host_i)
        i2[:ibuf.size] = ibuf
        df = np.nonzero((f2.reshape(cf, c)
                         != self._host_f.reshape(cf, c)).any(axis=1))[0]
        di = np.nonzero((i2.reshape(ci, c)
                         != self._host_i.reshape(ci, c)).any(axis=1))[0]
        self.last_shipped_chunks = int(df.size + di.size)
        return f2, i2, df, di

    def update(self, fbuf: np.ndarray, ibuf: np.ndarray,
               layout) -> Tuple[object, object]:
        c = self.chunk
        cf = -(-max(fbuf.size, 1) // c)
        ci = -(-max(ibuf.size, 1) // c)
        if self._needs_full_ship(layout, cf, ci):
            self._full_ship(fbuf, ibuf, layout, cf, ci)
            return self._dev_f, self._dev_i

        f2, i2, df, di = self._diff(fbuf, ibuf, cf, ci)
        try:
            new_f = self._apply(self._dev_f, df, f2.reshape(cf, c))
            new_i = self._apply(self._dev_i, di, i2.reshape(ci, c))
        except Exception:
            # a partial scatter (or a donated-buffer loss) would desync the
            # device copy from the host mirror: drop everything so the next
            # session re-ships in full instead of solving on stale data
            self.reset()
            raise
        self._dev_f, self._dev_i = new_f, new_i
        self._host_f, self._host_i = f2, i2
        return self._dev_f, self._dev_i

    @staticmethod
    def _apply(dev, idx, host2d):
        if idx.size == 0:
            return dev
        k = _pow2_bucket(idx.size)
        # pad with repeats of the first dirty chunk: duplicate scatter
        # indices write the same value, so the pad is a no-op
        pad = np.full(k, idx[0], np.int32)
        pad[:idx.size] = idx.astype(np.int32)
        return _scatter(dev, pad, host2d[pad])

    # ------------------------------------------------------------------
    # fused-dispatch flow: plan the delta, let the SOLVE jit apply it
    # (ops.solver.solve_allocate_delta), then commit the returned buffers
    # ------------------------------------------------------------------

    #: fixed delta-slot count for the fused dispatch: the chunk-index
    #: shape is part of the fused solve's jit signature, so EVERY distinct
    #: size would compile another full-solve executable (~tens of seconds
    #: each on TPU). One fixed size = exactly one fused variant; sessions
    #: dirtying more chunks fall back to the separate-scatter path (still
    #: zero new solve compiles — packed2d is its own single variant).
    FUSED_SLOTS = 16

    def plan_delta(self, fbuf: np.ndarray, ibuf: np.ndarray, layout):
        """Diff against the host mirror WITHOUT dispatching the solve.

        Returns (kind, payload):
        - ("fused", (f2d, i2d, f_idx, f_vals, i_idx, i_vals)) — at most
          FUSED_SLOTS dirty chunks: feed solve_allocate_delta, which
          scatters inside the solve dispatch; the caller must commit()
          the returned (donated) buffers, and on a dispatch failure call
          reset() so the next session re-ships in full.
        - ("updated", (f2d, i2d)) — more dirty chunks than FUSED_SLOTS:
          the scatters were applied here (reusing the diff already
          computed), feed the non-fused solve_allocate_packed2d.

        On the first call (or a layout change) the full buffers are
        device_put and a no-op fused delta (chunk 0 rewritten with
        identical bytes) is returned, so the caller has one code path.
        """
        c = self.chunk
        cf = -(-max(fbuf.size, 1) // c)
        ci = -(-max(ibuf.size, 1) // c)
        k = self.FUSED_SLOTS
        if self._needs_full_ship(layout, cf, ci):
            self._full_ship(fbuf, ibuf, layout, cf, ci)
            zero = np.zeros(k, np.int32)
            return "fused", (
                self._dev_f, self._dev_i,
                zero, np.broadcast_to(
                    self._host_f.reshape(cf, c)[0], (k, c)).copy(),
                zero, np.broadcast_to(
                    self._host_i.reshape(ci, c)[0], (k, c)).copy())

        f2, i2, df, di = self._diff(fbuf, ibuf, cf, ci)
        if int(df.size) > k or int(di.size) > k:
            # too many dirty chunks for the fused variant: apply the
            # scatters now (reusing this diff) and let the caller run the
            # non-fused solve
            try:
                new_f = self._apply(self._dev_f, df, f2.reshape(cf, c))
                new_i = self._apply(self._dev_i, di, i2.reshape(ci, c))
            except Exception:
                self.reset()
                raise
            self._dev_f, self._dev_i = new_f, new_i
            self._host_f, self._host_i = f2, i2
            return "updated", (self._dev_f, self._dev_i)
        f_idx = self._pad_idx(df, k)
        i_idx = self._pad_idx(di, k)
        self._host_f, self._host_i = f2, i2
        return "fused", (
            self._dev_f, self._dev_i,
            f_idx, f2.reshape(cf, c)[f_idx],
            i_idx, i2.reshape(ci, c)[i_idx])

    @staticmethod
    def _pad_idx(idx: np.ndarray, k: int) -> np.ndarray:
        """Chunk indices padded to k (duplicates write identical values so
        the pad is a no-op scatter)."""
        pad = np.full(k, idx[0] if idx.size else 0, np.int32)
        pad[:idx.size] = idx.astype(np.int32)
        return pad

    def commit(self, f2d, i2d) -> None:
        """Store the buffers returned by solve_allocate_delta (the inputs
        were donated and are now invalid)."""
        self._dev_f, self._dev_i = f2d, i2d

    # ------------------------------------------------------------------
    # device-resident score params: the per-session params dict is a few
    # small arrays ([N] node_static dominates, ~8 KB at 2k nodes) that
    # almost never change between cycles — re-uploading them every
    # dispatch wastes tunnel bandwidth on the critical path. Cache the
    # device copies and re-put only when the content bytes change.
    # ------------------------------------------------------------------

    def params_device(self, params: dict) -> dict:
        import jax

        def _ent(k, v):
            # delimited key + dtype + shape + content: without these two
            # distinct params dicts whose concatenated bytes happen to
            # line up (or whose arrays share bytes but not shape/dtype)
            # could collide and serve stale device params
            a = np.asarray(v)
            return b"\0".join((k.encode(), str(a.dtype).encode(),
                               repr(a.shape).encode(), a.tobytes())) + b"\1"

        blob = b"".join(_ent(k, v) for k, v in sorted(params.items()))
        if blob == getattr(self, "_params_blob", None):
            return self._params_dev
        self._params_dev = {k: jax.device_put(np.asarray(v))
                            for k, v in params.items()}
        self._params_blob = blob
        return self._params_dev
