"""JAX/TPU kernels: snapshot flattening, feasibility, scoring, solvers.

Solver imports are lazy (PEP 562) so the pure-Python control plane
(controllers, webhooks, CLI, cache) never pays jax/PJRT initialization —
jax loads on the first actual solve.
"""

from .arrays import (  # noqa: F401
    FlattenCache, ScoreParams, SnapshotArrays, bucket, flatten_snapshot,
)

_LAZY = ("SolveResult", "fits_matrix", "score_matrix", "solve_allocate",
         "solve_allocate_sequential", "solve_allocate_packed")

__all__ = ["FlattenCache", "ScoreParams", "SnapshotArrays", "bucket",
           "flatten_snapshot", *_LAZY]


def __getattr__(name):
    if name in _LAZY:
        from . import solver
        return getattr(solver, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
