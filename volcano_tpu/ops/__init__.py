"""JAX/TPU kernels: snapshot flattening, feasibility, scoring, solvers."""

from .arrays import ScoreParams, SnapshotArrays, bucket, flatten_snapshot  # noqa: F401
from .solver import (  # noqa: F401
    SolveResult, fits_matrix, score_matrix, solve_allocate,
    solve_allocate_sequential,
)
