"""JAX/TPU kernels: snapshot flattening, feasibility, scoring, solvers.

Solver imports are lazy (PEP 562) so the pure-Python control plane
(controllers, webhooks, CLI, cache) never pays jax/PJRT initialization —
jax loads on the first actual solve.
"""

from .arrays import (  # noqa: F401
    FlattenCache, ScoreParams, SnapshotArrays, bucket, flatten_snapshot,
)
from .ordering import OrderCache  # noqa: F401

_LAZY = ("SolveResult", "fits_matrix", "score_matrix", "solve_allocate",
         "solve_allocate_sequential", "solve_allocate_packed",
         "solve_allocate_packed2d")
_LAZY_EVICT = ("EvictResult", "solve_evict")
_LAZY_DEVCACHE = ("PackedDeviceCache", "ShardedDeviceCache",
                  "split_packed_layout")
# precompile itself only imports jax lazily (inside functions/threads), but
# routing it through the lazy hook keeps the import-cost contract uniform
_LAZY_PRECOMPILE = ("BucketPrewarmer", "CompileWatcher",
                    "configure_compilation_cache", "watcher")
_LAZY_PIPELINE = ("SessionPipeline", "SessionTicket", "start_readback")

__all__ = ["FlattenCache", "OrderCache", "ScoreParams", "SnapshotArrays",
           "bucket", "flatten_snapshot", *_LAZY, *_LAZY_EVICT,
           *_LAZY_DEVCACHE, *_LAZY_PRECOMPILE, *_LAZY_PIPELINE]


def __getattr__(name):
    if name in _LAZY:
        from . import solver
        return getattr(solver, name)
    if name in _LAZY_EVICT:
        from . import evict
        return getattr(evict, name)
    if name in _LAZY_DEVCACHE:
        from . import device_cache
        return getattr(device_cache, name)
    if name in _LAZY_PRECOMPILE:
        from . import precompile
        return getattr(precompile, name)
    if name in _LAZY_PIPELINE:
        from . import pipeline
        return getattr(pipeline, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
