"""Compile-and-dispatch pipeline layer: kill XLA compile stalls.

Three cooperating pieces keep every XLA compile off the scheduling
session thread:

- **Persistent compilation cache** (``configure_compilation_cache``):
  wires JAX's on-disk executable cache so a repeated bucket shape — or a
  process restart — deserializes a compiled executable (~100 ms) instead
  of re-paying the full XLA compile (~tens of seconds on TPU for the
  full-solve kernel).

- **CompileWatcher**: a ``jax.monitoring`` tap recording per-thread
  backend-compile counts/seconds and persistent-cache hits, feeding
  ``volcano_tpu.metrics``. The scheduler surfaces the deltas in
  ``last_cycle_timing`` so "a compile happened on the session thread"
  is an observable regression, not a mystery 10 s spike.

- **BucketPrewarmer**: the flatten pads to compile buckets
  (``ops.arrays.bucket`` quarter-steps), so the set of future jit
  signatures is *predictable*: when live task/node/job occupancy crosses
  a threshold of the current bucket, the next bucket's packed layout is
  synthesized host-side (``predict_next_layout`` — byte-exact layout
  arithmetic, no flatten needed) and the solver variants for it are
  traced + compiled on a daemon thread. jit caches are per-function and
  process-global, so the session thread's first post-crossing dispatch
  hits the already-populated cache.

The allocate action's dispatch/collect split (actions/allocate.py) rides
on the same module: JAX dispatch is async, so between dispatching the
solve and blocking on the compact readback the host runs replay
preparation, the prewarm occupancy check, and a young-generation GC —
work that previously serialized after the device finished.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Iterable, Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

#: env override consumed when no explicit dir is configured
CACHE_DIR_ENV = "VOLCANO_COMPILE_CACHE_DIR"

_configured_dir: Optional[str] = None


def configure_compilation_cache(cache_dir: Optional[str] = None,
                                min_compile_secs: float = 0.0) -> Optional[str]:
    """Enable JAX's persistent on-disk compilation cache.

    ``cache_dir`` falls back to $VOLCANO_COMPILE_CACHE_DIR; returns the
    directory in effect (None = left disabled). Idempotent — repeated
    calls with the same dir are no-ops; a different dir re-points the
    cache. Failures (ancient jax, read-only fs) log and disable rather
    than take down the scheduler: the cache is an optimization, not a
    correctness dependency.
    """
    global _configured_dir
    cache_dir = cache_dir or os.environ.get(CACHE_DIR_ENV) or None
    if not cache_dir:
        return _configured_dir
    if _configured_dir == cache_dir:
        return _configured_dir
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # default thresholds skip exactly the small recompiles a restart
        # re-pays; the solver variants this repo cares about all clear
        # them, but pinning to 0/-1 makes the cache deterministic in tests
        for knob, val in (
                ("jax_persistent_cache_min_compile_time_secs",
                 min_compile_secs),
                ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(knob, val)
            except Exception:  # noqa: BLE001 — knob absent on this jax
                pass
        try:
            # the cache backend latches on first use: a process that
            # compiled anything before this call (warmup, another
            # scheduler) must drop the initialized-with-no-dir instance
            # or the new dir silently never receives entries
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # noqa: BLE001 — private API drifted
            pass
        _configured_dir = cache_dir
    except Exception:  # noqa: BLE001
        log.exception("persistent compilation cache unavailable")
        return None
    return _configured_dir


# ---------------------------------------------------------------------------
# compile observability
# ---------------------------------------------------------------------------

class CompileWatcher:
    """Per-thread XLA compile accounting via ``jax.monitoring``.

    ``install()`` registers two listeners (idempotent): backend-compile
    durations keyed by ``threading.get_ident()`` and persistent-cache hit
    events. Threads registered through ``register_background`` (the
    prewarmer's workers) are labeled ``background`` in the exported
    metrics; everything else counts as ``session`` — exactly the split
    the <50 ms budget cares about.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._by_thread: Dict[int, list] = {}   # ident -> [count, seconds]
        self._background: set = set()
        self.cache_hits = 0
        self._installed = False

    # -- listener plumbing ------------------------------------------------

    def install(self) -> bool:
        with self._lock:
            if self._installed:
                return True
            try:
                import jax.monitoring as jm

                jm.register_event_duration_secs_listener(self._on_duration)
                jm.register_event_listener(self._on_event)
                self._installed = True
            except Exception:  # noqa: BLE001 — monitoring API drifted
                log.exception("jax.monitoring unavailable; compile "
                              "accounting falls back to jit cache sizes")
                return False
        return True

    def _on_duration(self, key: str, secs: float, **kw) -> None:
        try:
            if "backend_compile" not in key:
                return
            ident = threading.get_ident()
            with self._lock:
                ent = self._by_thread.setdefault(ident, [0, 0.0])
                ent[0] += 1
                ent[1] += secs
                label = ("background" if ident in self._background
                         else "session")
            from ..metrics import metrics

            metrics.solver_compile_total.inc(labels={"thread": label})
            metrics.solver_compile_seconds_total.inc(
                secs, labels={"thread": label})
        except Exception:  # noqa: BLE001 — never break jax's dispatch
            pass

    def _on_event(self, key: str, **kw) -> None:
        try:
            if not key.endswith("/cache_hits"):
                return
            with self._lock:
                self.cache_hits += 1
            from ..metrics import metrics

            metrics.compile_cache_hits_total.inc()
        except Exception:  # noqa: BLE001
            pass

    # -- accounting views -------------------------------------------------

    def register_background(self, ident: Optional[int] = None) -> None:
        with self._lock:
            self._background.add(
                threading.get_ident() if ident is None else ident)

    def counts(self, ident: Optional[int] = None) -> Tuple[int, float]:
        """(compiles, seconds) observed on one thread (default: caller's)."""
        ident = threading.get_ident() if ident is None else ident
        with self._lock:
            ent = self._by_thread.get(ident, (0, 0.0))
            return int(ent[0]), float(ent[1])

    def session_totals(self) -> Tuple[int, float]:
        """(compiles, seconds) summed over all non-background threads."""
        with self._lock:
            c, s = 0, 0.0
            for ident, (n, secs) in self._by_thread.items():
                if ident not in self._background:
                    c += n
                    s += secs
            return c, s


#: process-wide watcher; ``install()`` is called by the scheduler wiring,
#: the prewarmer, and the bench — whoever gets there first
watcher = CompileWatcher()


def solver_cache_size() -> int:
    """Total compiled-variant count across the solver jit entry points —
    the fallback compile detector when jax.monitoring is unavailable, and
    the exact "new full-solve variant" counter for the bench (monitoring
    counts every jit, including trivial ops)."""
    from . import solver as _s

    fns = [_s.solve_allocate, _s.solve_allocate_sequential,
           _s.solve_allocate_packed, _s.solve_allocate_packed2d,
           _s.solve_allocate_delta]
    try:
        # the sharded entry counts too: sharded-mode sessions dispatch it
        # and its compiles are exactly as much a session-thread stall
        from ..parallel import sharded_solver as _ss
        fns.append(_ss.solve_allocate_sharded_packed2d)
        fns.append(_ss.solve_allocate_sharded_arena)
    except Exception:  # noqa: BLE001 — parallel stack unavailable
        pass
    n = 0
    for fn in fns:
        try:
            n += fn._cache_size()
        except Exception:  # noqa: BLE001 — private API drifted
            return -1
    return n


# ---------------------------------------------------------------------------
# packed-layout prediction
# ---------------------------------------------------------------------------

#: semantic dims of every key in SnapshotArrays._base_device_dict — the
#: packed layout for ANY bucket combination follows from these plus the
#: sorted-key offset accumulation in SnapshotArrays.packed(). hdrf keys
#: are deliberately absent: their tree dims (H, D) don't scale with the
#: buckets, so hdrf sessions skip prewarm (predict returns None).
_PACKED_DIMS: Dict[str, Tuple[str, ...]] = {
    "task_init_req": ("T", "R"), "task_req": ("T", "R"),
    "task_job": ("T",), "task_rank": ("T",), "task_sig": ("T",),
    "task_counts_ready": ("T",), "task_valid": ("T",),
    "job_min": ("J",), "job_ready_base": ("J",), "job_queue": ("J",),
    "job_valid": ("J",), "job_drf_allocated": ("J", "R"),
    "drf_total": ("R",), "job_drf_prerank": ("J",),
    "node_idle": ("N", "R"), "node_extra_future": ("N", "R"),
    "node_used": ("N", "R"), "node_alloc": ("N", "R"),
    "node_npods": ("N",), "node_max_pods": ("N",), "node_valid": ("N",),
    "sig_masks": ("S", "N"),
    "queue_weight": ("Q",), "queue_capability": ("Q", "R"),
    "queue_allocated": ("Q", "R"), "queue_request": ("Q", "R"),
    "thresholds": ("R",), "scalar_dim_mask": ("R",),
}


def layout_dims(layout) -> Optional[Dict[str, int]]:
    """Recover the padded {T,N,J,Q,S,R} from a packed layout, or None when
    the layout carries keys outside the predictable set (hdrf)."""
    dims: Dict[str, int] = {}
    for key, _kind, _off, _size, shape in layout:
        names = _PACKED_DIMS.get(key)
        if names is None:
            return None
        for name, size in zip(names, shape):
            if dims.setdefault(name, size) != size:
                return None  # inconsistent layout; refuse to predict
    return dims


def predict_next_layout(layout, dims: Dict[str, int]):
    """Rebuild a packed layout for new padded sizes ``dims`` (complete
    {T,N,J,Q,S,R} map): same keys in the same (sorted) order, shapes
    remapped per _PACKED_DIMS, offsets re-accumulated exactly like
    SnapshotArrays.packed(). Byte-exact against a real flatten at those
    sizes (asserted by tests/test_precompile.py). None when the layout
    has unpredictable keys."""
    out = []
    foff = ioff = 0
    for key, kind, _off, _size, _shape in layout:
        names = _PACKED_DIMS.get(key)
        if names is None or any(n not in dims for n in names):
            return None
        shape = tuple(int(dims[n]) for n in names)
        size = 1
        for s in shape:
            size *= s
        if kind == "f":
            out.append((key, kind, foff, size, shape))
            foff += size
        else:
            out.append((key, kind, ioff, size, shape))
            ioff += size
    return tuple(out)


def dummy_packed_buffers(layout, chunk: int):
    """Zeroed chunked device-cache-shaped buffers (f2d, i2d) for a layout:
    the shapes — not the contents — are what the jit signature keys on.
    All-zero content makes the dummy solve converge immediately (no valid
    task, no valid job), so a warm call costs trace+compile plus a
    trivial device execution."""
    nf = max(off + size for _k, kind, off, size, _s in layout
             if kind == "f")
    ni = max(off + size for _k, kind, off, size, _s in layout
             if kind != "f")
    cf = -(-max(nf, 1) // chunk)
    ci = -(-max(ni, 1) // chunk)
    return (np.zeros((cf, chunk), np.float32),
            np.zeros((ci, chunk), np.int32))


def dummy_sharded_buffers(layout, chunk: int, mesh):
    """Zeroed, correctly-sharded dispatch inputs for the sharded arena
    entry (parallel.solve_allocate_sharded_arena) at a layout: replicated
    chunked rep buffers + [D, C, chunk] node buffers split along the mesh
    'n' axis, exactly the shardings ShardedDeviceCache commits — the jit
    cache keys on (aval, sharding), so a mis-sharded warm would compile a
    variant the session never dispatches."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .device_cache import _part_sizes, split_packed_layout

    D = int(mesh.devices.size)
    rep_l, node_l = split_packed_layout(layout, D)
    rf, ri = _part_sizes(rep_l)
    nf, ni = _part_sizes(node_l)
    crf = -(-max(rf, 1) // chunk)
    cri = -(-max(ri, 1) // chunk)
    cnf = -(-max(nf, 1) // chunk)
    cni = -(-max(ni, 1) // chunk)
    ns_rep = NamedSharding(mesh, P())
    ns_n = NamedSharding(mesh, P("n"))
    return (jax.device_put(np.zeros((crf, chunk), np.float32), ns_rep),
            jax.device_put(np.zeros((cri, chunk), np.int32), ns_rep),
            jax.device_put(np.zeros((D, cnf, chunk), np.float32), ns_n),
            jax.device_put(np.zeros((D, cni, chunk), np.int32), ns_n),
            rep_l, node_l)


def dummy_score_params(dims: Dict[str, int]) -> Dict[str, np.ndarray]:
    """Score-params dict with the avals build_score_inputs produces for a
    problem of these padded sizes (values irrelevant; shapes/dtypes key
    the jit signature)."""
    return {
        "binpack_weight": np.float32(0.0),
        "binpack_res_weights": np.ones(dims["R"], np.float32),
        "least_req_weight": np.float32(0.0),
        "most_req_weight": np.float32(0.0),
        "balanced_weight": np.float32(0.0),
        "node_static": np.zeros(dims["N"], np.float32),
    }


# ---------------------------------------------------------------------------
# background bucket pre-warm
# ---------------------------------------------------------------------------

#: static flag names shared by the packed solver entry points; the
#: sharded entry accepts a subset (parallel.sharded_solver.PACKED2D_FLAGS)
SOLVE_FLAG_NAMES = ("herd_mode", "score_families", "use_queue_cap",
                    "use_drf_order", "use_hdrf_order", "work_conserving")


class BucketPrewarmer:
    """Watch bucket occupancy; compile the next bucket's solver variants
    on a daemon thread before the cluster crosses into them.

    ``observe(arr, dc, flags)`` is called by the allocate action inside
    the dispatch/collect overlap window (zero critical-path cost: it only
    compares integers and maybe spawns a thread). When any of live
    T/N/J reaches ``threshold`` of its current bucket, the next bucket's
    layout is predicted and ``solve_allocate_packed2d`` +
    ``solve_allocate_delta`` (and, with a ``mesh``, the sharded packed2d
    entry) are traced+compiled against dummy buffers off-thread. Each
    (dims, flags) combination warms at most once per process; the
    persistent compilation cache makes the warm a disk-cache
    deserialization after the first process ever to cross that bucket.
    """

    def __init__(self, threshold: float = 0.8, mesh=None,
                 warm_delta: bool = True):
        self.threshold = threshold
        self.mesh = mesh
        self.warm_delta = warm_delta
        self._lock = threading.Lock()
        self._started: Dict[tuple, str] = {}   # key -> status
        self._threads: list = []
        self.completions = 0
        self.failures = 0

    # -- occupancy watch --------------------------------------------------

    def observe(self, arr, dc, flags: Optional[dict] = None) -> bool:
        """Check occupancy against the current buckets; spawn a warm for
        the next-bucket variant when warranted. Returns True when a warm
        was scheduled."""
        from .arrays import bucket

        layout = getattr(dc, "_layout", None)
        if layout is None:
            return False
        if flags is None:
            flags = getattr(dc, "last_solve_flags", None)
            if flags is None:
                return False
        flags = {k: v for k, v in flags.items() if k in SOLVE_FLAG_NAMES}
        live_t = len(arr.tasks_list)
        live_n = len(arr.nodes_list)
        live_j = len(arr.jobs_list)
        dims = layout_dims(layout)
        if dims is None:
            return False  # hdrf / unknown layout: no prediction
        crossed = []
        # J pads to bucket(nJ + 1) in the flatten, so its occupancy
        # compares live+1 against the bucket
        for name, live, pad1 in (("T", live_t, 0), ("N", live_n, 0),
                                 ("J", live_j, 1)):
            cur = dims[name]
            if live + pad1 >= self.threshold * cur and bucket(cur + 1) != cur:
                crossed.append(name)
        if not crossed:
            return False
        # an occupancy trigger says WHICH dims are near their edge, not
        # which will actually cross first (pods grow without nodes all the
        # time): warm every non-empty subset of the crossed dims, largest
        # first, so whichever combination the cluster lands on is covered
        # (≤7 combos, each deduped per process and disk-cached thereafter)
        fkey = tuple(sorted((k, v) for k, v in flags.items()))
        work = []
        subsets = sorted(
            (s for m in range(1, 1 << len(crossed))
             for s in [[d for i, d in enumerate(crossed) if m >> i & 1]]),
            key=len, reverse=True)
        for sub in subsets:
            nxt = dict(dims)
            for name in sub:
                nxt[name] = bucket(dims[name] + 1)
            key = (tuple(sorted(nxt.items())), fkey)
            with self._lock:
                if key in self._started:
                    continue
                self._started[key] = "running"
            layout2 = predict_next_layout(layout, nxt)
            if layout2 is None:
                with self._lock:
                    self._started[key] = "unsupported"
                continue
            work.append((key, layout2, nxt))
        if not work:
            return False
        t = threading.Thread(
            target=self._warm_many, args=(work, dc.chunk, flags),
            name="bucket-prewarm", daemon=True)
        with self._lock:
            self._threads.append(t)
        t.start()
        return True

    def _warm_many(self, work, chunk: int, flags: dict) -> None:
        for key, layout2, dims2 in work:
            self._warm(key, layout2, dims2, chunk, flags)

    # -- the warm itself (background thread) ------------------------------

    def _warm(self, key, layout, dims, chunk: int, flags: dict) -> None:
        watcher.install()
        watcher.register_background()
        try:
            import jax

            from .device_cache import PackedDeviceCache
            from .solver import solve_allocate_delta, solve_allocate_packed2d

            # device_put everything exactly like the real dispatch path
            # (PackedDeviceCache._full_ship / params_device): a committed
            # device array and a host np.ndarray key DIFFERENT jit cache
            # entries, so a numpy-fed warm would compile a variant the
            # session never dispatches
            params = {k2: jax.device_put(v)
                      for k2, v in dummy_score_params(dims).items()}

            def bufs():
                f2d, i2d = dummy_packed_buffers(layout, chunk)
                return jax.device_put(f2d), jax.device_put(i2d)

            r = solve_allocate_packed2d(*bufs(), layout, params, **flags)
            r.compact.block_until_ready()
            if self.warm_delta:
                # the fused dirty-chunk variant donates its buffers: give
                # it its own set
                k = PackedDeviceCache.FUSED_SLOTS
                zero = np.zeros(k, np.int32)
                res, nf, ni = solve_allocate_delta(
                    *bufs(), zero, np.zeros((k, chunk), np.float32),
                    zero, np.zeros((k, chunk), np.int32), layout, params,
                    **flags)
                res.compact.block_until_ready()
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P

                from ..parallel.sharded_solver import (
                    PACKED2D_FLAGS, solve_allocate_sharded_arena,
                    solve_allocate_sharded_packed2d,
                )
                sflags = {k2: v for k2, v in flags.items()
                          if k2 in PACKED2D_FLAGS}
                rs = solve_allocate_sharded_packed2d(
                    *bufs(), layout, params, self.mesh, **sflags)
                rs.assigned.block_until_ready()
                # the sharded ARENA variant too: a sharded session's
                # bucket crossing dispatches this entry against the
                # ShardedDeviceCache's shardings (node_static split along
                # 'n', scalars replicated), so the warm must match them
                sharded_bufs = dummy_sharded_buffers(
                    layout, chunk, self.mesh)
                ns_n = NamedSharding(self.mesh, P("n"))
                ns_rep = NamedSharding(self.mesh, P())
                sparams = {k2: jax.device_put(
                               np.asarray(v),
                               ns_n if k2 == "node_static" else ns_rep)
                           for k2, v in dummy_score_params(dims).items()}
                ra = solve_allocate_sharded_arena(
                    *sharded_bufs, sparams, self.mesh, **sflags)
                ra.assigned.block_until_ready()
            with self._lock:
                self._started[key] = "done"
                self.completions += 1
            from ..metrics import metrics

            metrics.prewarm_completions_total.inc()
            log.info("pre-warmed solver variants for buckets %s", dims)
        except Exception:  # noqa: BLE001 — a failed warm must not crash
            with self._lock:
                self._started[key] = "failed"
                self.failures += 1
            log.exception("bucket pre-warm failed for %s", dims)

    # -- sync points (bench / tests / shutdown) ---------------------------

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Join outstanding warm threads; True when none remain alive."""
        with self._lock:
            threads = list(self._threads)
        for t in threads:
            t.join(timeout)
        with self._lock:
            self._threads = [t for t in self._threads if t.is_alive()]
            return not self._threads

    @property
    def pending(self) -> int:
        with self._lock:
            return sum(1 for t in self._threads if t.is_alive())
