"""Hierarchical DRF ordering + progressive-filling cap in the kernel.

The reference's hdrf mode (plugins/drf/drf.go:527-633) keeps a queue-path
tree whose nodes carry weighted, saturation-scaled shares, re-sorted after
every placement. Here the tree is flattened to parent-pointer arrays once
per session (host side) and the share recursion runs as per-depth segment
reductions on device, so the round solver can re-rank jobs by the
hierarchical comparator every round — the hdrf analog of the plain
dominant-share re-rank in ops.solver.drf_state — AND gate each round's
growth per ancestor level so weighted trees converge to the reference's
weighted split (drf.go's one-placement-then-resort loop, in round-sized
bites).

Contract notes:
- the comparator walk (drf.go _compareQueues) compares (saturated,
  share/weight) level by level down the two queues' paths; the kernel
  encodes that as a fixed-depth lexicographic key, exact for
  uniform-depth hierarchies ("root/a/b" everywhere). Paths shorter than
  the deepest are padded with neutral (unsaturated, share 0) levels.
  On ragged hierarchies the key is a REFINEMENT of the host order:
  every pair the host comparator decides orders identically (the
  decision happens at a common-prefix level both encodings share);
  padding only breaks pairs the host leaves TIED — where the reference
  falls to its arbitrary-but-stable job-order tiebreak. Fuzzed against
  the host comparator in tests/test_fairshare.py
  (TestHDRFRaggedParity).
- saturation (_resource_saturated, drf.go:93-109): a leaf saturates when
  some dimension's allocation covers its request, or it requests a
  dimension the cluster has exhausted (not "demanding").
- internal-node shares use the reference's rescaling recursion
  (drf.go updateHierarchicalShare): unsaturated children are scaled to
  the minimum dominant share before summing into the parent, so
  siblings dominating DISJOINT dimensions both register as the min —
  the parent's share doesn't double-count orthogonal usage. The
  progressive cap below reads these SCALED shares, which is what makes
  it dimension-aware: two disjoint-dominant children can both fill past
  naive raw-allocation parity because their scaled keys stay equal.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from .arrays import bucket


def build_hdrf(arr, queues, job_attrs, total_allocated) -> None:
    """Fill arr.hdrf_* from the jobs' queue hierarchy annotations.

    queues: ssn.queues (QueueInfo with .hierarchy "root/eng/dev" and
    .weights "100/50/50"); job_attrs: drf plugin job attrs (unused beyond
    presence — leaf allocations ride arr.job_drf_allocated);
    total_allocated: cluster-wide allocated Resource (drf plugin's
    total_allocated) for the demanding-dimension flags.
    """
    vocab = arr.vocab
    R = arr.R
    J = arr.job_min.shape[0]

    # tree build: internal nodes keyed by path prefix, one leaf per job
    index: Dict[Tuple[str, ...], int] = {("root",): 0}
    parent = [0]        # root's parent is itself (never read)
    weight = [1.0]
    depth = [0]
    max_depth = 1
    job_path_nodes = []  # per job: list of internal node ids, depth 1..
    for j, job in enumerate(arr.jobs_list):
        q = queues.get(job.queue)
        hierarchy = getattr(q, "hierarchy", "") or "root"
        weights_s = getattr(q, "weights", "") or ""
        paths = hierarchy.split("/")
        wparts = weights_s.split("/")
        node_ids = []
        prefix = ("root",)
        for i in range(1, len(paths)):
            prefix = prefix + (paths[i],)
            nid = index.get(prefix)
            if nid is None:
                try:
                    w = float(wparts[i])
                except (IndexError, ValueError):
                    w = 1.0
                nid = len(parent)
                index[prefix] = nid
                parent.append(index[prefix[:-1]])
                weight.append(max(w, 1.0))
                depth.append(i)
            node_ids.append(nid)
        job_path_nodes.append(node_ids)
        # levels used by this job: internal 1..len(paths)-1 + leaf at
        # index len(paths)-1 => len(paths) columns suffice
        max_depth = max(max_depth, len(paths))

    n_internal = len(parent)
    # leaves: one per job slot (padded jobs get an inert leaf under root)
    H = bucket(n_internal + J)
    h_parent = np.zeros(H, np.int32)
    h_weight = np.ones(H, np.float32)
    h_depth = np.zeros(H, np.int32)
    h_is_leaf = np.zeros(H, bool)
    h_parent[:n_internal] = parent
    h_weight[:n_internal] = weight
    h_depth[:n_internal] = depth
    leaf_req = np.zeros((H, R), np.float32)
    job_leaf = np.zeros(J, np.int32)
    D = max_depth  # deepest level that can hold a node (leaves included)
    ancestors = np.full((J, D), -1, np.int32)
    for j in range(J):
        leaf = n_internal + j
        job_leaf[j] = leaf
        h_is_leaf[leaf] = True
        nodes = job_path_nodes[j] if j < len(job_path_nodes) else []
        h_parent[leaf] = nodes[-1] if nodes else 0
        h_depth[leaf] = len(nodes) + 1
        if j < len(arr.jobs_list):
            leaf_req[leaf] = arr.jobs_list[j].total_request.to_vector(vocab)
        for lvl, nid in enumerate(nodes):
            ancestors[j, lvl] = nid
        ancestors[j, len(nodes)] = leaf
    # unused leaf rows for padded job slots stay inert: depth 1 under
    # root, zero request, zero allocation -> share 0, never saturated
    arr.hdrf_parent = h_parent
    arr.hdrf_weight = h_weight
    arr.hdrf_depth = h_depth
    arr.hdrf_is_leaf = h_is_leaf
    arr.hdrf_leaf_req = leaf_req
    arr.hdrf_job_leaf = job_leaf
    arr.hdrf_ancestors = ancestors
    arr.hdrf_total_allocated = np.asarray(
        total_allocated.to_vector(vocab), np.float32)


def _hdrf_core(a, rank):
    """Shared device-side state for the hierarchical rank and cap.

    Returns (tree_state, rank_from, cap_from):
    - tree_state(jobres) -> (share[H], sat[H]): the reference's bottom-up
      weighted recursion (drf.go updateHierarchicalShare) over the live
      allocations a["job_drf_allocated"] + jobres.
    - rank_from(share, sat) -> (r_rank[T], job_pos[J]): jobs sorted by the
      per-level (saturated, share/weight) lexicographic key, tasks
      inheriting their job's position.
    - cap_from(share, sat, share_full, job_pos, eligible) -> eligible'[T]:
      the hierarchy-aware progressive-filling cap (see hdrf_state);
      share_full is tree_state evaluated with every eligible increment
      placed (the cap's linearization endpoint).
    """
    import jax
    import jax.numpy as jnp

    from .solver import _segment_prefix

    T = a["task_rank"].shape[0]
    J = a["job_min"].shape[0]
    H = a["hdrf_parent"].shape[0]
    D = a["hdrf_ancestors"].shape[1]
    parent = a["hdrf_parent"]
    weight = jnp.maximum(a["hdrf_weight"], 1.0)
    depth = a["hdrf_depth"]
    is_leaf = a["hdrf_is_leaf"]
    leaf_req = a["hdrf_leaf_req"]
    job_leaf = a["hdrf_job_leaf"]
    ancestors = a["hdrf_ancestors"]
    total = a["drf_total"]
    task_job = a["task_job"]
    rank = a["task_rank"] if rank is None else rank
    first_rank = jnp.full((J,), T, jnp.int32).at[task_job].min(rank)
    within_rank = rank - first_rank[task_job]
    BIG = jnp.int32(2**31 - 1)

    prerank = a.get("job_drf_prerank")
    if prerank is None:
        prerank = jnp.zeros(J, jnp.int32)
    # per-node prerank: leaves carry their job's, internal nodes neutral
    pr_node = jnp.full((H,), BIG, jnp.int32).at[job_leaf].set(prerank)

    # per-task increment in global dominant-share units (matches
    # ops.solver.drf_state's incr_t; accounting uses task_req)
    drf_total_c = jnp.maximum(total, 1e-9)
    incr_t = jnp.max(
        jnp.where(total[None, :] > 0.0,
                  a["task_req"] / drf_total_c[None, :], 0.0), axis=1)
    j_seg_start = jnp.concatenate(
        [jnp.array([True]), task_job[1:] != task_job[:-1]])

    def share_of(alloc):  # [H,R] -> [H]
        s = jnp.where(total[None, :] > 0.0,
                      alloc / jnp.maximum(total[None, :], 1e-9),
                      jnp.where(alloc > 0.0, 1.0, 0.0))
        return jnp.max(s, axis=1)

    def tree_state(jobres):
        """(share[H], sat[H]) after the bottom-up weighted recursion."""
        alloc = jnp.zeros((H, total.shape[0]), jnp.float32)
        alloc = alloc.at[job_leaf].add(a["job_drf_allocated"] + jobres)
        total_alloc = a["hdrf_total_allocated"] + jnp.sum(jobres, axis=0)
        demanding = total_alloc < total                       # [R]

        share = jnp.where(is_leaf, share_of(alloc), 0.0)
        sat_dim = (((alloc != 0.0) & (leaf_req != 0.0)
                    & (alloc >= leaf_req))
                   | (~demanding[None, :] & (leaf_req != 0.0)))
        sat = is_leaf & jnp.any(sat_dim, axis=1)

        for d in range(D - 1, -1, -1):  # static unroll, small depth
            child = depth == (d + 1)
            live = child & (share > 0.0) & ~sat
            mdr = jax.ops.segment_min(
                jnp.where(live, share, jnp.inf), parent, num_segments=H)
            scale = jnp.where(
                sat, 1.0, mdr[parent] / jnp.maximum(share, 1e-12))
            contrib = jnp.where((child & (share > 0.0))[:, None],
                                alloc * scale[:, None], 0.0)
            alloc_p = jax.ops.segment_sum(contrib, parent, num_segments=H)
            sat_p = jax.ops.segment_min(
                jnp.where(child, sat.astype(jnp.int32), 1), parent,
                num_segments=H) > 0
            has_child = jax.ops.segment_max(
                child.astype(jnp.int32), parent, num_segments=H) > 0
            tgt = (depth == d) & ~is_leaf & has_child
            alloc = jnp.where(tgt[:, None], alloc_p, alloc)
            share = jnp.where(tgt, share_of(alloc_p), share)
            sat = jnp.where(tgt, sat_p, sat)
        return share, sat

    # doubled-id tree recursion: the progressive cap needs the live state
    # AND the all-eligible-placed endpoint every round; stacking the two
    # problems on disjoint segment-id ranges [0,H) / [H,2H) runs both
    # through ONE pass of segment reductions instead of two (same per-
    # segment element sets, so the results match the separate recursions)
    parent2 = jnp.concatenate([parent, parent + H])
    depth2 = jnp.concatenate([depth, depth])
    is_leaf2 = jnp.concatenate([is_leaf, is_leaf])
    leaf_req2 = jnp.concatenate([leaf_req, leaf_req], axis=0)

    def tree_state_pair(jobres, jobres_full):
        """(share[H], sat[H], share_full[H]) — tree_state evaluated at both
        allocations in one fused recursion."""
        R_ = total.shape[0]
        alloc = jnp.zeros((2 * H, R_), jnp.float32)
        alloc = alloc.at[job_leaf].add(a["job_drf_allocated"] + jobres)
        alloc = alloc.at[job_leaf + H].add(
            a["job_drf_allocated"] + jobres_full)
        ta_a = a["hdrf_total_allocated"] + jnp.sum(jobres, axis=0)
        ta_b = a["hdrf_total_allocated"] + jnp.sum(jobres_full, axis=0)
        demanding = jnp.concatenate([
            jnp.broadcast_to((ta_a < total)[None, :], (H, R_)),
            jnp.broadcast_to((ta_b < total)[None, :], (H, R_))])

        share = jnp.where(is_leaf2, share_of(alloc), 0.0)
        sat_dim = (((alloc != 0.0) & (leaf_req2 != 0.0)
                    & (alloc >= leaf_req2))
                   | (~demanding & (leaf_req2 != 0.0)))
        sat = is_leaf2 & jnp.any(sat_dim, axis=1)

        for d in range(D - 1, -1, -1):  # static unroll, small depth
            child = depth2 == (d + 1)
            live = child & (share > 0.0) & ~sat
            mdr = jax.ops.segment_min(
                jnp.where(live, share, jnp.inf), parent2,
                num_segments=2 * H)
            scale = jnp.where(
                sat, 1.0, mdr[parent2] / jnp.maximum(share, 1e-12))
            contrib = jnp.where((child & (share > 0.0))[:, None],
                                alloc * scale[:, None], 0.0)
            alloc_p = jax.ops.segment_sum(contrib, parent2,
                                          num_segments=2 * H)
            sat_p = jax.ops.segment_min(
                jnp.where(child, sat.astype(jnp.int32), 1), parent2,
                num_segments=2 * H) > 0
            has_child = jax.ops.segment_max(
                child.astype(jnp.int32), parent2,
                num_segments=2 * H) > 0
            tgt = (depth2 == d) & ~is_leaf2 & has_child
            alloc = jnp.where(tgt[:, None], alloc_p, alloc)
            share = jnp.where(tgt, share_of(alloc_p), share)
            sat = jnp.where(tgt, sat_p, sat)
        return share[:H], sat[:H], share[H:]

    def rank_from(share, sat):
        # per-level lexicographic job key: level 1 is most significant;
        # within a level saturation dominates share/weight
        # (drf.go _compareQueues). The pre-drf provider rank (priority/
        # gang) tops even that — see job_drf_prerank.
        keys = [jnp.arange(J, dtype=jnp.int32)]  # final tie: static order
        for lvl in range(D - 1, -1, -1):
            anc = ancestors[:, lvl]                           # [J]
            ok = anc >= 0
            anc_c = jnp.maximum(anc, 0)
            keys.append(jnp.where(ok, share[anc_c] / weight[anc_c], 0.0))
            keys.append(jnp.where(ok, sat[anc_c], False))
        keys.append(prerank)
        order_j = jnp.lexsort(tuple(keys))
        job_pos = jnp.zeros(J, jnp.int32).at[order_j].set(
            jnp.arange(J, dtype=jnp.int32))
        order_t = jnp.lexsort((within_rank, job_pos[task_job]))
        r_rank = jnp.zeros(T, jnp.int32).at[order_t].set(
            jnp.arange(T, dtype=jnp.int32))
        return r_rank, job_pos

    def cap_from(share, sat, share_full, job_pos, eligible):
        """Hierarchy-aware progressive-filling cap.

        Per round, for every ancestor level (leaf-most first), a subtree
        may grow its (scaled share)/weight key only to
        (min competing sibling key) + step — the round-sized version of
        the reference's pick-lowest-key-queue loop; a subtree already
        past that mark waits, exactly like a queue the comparator ranks
        behind its siblings. Details:

        - keys come from the SCALED tree shares, so disjoint-dominant
          siblings (whose scaled keys stay equal as both fill) are not
          throttled against each other (the dimension-awareness a raw
          subtree-allocation cap lacks).
        - the allowed key growth converts to a budget in raw increment
          units through a per-subtree linearization: key_full (the tree
          re-evaluated with every eligible increment placed) bounds how
          far this subtree's key can move, so a subtree whose raw fill
          moves its scaled key slowly (disjoint-dominant children) gets
          a proportionally LARGER raw budget. The mean slope
          (key_full-key)/raw_total is <= 1/weight (scaling never
          amplifies), which guarantees the min-key subtree's budget
          admits at least its first task — per-round progress.
        - step's floor is weight-proportional in share units
          (weight/(8*competing_weight)), so sibling subtrees fill at
          weight-proportional RATES and a saturated cluster lands on the
          weighted split in ~8 rounds even without node contention.
        - each level's budget is charged in live hierarchical job-rank
          order (job_pos), so sibling subtrees alternate the way the
          reference's per-placement re-sort does; within a job the
          static order is the live order.
        - levels refine eligibility leaf-most first, so a task blocked
          at its queue level doesn't consume an upper subtree's budget.
        - saturated nodes rank after unsaturated ones in the comparator
          (drf.go:560-566); the cap analog blocks a subtree while an
          unsaturated competing sibling exists. A leaf saturates only
          when fully allocated or demanding an exhausted dimension —
          both unplaceable — so the block cannot strand feasible work.
          (Callers additionally prefilter never-fit tasks — see the
          solver's placeable mask — so an infeasible min-key sibling
          cannot hold its whole group's budget at zero.)
        - leaf siblings compete within the best (lowest) prerank group
          under their parent: with hierarchy on, the tree governs
          cross-queue order and priority orders jobs within a queue, so
          a high-priority job is not throttled against (or made to
          yield headroom to) lower-priority sibling shares.
        """
        key = share / weight                                    # [H]
        key_full = share_full / weight                          # [H]
        still = eligible
        max_incr = jnp.max(jnp.where(eligible, incr_t, 0.0))
        # full (round-entry) backlog per job: the SAME quantity share_full
        # was evaluated with, so grow/slope stays dimensionally consistent
        contrib_full = jnp.where(eligible, incr_t, 0.0)
        job_full = jax.ops.segment_sum(contrib_full, task_job,
                                       num_segments=J)
        for lvl in range(D - 1, -1, -1):
            anc_j = ancestors[:, lvl]                           # [J]
            present_j = anc_j >= 0
            anc_jc = jnp.maximum(anc_j, 0)
            # within-job cumulative eligible increment (static task order
            # == live order within a job)
            contrib = jnp.where(still, incr_t, 0.0)
            within_cum = _segment_prefix(
                contrib[:, None], j_seg_start)[:, 0] + contrib
            job_incr = jax.ops.segment_sum(contrib, task_job,
                                           num_segments=J)
            still_job = job_incr > 0.0
            elig_j = still_job & present_j
            node_elig = jnp.zeros(H, dtype=bool).at[anc_jc].max(elig_j)
            competing = node_elig & ~sat
            # leaf prerank gate (see docstring): only the best-prerank
            # eligible leaves of a parent set the pace
            minpr_p = jax.ops.segment_min(
                jnp.where(competing, pr_node, BIG), parent,
                num_segments=H)
            competing = competing & (~is_leaf
                                     | (pr_node == minpr_p[parent]))
            m_p = jax.ops.segment_min(
                jnp.where(competing, key, jnp.inf), parent,
                num_segments=H)
            cws_p = jax.ops.segment_sum(
                jnp.where(competing, weight, 0.0), parent, num_segments=H)
            m_j = m_p[parent[anc_jc]]
            cws_j = cws_p[parent[anc_jc]]
            has_comp = jnp.isfinite(m_j)
            w_j = weight[anc_jc]
            step_j = jnp.maximum(max_incr / w_j,
                                 1.0 / (8.0 * jnp.maximum(cws_j, 1e-9)))
            grow_j = jnp.where(
                has_comp,
                jnp.maximum(m_j + step_j - key[anc_jc], 0.0), jnp.inf)
            grow_j = jnp.where(present_j & sat[anc_jc] & has_comp,
                               0.0, grow_j)
            # allowed key growth -> raw-units budget via the subtree's
            # mean slope over its whole ROUND-ENTRY backlog (the backlog
            # share_full was evaluated with): budget = grow/slope =
            # grow * full_total/denom, capped at full_total. Slope
            # <= 1/weight (scaling never amplifies), so grow >= step >=
            # max_incr/weight guarantees the min-key subtree's budget
            # admits at least one task.
            node_full = jnp.zeros(H, jnp.float32).at[anc_jc].add(
                jnp.where(present_j, job_full, 0.0))
            denom_j = key_full[anc_jc] - key[anc_jc]
            full_j = node_full[anc_jc]
            budget_j = jnp.where(
                denom_j > 1e-12,
                jnp.clip(grow_j / jnp.maximum(denom_j, 1e-12), 0.0, 1.0)
                * full_j,
                jnp.where(grow_j > 0.0, full_j, 0.0))           # [J]
            # min-key floor: the comparator's lowest-key queue always
            # places at least one task per re-sort in the reference; the
            # slope bound alone cannot guarantee that here, because k
            # same-dominant-dimension children rescaling to a rising min
            # share amplify their parent's key growth up to k-fold, which
            # can shave the step budget just under one task
            is_min_j = (present_j & has_comp & ~sat[anc_jc]
                        & (key[anc_jc]
                           <= m_j + 1e-7 + 1e-5 * jnp.abs(m_j)))
            budget_j = jnp.where(is_min_j,
                                 jnp.maximum(budget_j, max_incr), budget_j)
            # budget charged in live job-rank order: jobs under the same
            # ancestor sorted by job_pos, exclusive prefix of their
            # (post-refinement) eligible increments
            sort_key = jnp.where(present_j,
                                 anc_jc * (J + 1) + job_pos, BIG)
            p_j = jnp.argsort(sort_key)
            s_anc = anc_jc[p_j]
            s_seg = jnp.concatenate(
                [jnp.array([True]),
                 (s_anc[1:] != s_anc[:-1])
                 | (~present_j[p_j][1:] | ~present_j[p_j][:-1])])
            s_incr = jnp.where(present_j[p_j], job_incr[p_j], 0.0)
            s_base = _segment_prefix(s_incr[:, None], s_seg)[:, 0]
            job_base = jnp.zeros(J, jnp.float32).at[p_j].set(s_base)
            cum_t = job_base[task_job] + within_cum             # [T]
            ok = cum_t <= budget_j[task_job] + 1e-6
            still = still & (~present_j[task_job] | ok)
        return still

    return tree_state, tree_state_pair, rank_from, cap_from


def hdrf_state(a, rank):
    """Device-side: returns rank_and_cap(eligible, jobres) ->
    (r_rank[T], eligible'[T]) — one tree recursion per round feeding both
    the hierarchical re-rank and the progressive-filling cap.

    This is the round solver's hdrf analog of ops.solver.drf_state's
    (drf_rank, drf_cap) pair; parity vs the reference's
    place-one-resort loop is fuzzed in tests/test_fairshare.py
    (TestHDRFProgressiveParity).
    """

    import jax

    _, tree_state_pair, rank_from, cap_from = _hdrf_core(a, rank)
    J = a["job_min"].shape[0]

    def rank_and_cap(eligible, jobres):
        # live state + the every-eligible-increment-placed endpoint (the
        # cap's linearization, see cap_from), fused into one doubled-id
        # tree recursion instead of two separate passes per round
        pending = jax.ops.segment_sum(
            a["task_req"] * eligible[:, None], a["task_job"],
            num_segments=J)
        share, sat, share_full = tree_state_pair(jobres, jobres + pending)
        r_rank, job_pos = rank_from(share, sat)
        still = cap_from(share, sat, share_full, job_pos, eligible)
        return r_rank, still

    return rank_and_cap


def hdrf_rank_state(a, rank):
    """Device-side: returns hdrf_rank(jobres) -> [T] int32 dense ranks
    (the re-rank alone, no cap — comparator parity tests and consumers
    that manage their own eligibility)."""
    tree_state, _, rank_from, _ = _hdrf_core(a, rank)

    def hdrf_rank(jobres):
        share, sat = tree_state(jobres)
        r_rank, _ = rank_from(share, sat)
        return r_rank

    return hdrf_rank
