"""Hierarchical DRF ordering in the kernel.

The reference's hdrf mode (plugins/drf/drf.go:527-633) keeps a queue-path
tree whose nodes carry weighted, saturation-scaled shares, re-sorted after
every placement. Here the tree is flattened to parent-pointer arrays once
per session (host side) and the share recursion runs as per-depth segment
reductions on device, so the round solver can re-rank jobs by the
hierarchical comparator every round — the hdrf analog of the plain
dominant-share re-rank in ops.solver.drf_state.

Contract notes:
- the comparator walk (drf.go _compareQueues) compares (saturated,
  share/weight) level by level down the two queues' paths; the kernel
  encodes that as a fixed-depth lexicographic key, exact for
  uniform-depth hierarchies ("root/a/b" everywhere). Paths shorter than
  the deepest are padded with neutral (unsaturated, share 0) levels.
  On ragged hierarchies the key is a REFINEMENT of the host order:
  every pair the host comparator decides orders identically (the
  decision happens at a common-prefix level both encodings share);
  padding only breaks pairs the host leaves TIED — where the reference
  falls to its arbitrary-but-stable job-order tiebreak. Fuzzed against
  the host comparator in tests/test_fairshare.py
  (TestHDRFRaggedParity).
- saturation (_resource_saturated, drf.go:93-109): a leaf saturates when
  some dimension's allocation covers its request, or it requests a
  dimension the cluster has exhausted (not "demanding").
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from .arrays import bucket


def build_hdrf(arr, queues, job_attrs, total_allocated) -> None:
    """Fill arr.hdrf_* from the jobs' queue hierarchy annotations.

    queues: ssn.queues (QueueInfo with .hierarchy "root/eng/dev" and
    .weights "100/50/50"); job_attrs: drf plugin job attrs (unused beyond
    presence — leaf allocations ride arr.job_drf_allocated);
    total_allocated: cluster-wide allocated Resource (drf plugin's
    total_allocated) for the demanding-dimension flags.
    """
    vocab = arr.vocab
    R = arr.R
    J = arr.job_min.shape[0]

    # tree build: internal nodes keyed by path prefix, one leaf per job
    index: Dict[Tuple[str, ...], int] = {("root",): 0}
    parent = [0]        # root's parent is itself (never read)
    weight = [1.0]
    depth = [0]
    max_depth = 1
    job_path_nodes = []  # per job: list of internal node ids, depth 1..
    for j, job in enumerate(arr.jobs_list):
        q = queues.get(job.queue)
        hierarchy = getattr(q, "hierarchy", "") or "root"
        weights_s = getattr(q, "weights", "") or ""
        paths = hierarchy.split("/")
        wparts = weights_s.split("/")
        node_ids = []
        prefix = ("root",)
        for i in range(1, len(paths)):
            prefix = prefix + (paths[i],)
            nid = index.get(prefix)
            if nid is None:
                try:
                    w = float(wparts[i])
                except (IndexError, ValueError):
                    w = 1.0
                nid = len(parent)
                index[prefix] = nid
                parent.append(index[prefix[:-1]])
                weight.append(max(w, 1.0))
                depth.append(i)
            node_ids.append(nid)
        job_path_nodes.append(node_ids)
        # levels used by this job: internal 1..len(paths)-1 + leaf at
        # index len(paths)-1 => len(paths) columns suffice
        max_depth = max(max_depth, len(paths))

    n_internal = len(parent)
    # leaves: one per job slot (padded jobs get an inert leaf under root)
    H = bucket(n_internal + J)
    h_parent = np.zeros(H, np.int32)
    h_weight = np.ones(H, np.float32)
    h_depth = np.zeros(H, np.int32)
    h_is_leaf = np.zeros(H, bool)
    h_parent[:n_internal] = parent
    h_weight[:n_internal] = weight
    h_depth[:n_internal] = depth
    leaf_req = np.zeros((H, R), np.float32)
    job_leaf = np.zeros(J, np.int32)
    D = max_depth  # deepest level that can hold a node (leaves included)
    ancestors = np.full((J, D), -1, np.int32)
    for j in range(J):
        leaf = n_internal + j
        job_leaf[j] = leaf
        h_is_leaf[leaf] = True
        nodes = job_path_nodes[j] if j < len(job_path_nodes) else []
        h_parent[leaf] = nodes[-1] if nodes else 0
        h_depth[leaf] = len(nodes) + 1
        if j < len(arr.jobs_list):
            leaf_req[leaf] = arr.jobs_list[j].total_request.to_vector(vocab)
        for lvl, nid in enumerate(nodes):
            ancestors[j, lvl] = nid
        ancestors[j, len(nodes)] = leaf
    # unused leaf rows for padded job slots stay inert: depth 1 under
    # root, zero request, zero allocation -> share 0, never saturated
    arr.hdrf_parent = h_parent
    arr.hdrf_weight = h_weight
    arr.hdrf_depth = h_depth
    arr.hdrf_is_leaf = h_is_leaf
    arr.hdrf_leaf_req = leaf_req
    arr.hdrf_job_leaf = job_leaf
    arr.hdrf_ancestors = ancestors
    arr.hdrf_total_allocated = np.asarray(
        total_allocated.to_vector(vocab), np.float32)


def hdrf_rank_state(a, rank):
    """Device-side: returns hdrf_rank(jobres) -> [T] int32 dense ranks.

    jobres [J,R] is the solve's own placements; leaf allocations are
    a["job_drf_allocated"] + jobres. Shares recompute bottom-up by depth
    level (children of depth-d nodes are exactly depth d+1), then jobs
    sort by the per-level (saturated, share/weight) lexicographic key.

    KNOWN DEVIATION (round-5 lever): the progressive-filling cap paired
    with this rank is the plain LEAF-share cap (ops.solver.drf_state),
    which converges uniform-dominant-resource hierarchies toward
    egalitarian per-job splits instead of the weighted tree split the
    host comparator reaches placement-by-placement. A hierarchy-aware
    cap (gating each job's growth at every ancestor level against live
    sibling subtree keys) fixes the uniform case but regresses
    disjoint-dominant-resource rescaling (eng children on different
    dims must BOTH fill past naive subtree parity); it needs to be
    dimension-aware before it can ship. tests/test_e2e.py
    TestExampleIntegrations encodes the current contract.
    """
    import jax
    import jax.numpy as jnp

    T = a["task_rank"].shape[0]
    J = a["job_min"].shape[0]
    H = a["hdrf_parent"].shape[0]
    D = a["hdrf_ancestors"].shape[1]
    parent = a["hdrf_parent"]
    weight = jnp.maximum(a["hdrf_weight"], 1.0)
    depth = a["hdrf_depth"]
    is_leaf = a["hdrf_is_leaf"]
    leaf_req = a["hdrf_leaf_req"]
    job_leaf = a["hdrf_job_leaf"]
    ancestors = a["hdrf_ancestors"]
    total = a["drf_total"]
    rank = a["task_rank"] if rank is None else rank
    first_rank = jnp.full((J,), T, jnp.int32).at[a["task_job"]].min(rank)
    within_rank = rank - first_rank[a["task_job"]]

    def share_of(alloc):  # [H,R] -> [H]
        s = jnp.where(total[None, :] > 0.0,
                      alloc / jnp.maximum(total[None, :], 1e-9),
                      jnp.where(alloc > 0.0, 1.0, 0.0))
        return jnp.max(s, axis=1)

    def tree_state(jobres):
        """(share[H], sat[H]) after the bottom-up weighted recursion."""
        alloc = jnp.zeros((H, a["drf_total"].shape[0]), jnp.float32)
        alloc = alloc.at[job_leaf].add(a["job_drf_allocated"] + jobres)
        total_alloc = a["hdrf_total_allocated"] + jnp.sum(jobres, axis=0)
        demanding = total_alloc < total                       # [R]

        share = jnp.where(is_leaf, share_of(alloc), 0.0)
        sat_dim = (((alloc != 0.0) & (leaf_req != 0.0)
                    & (alloc >= leaf_req))
                   | (~demanding[None, :] & (leaf_req != 0.0)))
        sat = is_leaf & jnp.any(sat_dim, axis=1)

        for d in range(D - 1, -1, -1):  # static unroll, small depth
            child = depth == (d + 1)
            live = child & (share > 0.0) & ~sat
            mdr = jax.ops.segment_min(
                jnp.where(live, share, jnp.inf), parent, num_segments=H)
            scale = jnp.where(
                sat, 1.0, mdr[parent] / jnp.maximum(share, 1e-12))
            contrib = jnp.where((child & (share > 0.0))[:, None],
                                alloc * scale[:, None], 0.0)
            alloc_p = jax.ops.segment_sum(contrib, parent, num_segments=H)
            sat_p = jax.ops.segment_min(
                jnp.where(child, sat.astype(jnp.int32), 1), parent,
                num_segments=H) > 0
            has_child = jax.ops.segment_max(
                child.astype(jnp.int32), parent, num_segments=H) > 0
            tgt = (depth == d) & ~is_leaf & has_child
            alloc = jnp.where(tgt[:, None], alloc_p, alloc)
            share = jnp.where(tgt, share_of(alloc_p), share)
            sat = jnp.where(tgt, sat_p, sat)
        return share, sat

    def hdrf_rank(jobres):
        share, sat = tree_state(jobres)

        # per-level lexicographic job key: level 1 is most significant;
        # within a level saturation dominates share/weight
        # (drf.go _compareQueues). The pre-drf provider rank (priority/
        # gang) tops even that — see job_drf_prerank.
        keys = [jnp.arange(J, dtype=jnp.int32)]  # final tie: static order
        for lvl in range(D - 1, -1, -1):
            anc = ancestors[:, lvl]                           # [J]
            ok = anc >= 0
            anc_c = jnp.maximum(anc, 0)
            keys.append(jnp.where(ok, share[anc_c] / weight[anc_c], 0.0))
            keys.append(jnp.where(ok, sat[anc_c], False))
        prerank = a.get("job_drf_prerank")
        keys.append(prerank if prerank is not None
                    else jnp.zeros(J, jnp.int32))
        order_j = jnp.lexsort(tuple(keys))
        job_pos = jnp.zeros(J, jnp.int32).at[order_j].set(
            jnp.arange(J, dtype=jnp.int32))
        order_t = jnp.lexsort((within_rank, job_pos[a["task_job"]]))
        return jnp.zeros(T, jnp.int32).at[order_t].set(
            jnp.arange(T, dtype=jnp.int32))

    return hdrf_rank
