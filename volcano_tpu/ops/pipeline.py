"""Cross-session upload/solve/readback pipeline.

Steady state on a latency-expensive tunnel is wall-clock bound by the
per-session round trips, not by device compute: BENCH_r04 measured
wall p50 176 ms against 22 ms of device solve time, with a 64-108 ms
no-op dispatch RTT floor. Each synchronous session pays (at least) one
upload+dispatch trip and one readback trip that the device spends idle.

``SessionPipeline`` amortizes those trips across consecutive sessions by
keeping three phases in flight at once, on separate streams/threads:

- **next-session delta upload** — session s+1's flatten + arena delta
  plan run on the caller thread and its dirty chunks are dispatched
  (riding the fused solve's argument transfer) while session s is still
  solving; JAX dispatch is async, so the caller never blocks here;
- **in-flight solve** — session s executes on device (device work is
  serial in dispatch order, so back-to-back dispatches queue without
  idling the chip);
- **previous-session readback** — session s-1's result transfer + decode
  block on the dedicated collector thread, concurrently with both of the
  above. ``start_readback`` additionally begins the device->host copy
  right at dispatch time when the runtime supports it, so the transfer
  overlaps the solve tail even before the collector blocks.

Wall time per steady-state session converges to
``max(device_ms, host_flatten_ms)`` instead of
``flatten + upload RTT + device + readback RTT``.

Decision safety: the pipeline never reorders *dependent* work — a
submit()'s dispatch closure runs on the caller thread in program order,
and results come back strictly FIFO. Callers whose session s+1 inputs
depend on session s's *results* (the scheduler's allocate action: binds
feed the next snapshot) must keep collect inside the cycle and only get
the start_readback overlap; callers with exogenous inputs (the bench's
churn script, trace replay, the solver sidecar) get the full three-phase
overlap. Bind-for-bind identity of both shapes against the serial path
is asserted by tests/test_arena.py.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Tuple

__all__ = ["SessionPipeline", "SessionTicket", "start_readback"]


def start_readback(*arrays) -> None:
    """Begin async device->host transfer for result arrays at dispatch
    time (jax ``copy_to_host_async``), so the wire transfer overlaps the
    remaining device work and any host-side overlap-window work. A
    runtime without the hook (or an array that is already host-side)
    makes this a no-op — the later blocking readback is then simply
    synchronous, never wrong."""
    for a in arrays:
        try:
            fn = getattr(a, "copy_to_host_async", None)
            if fn is not None:
                fn()
        except Exception:  # noqa: BLE001 — advisory prefetch only
            pass


class SessionTicket:
    """Handle for one in-flight session: resolves to the collect
    callback's return value (or re-raises its exception)."""

    __slots__ = ("tag", "_event", "_value", "_error", "t_dispatched",
                 "t_collected")

    def __init__(self, tag):
        self.tag = tag
        self._event = threading.Event()
        self._value = None
        self._error: Optional[BaseException] = None
        self.t_dispatched: float = 0.0
        self.t_collected: float = 0.0

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(f"session {self.tag!r} not collected "
                               f"within {timeout}s")
        if self._error is not None:
            raise self._error
        return self._value


class SessionPipeline:
    """FIFO three-phase session pipeline with one collector thread.

    ``submit(tag, dispatch, collect)`` runs ``dispatch()`` on the caller
    thread (an async JAX dispatch: upload + solve enqueue, returns device
    futures immediately) and hands ``collect(dispatched)`` — the blocking
    readback + decode — to the collector thread. At most ``depth``
    sessions are in flight; a deeper submit blocks until the oldest
    collects (bounded device memory: each in-flight fused session owns
    its own donated buffer generation).

    The ``events`` log records ("dispatch"|"collect", tag, t) in real
    order — the phase-overlap smoke test asserts that session s+1's
    dispatch lands before session s's collect completes.
    """

    def __init__(self, depth: int = 2):
        if depth < 1:
            raise ValueError("pipeline depth must be >= 1")
        self.depth = depth
        self._lock = threading.Lock()
        self._inflight: List[SessionTicket] = []
        self._collected: List[SessionTicket] = []
        self.events: List[Tuple[str, Any, float]] = []
        self._cv = threading.Condition(self._lock)
        self._queue: List[Tuple[SessionTicket, Any, Callable]] = []
        self._stop = False
        self._collector = threading.Thread(
            target=self._collect_loop, name="session-collector", daemon=True)
        self._collector.start()

    # -- producer side (caller thread) ---------------------------------

    def submit(self, tag, dispatch: Callable[[], Any],
               collect: Callable[[Any], Any],
               timeout: Optional[float] = None) -> SessionTicket:
        # backpressure BEFORE dispatching: the donated arena buffers for
        # session s+1 must not be consumed while depth sessions already
        # queue (device memory and fairness, not correctness)
        with self._cv:
            deadline = None if timeout is None else time.monotonic() + timeout
            while len(self._inflight) >= self.depth and not self._stop:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("pipeline backpressure timeout")
                self._cv.wait(remaining)
            if self._stop:
                raise RuntimeError("pipeline is closed")
            ticket = SessionTicket(tag)
            self._inflight.append(ticket)
        dispatched = dispatch()   # async: upload + solve enqueue
        ticket.t_dispatched = time.perf_counter()
        with self._cv:
            self.events.append(("dispatch", tag, ticket.t_dispatched))
            self._queue.append((ticket, dispatched, collect))
            self._cv.notify_all()
        return ticket

    def drain(self, timeout: Optional[float] = None) -> List[SessionTicket]:
        """Wait until every submitted session collected; returns all
        tickets in submit order (accumulated across the pipeline's
        lifetime)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._inflight:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("pipeline drain timeout")
                self._cv.wait(remaining)
            return list(self._collected)

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._collector.join(timeout=5.0)

    # -- collector side (background thread) ----------------------------

    def _collect_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    self._cv.wait()
                if self._stop and not self._queue:
                    return
                ticket, dispatched, collect = self._queue.pop(0)
            try:
                ticket._value = collect(dispatched)
            except BaseException as e:  # noqa: BLE001 — surfaced at result()
                ticket._error = e
            ticket.t_collected = time.perf_counter()
            with self._cv:
                self.events.append(("collect", ticket.tag,
                                    ticket.t_collected))
                self._inflight.remove(ticket)
                self._collected.append(ticket)
                self._cv.notify_all()
            ticket._event.set()

    # -- introspection (tests / bench) ---------------------------------

    def overlap_pairs(self) -> int:
        """Count of (dispatch of session k+1) events that landed before
        (collect of session k) — the phase-overlap evidence the smoke
        test asserts on. Tags must be orderable submit indices."""
        with self._lock:
            ev = list(self.events)
        collected_at = {tag: t for kind, tag, t in ev if kind == "collect"}
        n = 0
        for kind, tag, t in ev:
            if kind != "dispatch":
                continue
            prev = tag - 1 if isinstance(tag, int) else None
            if prev is not None and prev in collected_at \
                    and t < collected_at[prev]:
                n += 1
        return n
