"""Eviction solve: batched preempt/reclaim victim selection on TPU.

Replaces the reference's per-preemptor Python/Go victim loops
(actions/preempt/preempt.go:186-262, actions/reclaim/reclaim.go:40-192) with
one jitted lax.scan over preemptor tasks:

- victims are flattened once, sorted by (node, cheapest-first) — the order
  the reference pops its per-node victim priority queue in;
- per step, each node's minimal victim prefix that makes the preemptor fit
  is found with segment prefix-sums ("evict cheapest-first until FutureIdle
  fits", preempt.go:219-240 / "until the request is covered",
  reclaim.go:91-100) — [V,R] cumsums, no host round-trips;
- the preemptor pipelines onto the best feasible node (score order, like the
  host loop's node_order_fn sort) and the chosen victims die for later steps;
- preempt's gang atomicity (Statement commit iff JobPipelined) runs as a
  job-boundary revert, exactly like solve_allocate_sequential's.

Accepted greedy-order deviations vs the host oracle (documented contract):
plugin eligibility (drf share deltas, proportion deserved) is frozen at
solve start rather than re-evaluated after every eviction, and claimer
queues are visited in snapshot order rather than re-sorted per placement.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .solver import NEG, _segment_prefix, le_fits, score_matrix


class EvictResult(NamedTuple):
    assigned: jnp.ndarray    # [T] int32: node index the task pipelines on, or -1
    evicted_by: jnp.ndarray  # [V] int32: preemptor task index, or -1
    job_placed: jnp.ndarray  # [J] int32: pipelined placements per job


@functools.partial(jax.jit, static_argnames=(
    "score_families", "require_freed_covers", "allow_revert", "stop_at_need"))
def solve_evict(arrays: Dict[str, jnp.ndarray],
                victims: Dict[str, jnp.ndarray],
                score_params: Dict[str, jnp.ndarray],
                score_families: Tuple[str, ...] = ("kube",),
                require_freed_covers: bool = False,
                allow_revert: bool = True,
                stop_at_need: bool = True) -> EvictResult:
    """Scan preemptor tasks in (queue, job, task) rank order.

    arrays: a flatten of the *pending preemptor tasks* (ops.flatten_snapshot).
    victims: v_req [V,R] accounting resreq sorted by (node, cheapest-first);
      v_node [V] int32; v_valid [V] bool; elig [J,V] bool per preemptor job
      (tier-intersected Preemptable/Reclaimable verdicts + queue scoping);
      job_need [J] int32 pipelines still needed for JobPipelined.

    require_freed_covers: reclaim semantics — the freed victim resources
      alone must cover the claimer's request (reclaim.go:91-101), vs preempt
      where FutureIdle + freed must fit (preempt.go:219-240).
    allow_revert / stop_at_need: preempt's gang statement semantics; off for
      reclaim (evictions are immediate, jobs aren't capped at min).
    """
    a = arrays
    v_req = victims["v_req"]
    v_node = victims["v_node"]
    v_valid = victims["v_valid"]
    elig = victims["elig"]
    need = victims["job_need"]
    T = a["task_init_req"].shape[0]
    N = a["node_idle"].shape[0]
    V = v_req.shape[0]
    thr = a["thresholds"]
    sm = a["scalar_dim_mask"]
    sig_feas = a["sig_masks"][a["task_sig"]] & a["node_valid"][None, :]
    future0 = a["node_idle"] + a["node_extra_future"]
    # node ordering scores, frozen at solve start: one [T,N] matmul batch
    score_all = score_matrix(a["task_init_req"], future0, a["node_used"],
                             a["node_alloc"], score_params, score_families)
    seg_start = jnp.concatenate(
        [jnp.array([True]), v_node[1:] != v_node[:-1]])
    vidx = jnp.arange(V)

    def finalize(st, jidx):
        """Job boundary: revert this job's evictions and placements unless it
        reached JobPipelined (Statement.Discard, preempt.go:252-257)."""
        (future, alive, evby, assigned, jalloc,
         s_future, s_alive, s_evby, s_assigned) = st
        if not allow_revert:
            return future, alive, evby, assigned, jalloc
        done = jalloc[jidx] >= need[jidx]
        future = jnp.where(done, future, s_future)
        alive = jnp.where(done, alive, s_alive)
        evby = jnp.where(done, evby, s_evby)
        assigned = jnp.where(done, assigned, s_assigned)
        jalloc = jnp.where(done, jalloc, jalloc.at[jidx].set(0))
        return future, alive, evby, assigned, jalloc

    def step(carry, i):
        (future, alive, evby, assigned, jalloc, cur_job,
         s_future, s_alive, s_evby, s_assigned) = carry
        jidx = a["task_job"][i]
        boundary = jidx != cur_job

        def at_boundary(args):
            future, alive, evby, assigned, jalloc = finalize(args, cur_job)
            # fresh snapshots for the job now starting
            return (future, alive, evby, assigned, jalloc,
                    future, alive, evby, assigned)

        (future, alive, evby, assigned, jalloc,
         s_future, s_alive, s_evby, s_assigned) = jax.lax.cond(
            boundary, at_boundary, lambda args: args,
            (future, alive, evby, assigned, jalloc,
             s_future, s_alive, s_evby, s_assigned))
        cur_job = jidx

        active = a["task_valid"][i]
        if stop_at_need:
            # a job stops preempting once pipelined (preempt.go:200-207)
            active = active & (jalloc[jidx] < need[jidx])

        elig_v = elig[jidx] & alive & v_valid
        vreq_m = v_req * elig_v[:, None]
        prefix_incl = _segment_prefix(vreq_m, seg_start) + vreq_m    # [V,R]
        p_fit = a["task_init_req"][i][None, :]
        if require_freed_covers:
            fit_at = le_fits(p_fit, prefix_incl, thr, sm) & elig_v
            fit_now = jnp.zeros(N, dtype=bool)
        else:
            fit_at = le_fits(p_fit, future[v_node] + prefix_incl,
                             thr, sm) & elig_v
            fit_now = le_fits(p_fit, future, thr, sm)
        # minimal victim prefix per node ("cheapest-first until it fits")
        cut = jax.ops.segment_min(jnp.where(fit_at, vidx, V), v_node,
                                  num_segments=N)                    # [N]
        # a node is only considered when it holds >= 1 eligible victim
        # (validate_victims errs on an empty victim list)
        has_v = jax.ops.segment_max(elig_v.astype(jnp.int32), v_node,
                                    num_segments=N) > 0
        node_ok = has_v & (fit_now | (cut < V)) & sig_feas[i] & active
        got = jnp.any(node_ok)
        choice = jnp.argmax(
            jnp.where(node_ok, score_all[i], NEG)).astype(jnp.int32)
        c = jnp.where(got, choice, 0)

        ev = (elig_v & (v_node == c) & (vidx <= cut[c])
              & got & ~fit_now[c])
        freed = jnp.sum(v_req * ev[:, None], axis=0)
        # evictions raise the node's future idle; the pipelined preemptor
        # holds it back down (node_info.go:57-59 FutureIdle accounting)
        delta = jnp.where(got, freed - a["task_req"][i], 0.0)
        future = future.at[c].add(delta)
        alive = alive & ~ev
        evby = jnp.where(ev, i, evby)
        assigned = assigned.at[i].set(jnp.where(got, choice, -1))
        jalloc = jalloc.at[jidx].add(got.astype(jnp.int32))
        return (future, alive, evby, assigned, jalloc, cur_job,
                s_future, s_alive, s_evby, s_assigned), None

    init_assigned = jnp.full((T,), -1, jnp.int32)
    init_evby = jnp.full((V,), -1, jnp.int32)
    init_jalloc = jnp.zeros(a["job_min"].shape[0], jnp.int32)
    init = (future0, v_valid, init_evby, init_assigned, init_jalloc,
            a["task_job"][0],
            future0, v_valid, init_evby, init_assigned)
    carry, _ = jax.lax.scan(step, init, jnp.arange(T))
    (future, alive, evby, assigned, jalloc, cur_job,
     s_future, s_alive, s_evby, s_assigned) = carry
    future, alive, evby, assigned, jalloc = finalize(
        (future, alive, evby, assigned, jalloc,
         s_future, s_alive, s_evby, s_assigned), cur_job)
    return EvictResult(assigned=assigned, evicted_by=evby, job_placed=jalloc)
