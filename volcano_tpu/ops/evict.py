"""Eviction solve: batched preempt/reclaim victim selection on TPU.

Replaces the reference's per-preemptor Python/Go victim loops
(actions/preempt/preempt.go:186-262, actions/reclaim/reclaim.go:40-192) with
one jitted lax.scan over preemptor tasks:

- victims are flattened once, sorted by (node, cheapest-first) — the order
  the reference pops its per-node victim priority queue in;
- per step, each node's minimal victim prefix that makes the preemptor fit
  is found with segment prefix-sums ("evict cheapest-first until FutureIdle
  fits", preempt.go:219-240 / "until the request is covered",
  reclaim.go:91-100) — [V,R] cumsums, no host round-trips;
- the preemptor pipelines onto the best feasible node (score order, like the
  host loop's node_order_fn sort) and the chosen victims die for later steps;
- preempt's gang atomicity (Statement commit iff JobPipelined) runs as a
  job-boundary revert, exactly like solve_allocate_sequential's.

Accepted greedy-order deviations vs the host oracle (documented contract):
plugin eligibility (drf share deltas, proportion deserved) is frozen at
solve start rather than re-evaluated after every eviction, and claimer
queues are visited in snapshot order rather than re-sorted per placement.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .solver import (
    COMPACT_UNAVAILABLE, NEG, _segment_prefix, le_fits, score_matrix,
)


class EvictResult(NamedTuple):
    assigned: jnp.ndarray    # [T] int32: node index the task pipelines on, or -1
    evicted_by: jnp.ndarray  # [V] int32: claimer JOB index, or -1
    job_placed: jnp.ndarray  # [J] int32: pipelined placements per job
    compact: jnp.ndarray = None  # [T+V] int16: assigned ++ evicted_by —
                                 # one readback instead of two round trips;
                                 # sentinel-filled when indices overflow


def _evict_compact(assigned, evby, n_nodes: int, n_jobs: int):
    if max(n_nodes, n_jobs) >= (1 << 15):
        # indices don't fit int16: sentinel so decode fails loudly
        return jnp.full(assigned.shape[0] + evby.shape[0],
                        COMPACT_UNAVAILABLE, jnp.int16)
    return jnp.concatenate([assigned, evby]).astype(jnp.int16)


def decode_evict_compact(compact, n_tasks: int):
    """host-side: compact int16 -> (assigned [T], evicted_by [V]) int32.
    Raises when the solve emitted the overflow sentinel — read
    res.assigned / res.evicted_by instead."""
    import numpy as np
    c = np.asarray(compact).astype(np.int32)
    if c.size and c[0] == COMPACT_UNAVAILABLE:
        raise ValueError(
            "compact evict result unavailable (node/job count exceeds the "
            "int16 packing); read res.assigned / res.evicted_by instead")
    return c[:n_tasks], c[n_tasks:]


@functools.partial(jax.jit, static_argnames=(
    "score_families", "require_freed_covers", "allow_revert", "stop_at_need"))
def solve_evict(arrays: Dict[str, jnp.ndarray],
                victims: Dict[str, jnp.ndarray],
                score_params: Dict[str, jnp.ndarray],
                score_families: Tuple[str, ...] = ("kube",),
                require_freed_covers: bool = False,
                allow_revert: bool = True,
                stop_at_need: bool = True) -> EvictResult:
    """Scan preemptor tasks in (queue, job, task) rank order.

    arrays: a flatten of the *pending preemptor tasks* (ops.flatten_snapshot).
    victims: v_req [V,R] accounting resreq sorted by (node, cheapest-first);
      v_node [V] int32; v_valid [V] bool; elig [J,V] bool per preemptor job
      (tier-intersected Preemptable/Reclaimable verdicts + queue scoping);
      job_need [J] int32 pipelines still needed for JobPipelined.

    require_freed_covers: reclaim semantics — the freed victim resources
      alone must cover the claimer's request (reclaim.go:91-101), vs preempt
      where FutureIdle + freed must fit (preempt.go:219-240).
    allow_revert / stop_at_need: preempt's gang statement semantics; off for
      reclaim (evictions are immediate, jobs aren't capped at min).
    """
    a = arrays
    v_req = victims["v_req"]
    v_node = victims["v_node"]
    v_valid = victims["v_valid"]
    elig = victims["elig"]
    need = victims["job_need"]
    T = a["task_init_req"].shape[0]
    N = a["node_idle"].shape[0]
    V = v_req.shape[0]
    thr = a["thresholds"]
    sm = a["scalar_dim_mask"]
    sig_feas = a["sig_masks"][a["task_sig"]] & a["node_valid"][None, :]
    future0 = a["node_idle"] + a["node_extra_future"]
    # node ordering scores, frozen at solve start: one [T,N] matmul batch
    score_all = score_matrix(a["task_init_req"], future0, a["node_used"],
                             a["node_alloc"], score_params, score_families)
    seg_start = jnp.concatenate(
        [jnp.array([True]), v_node[1:] != v_node[:-1]])
    vidx = jnp.arange(V)

    def finalize(st, jidx):
        """Job boundary: revert this job's evictions and placements unless it
        reached JobPipelined (Statement.Discard, preempt.go:252-257)."""
        (future, alive, evby, assigned, jalloc,
         s_future, s_alive, s_evby, s_assigned) = st
        if not allow_revert:
            return future, alive, evby, assigned, jalloc
        done = jalloc[jidx] >= need[jidx]
        future = jnp.where(done, future, s_future)
        alive = jnp.where(done, alive, s_alive)
        evby = jnp.where(done, evby, s_evby)
        assigned = jnp.where(done, assigned, s_assigned)
        jalloc = jnp.where(done, jalloc, jalloc.at[jidx].set(0))
        return future, alive, evby, assigned, jalloc

    def step(carry, i):
        (future, alive, evby, assigned, jalloc, cur_job,
         s_future, s_alive, s_evby, s_assigned) = carry
        jidx = a["task_job"][i]
        boundary = jidx != cur_job

        def at_boundary(args):
            future, alive, evby, assigned, jalloc = finalize(args, cur_job)
            # fresh snapshots for the job now starting
            return (future, alive, evby, assigned, jalloc,
                    future, alive, evby, assigned)

        (future, alive, evby, assigned, jalloc,
         s_future, s_alive, s_evby, s_assigned) = jax.lax.cond(
            boundary, at_boundary, lambda args: args,
            (future, alive, evby, assigned, jalloc,
             s_future, s_alive, s_evby, s_assigned))
        cur_job = jidx

        active = a["task_valid"][i]
        if stop_at_need:
            # a job stops preempting once pipelined (preempt.go:200-207)
            active = active & (jalloc[jidx] < need[jidx])

        elig_v = elig[jidx] & alive & v_valid
        vreq_m = v_req * elig_v[:, None]
        prefix_incl = _segment_prefix(vreq_m, seg_start) + vreq_m    # [V,R]
        p_fit = a["task_init_req"][i][None, :]
        if require_freed_covers:
            fit_at = le_fits(p_fit, prefix_incl, thr, sm) & elig_v
            fit_now = jnp.zeros(N, dtype=bool)
        else:
            fit_at = le_fits(p_fit, future[v_node] + prefix_incl,
                             thr, sm) & elig_v
            fit_now = le_fits(p_fit, future, thr, sm)
        # minimal victim prefix per node ("cheapest-first until it fits")
        cut = jax.ops.segment_min(jnp.where(fit_at, vidx, V), v_node,
                                  num_segments=N)                    # [N]
        # a node is only considered when it holds >= 1 eligible victim
        # (validate_victims errs on an empty victim list)
        has_v = jax.ops.segment_max(elig_v.astype(jnp.int32), v_node,
                                    num_segments=N) > 0
        node_ok = has_v & (fit_now | (cut < V)) & sig_feas[i] & active
        got = jnp.any(node_ok)
        choice = jnp.argmax(
            jnp.where(node_ok, score_all[i], NEG)).astype(jnp.int32)
        c = jnp.where(got, choice, 0)

        ev = (elig_v & (v_node == c) & (vidx <= cut[c])
              & got & ~fit_now[c])
        freed = jnp.sum(v_req * ev[:, None], axis=0)
        # evictions raise the node's future idle; the pipelined preemptor
        # holds it back down (node_info.go:57-59 FutureIdle accounting)
        delta = jnp.where(got, freed - a["task_req"][i], 0.0)
        future = future.at[c].add(delta)
        alive = alive & ~ev
        evby = jnp.where(ev, jidx, evby)
        assigned = assigned.at[i].set(jnp.where(got, choice, -1))
        jalloc = jalloc.at[jidx].add(got.astype(jnp.int32))
        return (future, alive, evby, assigned, jalloc, cur_job,
                s_future, s_alive, s_evby, s_assigned), None

    init_assigned = jnp.full((T,), -1, jnp.int32)
    init_evby = jnp.full((V,), -1, jnp.int32)
    init_jalloc = jnp.zeros(a["job_min"].shape[0], jnp.int32)
    init = (future0, v_valid, init_evby, init_assigned, init_jalloc,
            a["task_job"][0],
            future0, v_valid, init_evby, init_assigned)
    carry, _ = jax.lax.scan(step, init, jnp.arange(T))
    (future, alive, evby, assigned, jalloc, cur_job,
     s_future, s_alive, s_evby, s_assigned) = carry
    future, alive, evby, assigned, jalloc = finalize(
        (future, alive, evby, assigned, jalloc,
         s_future, s_alive, s_evby, s_assigned), cur_job)
    return EvictResult(assigned=assigned, evicted_by=evby, job_placed=jalloc,
                       compact=_evict_compact(assigned, evby, N,
                                              need.shape[0]))


def absorb_counts(r, r_fit, sig, base, ptot, has_v, feas_n, thr, sm,
                  t_cap: float):
    """Per-node claimer-absorption counts for one uniform job: (m_all,
    f_n, cap_extra) where f_n = claimers fitting with NO eviction, m_all =
    max with all eligible victims freed, cap_extra = slots costing
    evictions. Shared by the single-device and mesh-sharded kernels —
    floor + one-step le_fits-validated backoff, so the chosen count
    always fits and a victim cut always exists."""

    def fits_m(mm, av):
        return le_fits(mm[:, None] * r_fit[None, :], av, thr, sm,
                       ignore_req=r[None, :])

    def validated(av):
        per_dim = jnp.where(sig[None, :],
                            jnp.floor(av / jnp.maximum(r, 1e-9)),
                            jnp.inf)
        m = jnp.min(per_dim, axis=1)
        m = jnp.clip(jnp.nan_to_num(m, posinf=t_cap), 0.0, t_cap)
        back = jnp.maximum(m - 1.0, 0.0)
        return jnp.where(fits_m(m, av), m,
                         jnp.where(fits_m(back, av), back, 0.0))

    avail = base + ptot
    m = jnp.where(feas_n & has_v, validated(avail), 0.0)
    f_n = jnp.where(feas_n, validated(base), 0.0)
    m_all = jnp.where(has_v, jnp.maximum(m, f_n), f_n)
    return m_all, f_n, jnp.maximum(m_all - f_n, 0.0)


def spread_counts(count, score_j, m_all, f_all, cap_extra):
    """Eviction-minimal spread of `count` claimers over nodes: fill free
    capacity first in score order, then waterfill the remainder evenly
    across nodes (trimming the surplus from the lowest-scoring at-level
    nodes). Returns (c [N] int32, order [N], cum [N] float32) — order/cum
    drive the claimer-position -> node mapping. Pure [N]-vector math, so
    the sharded kernel runs it replicated on gathered vectors."""
    N = m_all.shape[0]
    order = jnp.argsort(-score_j)
    f_o = f_all[order]
    cum_f = jnp.cumsum(f_o)
    c_free_o = jnp.clip(count.astype(jnp.float32) - (cum_f - f_o),
                        0.0, f_o)
    c_free = jnp.zeros(N, jnp.float32).at[order].set(c_free_o)
    D = jnp.maximum(count.astype(jnp.float32) - jnp.sum(c_free), 0.0)
    # waterfill level l* = smallest l with sum(min(cap_extra, l)) >= D
    srt = jnp.sort(cap_extra)
    csum = jnp.cumsum(srt)
    S = csum + srt * (N - 1 - jnp.arange(N, dtype=jnp.float32))
    found = jnp.any(S >= D)
    i0 = jnp.argmax(S >= D)
    csum_prev = jnp.where(i0 > 0, csum[jnp.maximum(i0 - 1, 0)], 0.0)
    seg = jnp.maximum((N - i0).astype(jnp.float32), 1.0)
    lvl = jnp.ceil((D - csum_prev) / seg)
    lvl = jnp.where(found, jnp.maximum(lvl, 0.0),
                    jnp.max(cap_extra, initial=0.0))
    c_extra = jnp.minimum(cap_extra, lvl)
    surplus = jnp.maximum(jnp.sum(c_extra) - D, 0.0)
    at_level = (c_extra >= lvl) & (lvl > 0)
    trim_order = jnp.argsort(jnp.where(at_level, score_j, jnp.inf))
    trim_pos = jnp.zeros(N, jnp.int32).at[trim_order].set(
        jnp.arange(N, dtype=jnp.int32))
    c_extra = c_extra - (at_level
                         & (trim_pos < surplus)).astype(jnp.float32)
    c = (c_free + c_extra).astype(jnp.int32)
    cum = jnp.cumsum(c[order]).astype(jnp.float32)
    return c, order, cum


def pack_victim_arrays(arr, victims, n_claim: int) -> Dict[str, np.ndarray]:
    """Build the solve_evict_uniform victim/job arrays for the common
    single-claiming-gang shape (job slot 0 claims ``n_claim`` uniform
    tasks; every ``victims`` TaskInfo is eligible). Owns the varrays
    contract in ONE place — the bench, the multichip dryrun and the suite
    all feed the kernel through it."""
    from .arrays import bucket

    node_index = {n.name: i for i, n in enumerate(arr.nodes_list)}
    ordered = sorted(victims, key=lambda t: node_index[t.node_name])
    V = bucket(max(len(ordered), 1))
    J = arr.job_min.shape[0]
    R = arr.R
    v_req = np.zeros((V, R), np.float32)
    v_node = np.zeros(V, np.int32)
    v_valid = np.zeros(V, bool)
    for i, t in enumerate(ordered):
        v_req[i] = t.resreq.to_vector(arr.vocab)
        v_node[i] = node_index[t.node_name]
        v_valid[i] = True
    elig = np.zeros((J, V), bool)
    elig[0, :len(ordered)] = True
    need = np.zeros(J, np.int32)
    need[0] = n_claim
    job_req = np.zeros((J, R), np.float32)
    job_req[0] = arr.task_init_req[0]
    job_acct = np.zeros((J, R), np.float32)
    job_acct[0] = arr.task_req[0]
    job_count = np.zeros(J, np.int32)
    job_count[0] = n_claim
    return {"v_req": v_req, "v_node": v_node, "v_valid": v_valid,
            "elig": elig, "job_need": need, "job_req": job_req,
            "job_acct": job_acct, "job_count": job_count}


@functools.partial(jax.jit, static_argnames=(
    "score_families", "require_freed_covers", "stop_at_need"))
def solve_evict_uniform(arrays: Dict[str, jnp.ndarray],
                        victims: Dict[str, jnp.ndarray],
                        score_params: Dict[str, jnp.ndarray],
                        score_families: Tuple[str, ...] = ("kube",),
                        require_freed_covers: bool = False,
                        stop_at_need: bool = True) -> EvictResult:
    """Per-JOB closed-form eviction solve for uniform claimers.

    When every pending claimer of a job has the same request (the gang
    case — BASELINE config #4 is one 1k-task gang), the whole job places
    in one step: per node, the candidate count floor((future +
    total-freeable) / request) is validated by le_fits itself (one-step
    backoff, zero fallback — the same rule as every other fit check, so
    the chosen count always fits and a victim cut always exists);
    claimers spread across nodes in score order; the minimal
    cheapest-first victim prefix covering each node's count is evicted.
    Gang all-or-nothing is exact — a job whose total placeable count
    misses its need places (and evicts) NOTHING, so no revert pass
    exists. O(jobs) scan steps instead of O(claimers), ~60x fewer for
    config #4.

    PREEMPT ONLY: reclaim's per-claimer coverage rule (each reclaimer's
    own victim prefix must cover its full request, reclaim.go:91-101) is
    not a per-node divisibility, so reclaim stays on the per-task scan
    kernel (require_freed_covers is accepted for kernel-level tests only).

    victims: as solve_evict, plus job_req [J,R] (the per-job uniform FIT
    request / init_resreq), job_acct [J,R] (the uniform accounting resreq
    debited from future, node_info.go AddTask), and job_count [J].
    """
    a = arrays
    v_req = victims["v_req"]
    v_node = victims["v_node"]
    v_valid = victims["v_valid"]
    elig = victims["elig"]
    need = victims["job_need"]
    job_req = victims["job_req"]          # [J,R] fit request
    job_acct = victims["job_acct"]        # [J,R] accounting request
    job_count = victims["job_count"]      # [J]
    T = a["task_init_req"].shape[0]
    N = a["node_idle"].shape[0]
    V = v_req.shape[0]
    J = a["job_min"].shape[0]
    thr = a["thresholds"]
    sm = a["scalar_dim_mask"]
    future0 = a["node_idle"] + a["node_extra_future"]
    # requests are uniform per job: score [J,N] directly instead of [T,N]
    job_score = score_matrix(job_req, future0, a["node_used"],
                             a["node_alloc"], score_params, score_families)
    seg_start = jnp.concatenate(
        [jnp.array([True]), v_node[1:] != v_node[:-1]])
    vidx = jnp.arange(V)
    # per-job node feasibility mask (claimers of one job share a signature
    # in the uniform case; take the AND over the job's tasks to stay safe)
    sig_feas_t = a["sig_masks"][a["task_sig"]] | ~a["task_valid"][:, None]
    job_feas = jnp.ones((J, N), jnp.int32).at[a["task_job"]].min(
        sig_feas_t.astype(jnp.int32)) > 0
    # position of each task within its job (contiguous grouping)
    first_task = jnp.full((J,), T - 1, jnp.int32).at[
        a["task_job"]].min(jnp.arange(T, dtype=jnp.int32))
    task_pos = jnp.arange(T, dtype=jnp.int32) - first_task[a["task_job"]]

    def step(carry, j):
        future, alive, evby, assigned, jalloc = carry
        r = job_req[j]                                             # [R]
        # per-dim significance mirrors le_fits' per-task rule: scalar dims
        # requesting <= 10 milli are ignored for FIT (r_fit zeroed) but
        # still debited for accounting, like the per-task kernel
        sig = jnp.where(sm, r > 10.0, r > 0.0)                     # [R]
        r_fit = jnp.where(sig, r, 0.0)
        count = (jnp.minimum(job_count[j], need[j]) if stop_at_need
                 else job_count[j])
        active = a["job_valid"][j] & (count > 0)

        elig_v = elig[j] & alive & v_valid
        vreq_m = v_req * elig_v[:, None]
        prefix_incl = _segment_prefix(vreq_m, seg_start) + vreq_m  # [V,R]
        ptot = jax.ops.segment_sum(vreq_m, v_node, num_segments=N)  # [N,R]
        has_v = jax.ops.segment_max(
            elig_v.astype(jnp.int32), v_node, num_segments=N) > 0
        base = jnp.zeros_like(future) if require_freed_covers else future
        # per-node absorption counts (free-capacity slots included —
        # victimless feasible nodes count: eviction minimality means
        # spending idle capacity before killing anything)
        feas_n = job_feas[j] & a["node_valid"]
        m_all, f_n, cap_extra = absorb_counts(
            r, r_fit, sig, base, ptot, has_v, feas_n, thr, sm, float(T))

        total = jnp.sum(m_all).astype(jnp.int32)
        # gang: need `need[j]` pipelines; if unreachable place nothing
        satisfied = (total >= need[j]) if stop_at_need else jnp.bool_(True)
        do = active & satisfied & (total > 0)
        count = jnp.where(do, jnp.minimum(count, total), 0)

        # eviction-minimal spread (preempt.go:219-240 evicts the cheapest
        # prefix per preemptor; the batched equivalent fills free capacity
        # first, then waterfills the remainder evenly so no node
        # over-evicts while another sits on idle victims)
        score_j = jnp.where(m_all > 0, job_score[j], NEG)
        c, order, cum = spread_counts(count, score_j, m_all, f_n,
                                      cap_extra)

        # task -> node: claimer position p lands on the node where the
        # score-ordered cumulative count first exceeds p
        is_mine = (a["task_job"] == j) & a["task_valid"]
        p = task_pos
        node_for_p = order[jnp.clip(
            jnp.searchsorted(cum, p.astype(cum.dtype), side="right"),
            0, N - 1)]
        placed_t = is_mine & (p < count)
        assigned = jnp.where(placed_t, node_for_p.astype(jnp.int32),
                             assigned)

        # minimal victim prefix per node covering c_n * r beyond future.
        # demand_fit drops the insignificant dims (same rule as `m` above,
        # else cut could stay V and mass-evict); accounting debits the
        # RUNNING request (node_info.go AddTask subtracts Resreq), like
        # the per-task kernel's `freed - task_req[i]`
        demand_fit = c.astype(jnp.float32)[:, None] * r_fit[None, :]
        demand_acct = (c.astype(jnp.float32)[:, None]
                       * job_acct[j][None, :])
        fit_now_n = le_fits(demand_fit, base, thr, sm,
                            ignore_req=demand_fit)
        need_evict_n = (c > 0) & ~fit_now_n
        fit_at = le_fits(demand_fit[v_node], base[v_node] + prefix_incl,
                         thr, sm, ignore_req=demand_fit[v_node]) & elig_v
        cut = jax.ops.segment_min(jnp.where(fit_at, vidx, V), v_node,
                                  num_segments=N)
        # cut < V is guaranteed by the conservative m; the guard keeps a
        # never-satisfiable fit from mass-evicting the whole node
        ev = (elig_v & need_evict_n[v_node] & (vidx <= cut[v_node])
              & (cut[v_node] < V))
        freed = jax.ops.segment_sum(v_req * ev[:, None], v_node,
                                    num_segments=N)
        future = future + freed - demand_acct
        alive = alive & ~ev
        evby = jnp.where(ev, j, evby)
        jalloc = jalloc.at[j].add(count)
        return (future, alive, evby, assigned, jalloc), None

    init = (future0, v_valid, jnp.full((V,), -1, jnp.int32),
            jnp.full((T,), -1, jnp.int32), jnp.zeros(J, jnp.int32))
    carry, _ = jax.lax.scan(step, init, jnp.arange(J))
    future, alive, evby, assigned, jalloc = carry
    return EvictResult(assigned=assigned, evicted_by=evby,
                       job_placed=jalloc,
                       compact=_evict_compact(assigned, evby, N, J))
