"""Event-sourced session ordering: the OrderCache.

The allocate action's collection pass re-derives the namespace -> queue ->
job -> task order from scratch every cycle (reference allocate.go:61-189):
filter every job, evaluate every composite order key, sort every queue's
jobs and every job's pending list. After PR 11 event-sourced the flatten,
that pass was the last cycle-start host cost scaling with cluster size
instead of change volume — at 10k pending tasks across 1k jobs it re-keys
and re-sorts everything even when three watch events arrived since the
last solve.

The OrderCache keeps the ordering *inputs* warm across sessions, fed by
the same typed watch-event deltas that drive the FlattenCache ledger
(SchedulerCache._feed_flatten: watch hooks + the version-gated
snapshot-clone seam catch-all) plus the enqueue action's in-session phase
flips:

- per job: an eligibility record (the _ordered_jobs filters), the
  composite job-order key (session.full_order_key), and the pending task
  list already sorted by the full task-order key;
- per (namespace, queue): the eligible jobs as a bisect-maintained sorted
  index of (key, uid) pairs.

At cycle start only event-dirty jobs are re-filtered / re-keyed /
re-sorted and re-placed in their queue index; the final namespace/queue
interleave then runs as a flat walk over the sorted indexes with the
LIVE queue-order / overused / namespace-order dispatchers evaluated once
per queue per cycle — valid because solver-mode collection happens before
any session mutation, so those orders are frozen for its duration
(exactly the contract the keyed job queues already rely on,
actions/allocate._ordered_jobs). A cycle with zero deltas reuses the
previous walk result object outright.

Consistency epoch, PR-11 discipline: feed_event counts deltas observed
vs deltas marked; a dropped or duplicated delivery (the ``order_event``/
``order_event_dup`` fault points) skews the counters and the next collect
detects it and falls back to the full sort, which trusts nothing.
Anything structural degrades the same way with a typed reason:

- ``comparator_only``  — some active order plugin registered no key
  extractor; the cache stands down and the caller runs the live
  comparator walk (marks keep accruing, so a later keyed cycle resumes
  incrementally);
- ``conf_reload``      — a hot-reload changed the active order-provider
  set (plugin added/removed/moved tiers);
- ``key_context``      — a provider's declared key context moved (e.g.
  drf's cluster total after a node respec, a priority-class edit):
  live-share-dependent keys are only trusted while their context holds;
- ``session_mutations``— an earlier action in this cycle mutated the
  session's clones outside the ledger's sight (preempt-before-allocate
  confs); the full sort reads the post-mutation state;
- ``queue_membership`` — a queue event changed the queue set, which can
  flip eligibility of jobs the ledger never marked;
- ``epoch_mismatch``   — the drop/dup case above;
- ``cold_start`` / ``membership_drift`` / ``index_drift`` — first cycle
  and the defensive invariants.

Key contract: an order-key extractor registered via
``Session.add_order_key_fn`` must be a pure function of the item's own
(version-gated) state; a key that also reads cluster-wide state must
register a context fn via ``Session.add_order_key_context_fn`` whose
value changes whenever that outside state changes (drf registers the
cluster total, priority the priority-class table). Order identity is
asserted element-for-element against the live comparator walk across a
seeded churn matrix by tests/test_order_events.py.
"""

from __future__ import annotations

import threading
from bisect import bisect_left, insort
from functools import cmp_to_key
from typing import Dict, List, Optional, Tuple

from ..api import TaskStatus
from ..models import PodGroupPhase


class _Decline(Exception):
    """Internal: abandon the event path for this cycle, typed reason."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


def _task_ct(t):
    return t.pod.creation_timestamp


class OrderCache:
    """See module docstring. One instance lives on the SchedulerCache
    (like the FlattenCache) and persists across sessions."""

    def __init__(self):
        # -- event ledger ---------------------------------------------------
        self._lock = threading.Lock()
        self._feed = 0          # deltas OBSERVED (pre-drop)
        self._seq = 0           # deltas actually marked
        self._prev_feed = 0     # both counters as of the last consume
        self._prev_seq = 0
        self._dirty_jobs: set = set()
        self._queue_event = False   # queue add/update/delete seen
        self._broken: Optional[str] = None
        # -- keyed order state ----------------------------------------------
        self._entries: Dict[str, dict] = {}       # job uid -> entry
        #: ns -> {queue name -> sorted [(job full key, uid), ...]}
        self._ns_queues: Dict[str, Dict[str, list]] = {}
        self._queue_names: frozenset = frozenset()
        self._sig: Optional[tuple] = None    # active order-provider tuple
        self._ctx: Optional[tuple] = None    # provider context values
        self._primed = False
        self._last_walk: Optional[list] = None
        self._ctx_memo: Optional[tuple] = None  # (session, ok) for reuse
        # -- observability --------------------------------------------------
        self.last_mode = "none"   # reuse | event | full | legacy
        self.last_reason: Optional[str] = None
        self.last_entries_patched = 0
        self.fallback_counts: Dict[str, int] = {}
        #: cumulative count of actual list sorts (task lists + queue
        #: indexes) — the quiet-cluster regression counter
        self.sorts_performed = 0
        self.walks_reused = 0

    # -- event feed ---------------------------------------------------------

    def feed_event(self, kind: str, event: str, job: Optional[str] = None,
                   node: Optional[str] = None) -> None:
        """Record one typed mirror delta (same call shape as
        FlattenCache.feed_event; the SchedulerCache forwards every delta to
        both ledgers). Node deltas are counted but never dirty a job —
        node state reaches ordering only through declared key contexts."""
        from ..resilience.faultinject import faults
        with self._lock:
            self._feed += 1
        try:
            # chaos seam: an armed `order_event` drops this delta exactly
            # as a torn feed would — observed counter moved, mark never
            # lands, epoch check catches the skew at the next collect
            faults.fire("order_event")
        except Exception:  # noqa: BLE001 — the drop IS the fault
            return
        self._mark(kind, event, job, node)
        try:
            # `order_event_dup`: the same delta delivered twice
            faults.fire("order_event_dup")
        except Exception:  # noqa: BLE001
            self._mark(kind, event, job, node)

    def _mark(self, kind: str, event: str, job: Optional[str],
              node: Optional[str]) -> None:
        with self._lock:
            self._seq += 1
            if kind in ("pod", "job", "podgroup"):
                if job:
                    self._dirty_jobs.add(job)
            elif kind == "node":
                pass  # ordering reads nodes only via key contexts
            elif kind == "queue":
                # membership can flip eligibility of unmarked jobs;
                # validated against the live queue set at collect
                self._queue_event = True
            else:
                self._broken = f"unmapped:{kind}"

    def suppress(self, reason: str) -> None:
        """Decline the event path at the next collect with ``reason``."""
        with self._lock:
            self._broken = reason

    def _take(self) -> dict:
        with self._lock:
            return {
                "feed": self._feed, "seq": self._seq,
                "jobs": set(self._dirty_jobs),
                "queue_event": self._queue_event,
                "broken": self._broken,
            }

    def _consume(self, taken: dict) -> None:
        with self._lock:
            self._dirty_jobs -= taken["jobs"]
            if self._feed == taken["feed"]:
                # no concurrent marks: flags fully consumed; otherwise
                # leave them for the next cycle's validation
                self._queue_event = False
                self._broken = None
            self._prev_feed = taken["feed"]
            self._prev_seq = taken["seq"]

    # -- provider signature / key contexts ----------------------------------

    def _signature(self, ssn) -> Tuple[tuple, tuple]:
        """(active order-provider tuple, context values) for the job and
        task order registries. Providers without a context fn are trusted
        as pure functions of the (version-gated) item."""
        sig, ctx = [], []
        for registry in ("job_order_fns", "task_order_fns"):
            reg_ctx = ssn.order_key_context_fns.get(registry, {})
            for ti, name, _ in ssn._tier_fns(registry):
                sig.append((registry, ti, name))
                cfn = reg_ctx.get(name)
                if cfn is not None:
                    ctx.append(((registry, name), cfn()))
        return tuple(sig), tuple(ctx)

    # -- per-job entries ----------------------------------------------------

    def _entry(self, ssn, job, jobkey, taskkey) -> dict:
        """Eligibility + key + sorted pending list for one job — the exact
        filter sequence of actions/allocate._ordered_jobs and the exact
        task filter/sort of _pending_tasks."""
        pending_map = job.task_status_index.get(TaskStatus.PENDING)
        eligible = bool(pending_map)
        if eligible and (job.pod_group is None
                         or job.pod_group.status.phase
                         == PodGroupPhase.PENDING):
            eligible = False
        if eligible:
            vr = ssn.job_valid(job)
            if vr is not None and not vr.passed:
                eligible = False
        if eligible and job.queue not in ssn.queues:
            eligible = False
        if not eligible:
            return {"ver": job.flat_version, "eligible": False}
        tasks = [t for t in pending_map.values()
                 if not t.resreq.is_empty()]  # BestEffort is backfill's
        if len(tasks) > 1:
            tasks.sort(key=taskkey)
            self.sorts_performed += 1
        return {"ver": job.flat_version, "eligible": True,
                "ns": job.namespace, "queue": job.queue,
                "key": jobkey(job), "tasks": tasks}

    def _index_insert(self, ent: dict, uid: str) -> None:
        lst = self._ns_queues.setdefault(
            ent["ns"], {}).setdefault(ent["queue"], [])
        insort(lst, (ent["key"], uid))

    def _index_remove(self, ent: dict, uid: str) -> None:
        lst = self._ns_queues.get(ent["ns"], {}).get(ent["queue"])
        item = (ent["key"], uid)
        if lst:
            i = bisect_left(lst, item)
            if i < len(lst) and lst[i] == item:
                del lst[i]
                return
        raise _Decline("index_drift")

    # -- the walk -----------------------------------------------------------

    def _walk(self, ssn) -> list:
        """namespace -> queue -> job interleave over the sorted indexes.
        Identical yield order to _ordered_jobs' heap walk because every
        dispatcher it consults (namespace_order_fn, queue_order_fn,
        overused, the job keys) is a strict total order frozen for the
        collection: the heap's pop-one-push-back loop degenerates to
        draining namespaces in namespace order, each namespace's
        non-overused queues in queue order, each queue's jobs in key
        order."""
        jobs = ssn.jobs
        entries = self._entries
        ns_items = [(ns, qmap) for ns, qmap in self._ns_queues.items()
                    if any(qmap.values())]
        if len(ns_items) > 1:
            def ns_cmp(a, b):
                if ssn.namespace_order_fn(a[0], b[0]):
                    return -1
                if ssn.namespace_order_fn(b[0], a[0]):
                    return 1
                return 0
            ns_items.sort(key=cmp_to_key(ns_cmp))
        out = []
        for _ns, qmap in ns_items:
            qis = []
            for qname, lst in qmap.items():
                if not lst:
                    continue
                qi = ssn.queues.get(qname)
                if qi is None:
                    # an entry's queue vanished without the queue-event
                    # revalidation catching it: don't guess, full sort
                    raise _Decline("queue_membership")
                if ssn.overused(qi):
                    continue
                qis.append(qi)
            if len(qis) > 1:
                def q_cmp(a, b):
                    if ssn.queue_order_fn(a, b):
                        return -1
                    if ssn.queue_order_fn(b, a):
                        return 1
                    return 0
                qis.sort(key=cmp_to_key(q_cmp))
            for qi in qis:
                for _key, uid in qmap[qi.name]:
                    job = jobs.get(uid)
                    if job is None:
                        raise _Decline("membership_drift")
                    out.append((job, entries[uid]["tasks"]))
        return out

    # -- cycle entry points -------------------------------------------------

    def collect(self, ssn) -> Optional[List[tuple]]:
        """The ordering pass: [(job, sorted pending tasks), ...] in the
        session's namespace/queue/job/task order, or None when the active
        conf is comparator-only and the caller must run the live walk.
        Consumes the ledger like FlattenCache's flatten (PR-11
        discipline); the result's task lists are cache-owned — callers
        must not mutate them (the allocate action hands them straight to
        the flatten, which makes the same demand)."""
        jobkey = ssn.full_order_key("job_order_fns")
        taskkey = ssn.full_order_key("task_order_fns", ct_of=_task_ct)
        if jobkey is None or taskkey is None:
            self._note("legacy", "comparator_only", 0)
            return None
        sig, ctx = self._signature(ssn)
        taken = self._take()
        result = None
        reason = None
        patched = 0
        if self._primed:
            try:
                result, mode, patched = self._collect_event(
                    ssn, taken, sig, ctx, jobkey, taskkey)
            except _Decline as d:
                reason = d.reason
        else:
            reason = "cold_start"
        if result is None:
            result = self._rebuild(ssn, jobkey, taskkey)
            mode = "full"
            patched = len(self._entries)
        self._consume(taken)
        self._primed = True
        self._sig, self._ctx = sig, ctx
        self._queue_names = frozenset(ssn.queues)
        self._last_walk = result
        self._note(mode, reason, patched)
        return result

    def _collect_event(self, ssn, taken, sig, ctx, jobkey, taskkey):
        if taken["broken"]:
            raise _Decline(taken["broken"])
        if getattr(ssn, "_mutation_ops", 0):
            # an earlier action already mutated the session's clones;
            # those deltas never reached this ledger
            raise _Decline("session_mutations")
        if sig != self._sig:
            raise _Decline("conf_reload")
        if ctx != self._ctx:
            raise _Decline("key_context")
        if (taken["feed"] - self._prev_feed) \
                != (taken["seq"] - self._prev_seq):
            # the consistency epoch: a delta was observed but never
            # marked (or marked twice) — the ledger cannot be trusted
            raise _Decline("epoch_mismatch")
        if taken["queue_event"]:
            if frozenset(ssn.queues) != self._queue_names:
                raise _Decline("queue_membership")
        if (taken["feed"] == self._prev_feed and not taken["jobs"]
                and self._last_walk is not None
                and len(self._entries) == len(ssn.jobs)
                and not ssn._tier_fns("namespace_order_fns")):
            # a genuinely quiet cycle: zero deltas of any kind since the
            # last collect, so every input to the walk (entries, queue
            # attrs, overuse) is unchanged — reuse the previous walk
            # object outright. Declined when namespace-order providers
            # are active: their inputs (resource quotas) are not part of
            # this ledger's feed.
            self.walks_reused += 1
            return self._last_walk, "reuse", 0
        entries = self._entries
        patched = 0
        for uid in taken["jobs"]:
            old = entries.pop(uid, None)
            if old is not None and old["eligible"]:
                self._index_remove(old, uid)
            job = ssn.jobs.get(uid)
            if job is None:
                continue  # departed (or not in this snapshot's job set)
            ent = self._entry(ssn, job, jobkey, taskkey)
            entries[uid] = ent
            if ent["eligible"]:
                self._index_insert(ent, uid)
            patched += 1
        if len(entries) != len(ssn.jobs):
            # a job entered/left the snapshot without a mark — the
            # catch-all seam should make this impossible; don't guess
            raise _Decline("membership_drift")
        return self._walk(ssn), "event", patched

    def _rebuild(self, ssn, jobkey, taskkey) -> list:
        """The full sort: recompute every entry and queue index from the
        live session — trusts nothing, same yield order as the live
        comparator walk."""
        entries: Dict[str, dict] = {}
        nsq: Dict[str, Dict[str, list]] = {}
        for uid, job in ssn.jobs.items():
            ent = self._entry(ssn, job, jobkey, taskkey)
            entries[uid] = ent
            if ent["eligible"]:
                nsq.setdefault(ent["ns"], {}).setdefault(
                    ent["queue"], []).append((ent["key"], uid))
        for qmap in nsq.values():
            for lst in qmap.values():
                if len(lst) > 1:
                    lst.sort()
                    self.sorts_performed += 1
        self._entries = entries
        self._ns_queues = nsq
        return self._walk(ssn)

    def _note(self, mode: str, reason: Optional[str],
              patched: int) -> None:
        self.last_mode = mode
        self.last_reason = reason
        self.last_entries_patched = patched
        if reason is not None:
            self.fallback_counts[reason] = \
                self.fallback_counts.get(reason, 0) + 1

    def invalidate(self, reason: str = "invalidated") -> None:
        """Hard reset after an unexpected error: drop every cached
        structure and re-baseline the ledger; the next keyed collect
        rebuilds from scratch (``cold_start``). The caller's degradation
        contract: an ordering-cache bug costs one comparator-walk cycle,
        never a contained allocate action."""
        with self._lock:
            self._dirty_jobs.clear()
            self._prev_feed = self._feed
            self._prev_seq = self._seq
            self._queue_event = False
            self._broken = None
        self._primed = False
        self._entries = {}
        self._ns_queues = {}
        self._last_walk = None
        self._ctx_memo = None
        self._note("legacy", reason, 0)

    # -- shared pending-task lists ------------------------------------------

    def pending_tasks(self, ssn, job) -> Optional[list]:
        """A COPY of ``job``'s cached sorted pending list, or None when
        the entry is missing/stale or this session's task-order providers
        or contexts differ from the cache's. Version-gated on the session
        clone's flat_version, so any mutation since the entry was cut
        (binds, evictions, watch deliveries) is an automatic miss — safe
        to call from any action at any point in the cycle (preempt/
        reclaim claimer collection, the host allocate loop)."""
        if not self._primed:
            return None
        memo = self._ctx_memo
        if memo is None or memo[0] is not ssn:
            ok = False
            if self._sig is not None and ssn.full_order_key(
                    "task_order_fns", ct_of=_task_ct) is not None:
                sig, ctx = self._signature(ssn)
                ok = sig == self._sig and ctx == self._ctx
            memo = (ssn, ok)
            self._ctx_memo = memo
        if not memo[1]:
            return None
        ent = self._entries.get(job.uid)
        if ent is None or not ent["eligible"] \
                or ent["ver"] != job.flat_version:
            return None
        return list(ent["tasks"])
