"""Allocate solvers: batched task x node constraint satisfaction on TPU.

Replaces the reference's per-task hot loop (actions/allocate/allocate.go:43-266
+ util/scheduler_helper.go PredicateNodes/PrioritizeNodes 16-goroutine fan-out)
with jitted whole-snapshot kernels:

- ``solve_allocate``      round-based parallel solver (the fast path): each
  round every unassigned task picks its best feasible node (scores are
  matmuls -> MXU), per-node admission happens by priority-ordered prefix
  sums, resources are debited with segment-sums, and a gang fixpoint loop
  reverts jobs that can't reach min_available (the Statement.Discard
  semantics, in-kernel). Converges in O(rounds) ~ contention, not O(tasks).

- ``solve_allocate_sequential``  lax.scan over tasks in priority order,
  reproducing the reference's sequential greedy semantics (allocation of
  task k is visible to task k+1, job-boundary gang revert) for parity tests.

Both run under jit with static padded shapes; all control flow is
lax.while_loop/scan — no host round-trips inside a solve.

Semantics notes (mirroring the Go data model):
- fit check uses the launch request (InitResreq <= Idle, LessEqual with
  per-dim thresholds: l < r + thr; scalar dims with request <= 10 milli are
  ignored) — resource_info.go LessEqual.
- accounting debits the running request (NodeInfo.AddTask subtracts Resreq).
- tasks that don't fit Idle anywhere may pipeline onto FutureIdle =
  Idle + Releasing - Pipelined (node_info.go:57-59).
- gang: a job commits only if ready_base + newly_allocated >= min_available;
  pipelined tasks do not count toward readiness (job_info.go:317-377).
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG = jnp.float32(-1e30)
BIG_KEY = jnp.int32(2**31 - 1)

#: scale-aware fit tolerance (float32 ulp compensation): the reference
#: compares in float64 with a 1-BYTE memory threshold
#: (resource_info.go:70-72), but this kernel's idle accounting subtracts
#: in float32, where one ulp at a 10-GiB node is ~1 KiB — an exact fit
#: can drift a few hundred bytes below the request and strand the last
#: placement the float64 reference makes. A few-ulp relative term keeps
#: exact fits feasible at any magnitude; at milli-CPU magnitudes it is
#: far below the 10-milli threshold, so only huge-magnitude dims
#: (memory) see it, and at worst it over-admits by ~5e-7 of a node.
REL_FIT_TOL = jnp.float32(5e-7)


class SolveResult(NamedTuple):
    assigned: jnp.ndarray   # [T] int32 node index or -1
    kind: jnp.ndarray       # [T] int32: 0 = allocate, 1 = pipeline, -1 = none
    job_ready: jnp.ndarray  # [J] bool: job committed (gang-satisfied)
    rounds: jnp.ndarray     # [] int32 diagnostic
    compact: jnp.ndarray = None  # [T] int16: node | (kind << 14), -1 = none
                                 # — the wire-cheap readback (decode with
                                 # decode_compact); assigned/kind stay for
                                 # in-kernel consumers and tests


COMPACT_KIND_SHIFT = 14        # node index < 2^14; kind bit above it
COMPACT_UNAVAILABLE = -2       # whole-array sentinel: N too large to pack


def _compact(assigned, kind, n_nodes: int):
    if n_nodes > (1 << COMPACT_KIND_SHIFT):
        # node indices don't fit 14 bits: emit a detectable sentinel so a
        # consumer that forgets the N guard fails loudly in decode_compact
        # instead of silently mis-decoding wrapped values
        return jnp.full(assigned.shape, COMPACT_UNAVAILABLE, jnp.int16)
    return jnp.where(
        assigned < 0, jnp.int16(-1),
        (assigned + kind * (1 << COMPACT_KIND_SHIFT)).astype(jnp.int16))


def decode_compact(compact):
    """host-side: compact int16 -> (assigned int32, kind int32)."""
    import numpy as np
    c = np.asarray(compact).astype(np.int32)
    if c.size and c[0] == COMPACT_UNAVAILABLE:
        raise ValueError(
            "compact result unavailable (node count exceeds the int16 "
            "packing); read res.assigned / res.kind instead")
    none = c < 0
    kind = np.where(none, -1, c >> COMPACT_KIND_SHIFT)
    assigned = np.where(none, -1, c & ((1 << COMPACT_KIND_SHIFT) - 1))
    return assigned, kind


# ---------------------------------------------------------------------------
# building blocks
# ---------------------------------------------------------------------------

def le_fits(lhs, avail, thr, scalar_mask, ignore_req=None):
    """Threshold-tolerant LessEqual reduced over the trailing resource axis
    (resource_info.go LessEqual): a dim fits iff lhs < avail + thr OR
    lhs <= avail — the <= disjunct keeps exact fits feasible, because at
    memory magnitudes the threshold vanishes in float32 (2^30 + 1 rounds to
    2^30). Scalar dims whose request (ignore_req, default lhs) is <= 10
    milli are ignored entirely. All inputs broadcast against [..., R].

    Single source of truth for the fit rule — the round solver, sequential
    solver, queue caps, and sharded admission all call this so a semantics
    tweak can't desynchronize them.
    """
    dim_ok = (lhs < avail + (thr + REL_FIT_TOL * jnp.abs(avail))) \
        | (lhs <= avail)
    req = lhs if ignore_req is None else ignore_req
    return jnp.all(dim_ok | (scalar_mask & (req <= 10.0)), axis=-1)


def fits_matrix(req, avail, thr, scalar_mask):
    """LessEqual(req, avail) per (task, node): [T,N] bool."""
    return le_fits(req[:, None, :], avail[None, :, :], thr, scalar_mask)


def score_matrix(init_req, idle, used, alloc, params,
                 families: Tuple[str, ...] = ("binpack", "kube")):
    """Plugin scoring families as dense linear algebra: [T,N] float32.

    binpack  (binpack.go:111-260):  100 * sum_r w_r (used_r+req_r)/alloc_r / sum_w
    least-requested (k8s scorer):   100 * mean_r (alloc-used-req)/alloc over cpu,mem
    most-requested:                 100 * mean_r (used+req)/alloc over cpu,mem
    balanced-allocation:            100 * (1 - |cpu_frac - mem_frac|)

    The per-task terms become [T,R] @ [R,N] matmuls (MXU); per-node terms are
    broadcast vectors. ``families`` is static so zero-weight families cost
    nothing (a binpack-only session skips the [T,N,2] fraction tensors).
    """
    inv_alloc = 1.0 / alloc                    # [N,R]
    score = jnp.zeros((init_req.shape[0], idle.shape[0]), jnp.float32)

    if "binpack" in families:
        w = params["binpack_res_weights"]      # [R]
        wsum = jnp.maximum(jnp.sum(w), 1e-9)
        # binpack: (sum_r req*(w/alloc) + sum_r used*w/alloc) * 100/sum_w.
        # The task term is an explicit per-dimension broadcast sum, NOT a
        # matmul: R is 2-4 (no MXU win) and jnp.dot's default matmul
        # precision is reduced on some backends, which would break bitwise
        # parity with the fused pallas kernel (exact f32 VPU arithmetic).
        R_ = init_req.shape[1]
        wial = w[None, :] * inv_alloc                              # [N,R]
        bp_node = jnp.sum(used * w[None, :] * inv_alloc, axis=-1)  # [N]
        bp_task = jnp.zeros((init_req.shape[0], idle.shape[0]),
                            jnp.float32)
        for r in range(R_):
            bp_task = bp_task + (init_req[:, r][:, None]
                                 * wial[:, r][None, :])
        score += (params["binpack_weight"]
                  * (bp_task + bp_node[None, :]) * (100.0 / wsum))

    if "kube" in families:
        # least/most requested + balanced use cpu(0), mem(1) only
        frac = ((used[None, :, 0:2] + init_req[:, None, 0:2])
                * inv_alloc[None, :, 0:2])                         # [T,N,2]
        least = jnp.mean(jnp.clip(1.0 - frac, 0.0, 1.0), axis=-1) * 100.0
        most = jnp.mean(jnp.clip(frac, 0.0, 1.0), axis=-1) * 100.0
        balanced = (1.0 - jnp.abs(frac[..., 0] - frac[..., 1])) * 100.0
        score += (params["least_req_weight"] * least
                  + params["most_req_weight"] * most
                  + params["balanced_weight"] * balanced)

    score += params["node_static"][None, :]
    return score


def water_fill_deserved(total, weight, cap, request, thr, max_iters: int):
    """Iterative weighted water-filling of per-queue deserved resources
    (proportion.go:137-197), vectorized over queues on device.

    total [R]; weight [Q] (0 = absent/padded queue); cap [Q,R] with +inf on
    uncapped dims; request [Q,R]. Each pass hands every unmet queue its
    weight-proportional slice of the remaining pool simultaneously (the
    reference's inner for-loop reads one `remaining` snapshot per pass, so
    the pass is order-free); queues clamp at capability or request and stop
    participating. Terminates when the pool is sub-threshold or all queues
    met — at most Q+1 passes (an all-unmet pass drains the pool).
    """

    def cond(s):
        deserved, meet, remaining, it = s
        tw = jnp.sum(jnp.where(meet, 0.0, weight))
        return (tw > 0) & jnp.any(remaining >= thr) & (it < max_iters)

    def body(s):
        deserved, meet, remaining, it = s
        tw = jnp.sum(jnp.where(meet, 0.0, weight))
        frac = jnp.where(meet, 0.0, weight) / jnp.maximum(tw, 1e-9)
        old = deserved
        grown = deserved + frac[:, None] * remaining[None, :]
        cap_viol = jnp.any(grown > cap, axis=1)
        req_less = jnp.all(request < grown, axis=1)
        clamped = jnp.where(
            cap_viol[:, None],
            jnp.minimum(jnp.minimum(grown, cap), request),
            jnp.where(req_less[:, None], jnp.minimum(grown, request), grown))
        deserved = jnp.where(meet[:, None], deserved, clamped)
        meet = meet | cap_viol | req_less
        remaining = jnp.maximum(
            remaining - jnp.sum(deserved - old, axis=0), 0.0)
        return deserved, meet, remaining, it + 1

    Q = weight.shape[0]
    init = (jnp.zeros_like(request), weight <= 0, total, jnp.int32(0))
    deserved, _, _, _ = jax.lax.while_loop(cond, body, init)
    return deserved


def drf_state(a, rank):
    """Shared prelude for in-kernel DRF ordering (single-device and
    mesh-sharded solvers): returns (jobres0, drf_rank, drf_cap). All the
    math is replicated-safe — shares are [J] reductions, ranks [T] sorts.

    drf_rank(jobres): dense per-task priority from live dominant shares
    (lower-share jobs first, original order within a job and among ties).
    drf_cap(eligible, jobres): progressive-filling headroom — per round a
    job may only grow its dominant share to (the minimum competing share)
    + one step, at least one task and at least 1/(8 x competing jobs), so
    a saturated cluster converges to equal shares in a handful of rounds
    (drf.go's per-placement job re-sort, in round-sized bites)."""
    T = a["task_rank"].shape[0]
    J = a["job_min"].shape[0]
    rank = a["task_rank"] if rank is None else rank
    first_rank = jnp.full((J,), T, jnp.int32).at[a["task_job"]].min(rank)
    within_rank = rank - first_rank[a["task_job"]]
    drf_total = jnp.maximum(a["drf_total"], 1e-9)
    incr_t = jnp.max(
        jnp.where(a["drf_total"][None, :] > 0.0,
                  a["task_req"] / drf_total[None, :], 0.0), axis=1)
    j_seg_start = jnp.concatenate(
        [jnp.array([True]), a["task_job"][1:] != a["task_job"][:-1]])

    def drf_share(jobres):
        share = jnp.max(
            jnp.where(a["drf_total"][None, :] > 0.0,
                      jobres / drf_total[None, :], 0.0), axis=1)     # [J]
        return jnp.where(a["job_valid"], share, jnp.inf)

    # static MAJOR key from the job-order providers preceding drf in the
    # tiers (priority/gang): live shares only break its ties, so a strict
    # priority never inverts under the share re-rank. Zeros when nothing
    # precedes drf (pure share order, the original behavior). .get():
    # hand-built array dicts (fuzz/bench) predate the key.
    prerank = a.get("job_drf_prerank")
    if prerank is None:
        prerank = jnp.zeros(J, jnp.int32)

    def drf_rank(jobres):
        order_j = jnp.lexsort((drf_share(jobres), prerank))
        job_pos = jnp.zeros(J, jnp.int32).at[order_j].set(
            jnp.arange(J, dtype=jnp.int32))
        order_t = jnp.lexsort((within_rank, job_pos[a["task_job"]]))
        return jnp.zeros(T, jnp.int32).at[order_t].set(
            jnp.arange(T, dtype=jnp.int32))

    def drf_cap(eligible, jobres):
        share = drf_share(jobres)
        elig_job = jnp.zeros(J, jnp.int32).at[a["task_job"]].max(
            eligible.astype(jnp.int32)) > 0
        n_elig = jnp.maximum(jnp.sum(elig_job), 1)
        # progressive filling competes WITHIN a prerank group: a
        # higher-priority job must not be throttled against (or yield
        # headroom to) lower-priority shares
        grp = jnp.clip(prerank, 0, J - 1)
        m_grp = jax.ops.segment_min(
            jnp.where(elig_job, share, jnp.inf), grp, num_segments=J)
        m = m_grp[grp]                                           # [J]
        max_incr = jnp.max(jnp.where(eligible, incr_t, 0.0))
        step = jnp.maximum(max_incr, 1.0 / (8.0 * n_elig))
        allowed = jnp.maximum(share, m) + step                   # [J]
        cum = _segment_prefix((incr_t * eligible)[:, None],
                              j_seg_start)[:, 0] + incr_t
        # absolute comparison (share + cum vs allowed): subtracting share
        # from allowed first loses a float32 ulp and starves exact steps
        return eligible & (share[a["task_job"]] + cum
                           <= allowed[a["task_job"]] + 1e-6)

    return a["job_drf_allocated"], drf_rank, drf_cap


def queue_cap_state(a, rank, thr, total, ease_unrequested: bool = True):
    """Shared prelude for in-kernel queue fair share (used by the
    single-device and mesh-sharded solvers — only the cluster `total`
    source differs): water-filled deserved, the task->queue map, and the
    static (queue, rank) sort for per-round prefix caps."""
    q = a["queue_weight"].shape[0]
    deserved = water_fill_deserved(
        total, a["queue_weight"], a["queue_capability"],
        a["queue_request"], thr, max_iters=q + 1)
    if ease_unrequested:
        # dims a queue never requested must not bind its cap: a queue
        # whose workloads don't use a dim should not be throttled at its
        # (meaningless) water-filled deserved there, so those dims are
        # replaced by +inf for the per-round caps. (One of two deliberate
        # strandings-avoidance improvements over the reference's any-dim
        # overused rule; see phase_rounds' overflow pass. Disabled by
        # work_conserving=False for strict reference parity.)
        deserved = jnp.where(a["queue_request"] > thr[None, :],
                             deserved, jnp.inf)
    task_queue = a["job_queue"][a["task_job"]]
    t = task_queue.shape[0]
    q_perm = jnp.argsort(task_queue * (t + 1) + rank)
    s_q = task_queue[q_perm]
    q_seg_start = jnp.concatenate(
        [jnp.array([True]), s_q[1:] != s_q[:-1]])
    return q, deserved, task_queue, q_perm, q_seg_start


def _queue_cap_mask(eligible, task_queue, req, qrem, thr, scalar_mask,
                    q_perm, q_seg_start, s_q=None, s_req_raw=None):
    """Per-round queue admission cap: among eligible tasks in (queue, rank)
    order, a task passes iff its queue's running prefix of *eligible*
    requests + its own request still fits the queue's remaining deserved
    (threshold-tolerant, like fits_matrix). Conservative like node prefix
    admission: a blocked task waits for the next round's recomputed
    remaining.

    q_perm/q_seg_start are the static (queue, rank) sort and its queue
    segment boundaries — task_queue and rank never change within a solve,
    so the sort is hoisted out of the round loop (one argsort per solve
    instead of one per round); only the eligibility mask varies here.
    s_q/s_req_raw are the sorted task_queue/req gathers — also static for
    a static q_perm, so callers hoist them too (live-DRF callers, whose
    q_perm changes per round, leave them None)."""
    T = req.shape[0]
    if s_q is None:
        s_q = task_queue[q_perm]
    if s_req_raw is None:
        s_req_raw = req[q_perm]
    s_act = eligible[q_perm]
    s_rem = qrem[s_q]
    # a task whose own request can never fit the queue's remaining deserve
    # must not hold budget in the prefix — the sequential reference only
    # charges the queue on actual placement, so a too-big task ahead in
    # rank order doesn't starve feasible tasks behind it
    s_fits_alone = le_fits(s_req_raw, s_rem, thr, scalar_mask,
                           ignore_req=s_req_raw) & s_act
    s_req = s_req_raw * s_fits_alone[:, None]
    prefix = _segment_prefix(s_req, q_seg_start)
    ok_sorted = le_fits(prefix + s_req, s_rem, thr, scalar_mask,
                        ignore_req=s_req) & s_fits_alone
    return jnp.zeros(T, dtype=bool).at[q_perm].set(ok_sorted)


def _segment_prefix(sorted_vals, seg_start_mask):
    """Exclusive prefix-sum of sorted_vals [T,R] within segments delimited by
    seg_start_mask [T] bool."""
    csum = jnp.cumsum(sorted_vals, axis=0)
    excl = csum - sorted_vals
    idx = jnp.arange(sorted_vals.shape[0])
    start_idx = jnp.where(seg_start_mask, idx, -1)
    start_idx = jax.lax.associative_scan(jnp.maximum, start_idx)
    base = excl[jnp.maximum(start_idx, 0)]
    return excl - base


def _waterfall_choice(eligible, node_score, fit_req, avail, npods,
                      max_pods, thr, scalar_mask, mode: str):
    """Spread a herd across nodes in one round.

    When many tasks prefer the same node (binpack's global argmax, or
    least-requested's identical-nodes tie), per-task argmax fills one node
    per round. Instead, order nodes by their herd desirability
    (``node_score`` = per-node max of the masked score — computed by the
    dense path or the fused pallas kernel) and pre-assign task *positions*
    to nodes:

    - pack mode: task position p lands on the node where cumulative slot
      capacity first exceeds p (fills best node to capacity, then next) —
      matches the reference's sequential binpack fill for uniform tasks.
    - spread mode: position p lands on node p mod m (striping) — matches
      sequential least-requested round-robin for uniform tasks.

    Tasks for which the pre-assigned node is infeasible fall back to their
    personal argmax; prefix admission corrects slot overestimates.
    """
    T = eligible.shape[0]
    N = node_score.shape[0]
    # mean eligible request estimates per-node slot counts (the estimate
    # only steers TARGETING — prefix admission is exact; quantile
    # estimators were tried and lose to the mean across the parity corpus)
    n_elig = jnp.maximum(jnp.sum(eligible), 1)
    mean_req = jnp.sum(fit_req * eligible[:, None], axis=0) / n_elig  # [R]
    sig = mean_req > jnp.where(scalar_mask, 10.0, 0.0)
    slots_dim = jnp.where(
        sig[None, :],
        jnp.floor((avail + thr[None, :]) / jnp.maximum(mean_req[None, :], 1e-9)),
        jnp.inf)
    slots = jnp.min(slots_dim, axis=1)                              # [N]
    slots = jnp.minimum(slots, (max_pods - npods).astype(jnp.float32))
    slots = jnp.clip(slots, 0.0, float(T))
    has_slot = slots > 0

    order = jnp.argsort(-jnp.where(has_slot, node_score, NEG))      # [N]
    slots_o = slots[order]
    pos = jnp.cumsum(eligible.astype(jnp.int32)) - 1                # [T]
    if mode == "spread":
        # stripe only across nodes whose herd score ties the best:
        # sequential least-requested alternates between EQUAL nodes but
        # keeps filling a strictly-better node until another catches up,
        # so striping across unequal nodes would scatter a gang the
        # reference packs (and revert it under contention)
        masked_score = jnp.where(has_slot, node_score, NEG)
        best_s = jnp.max(masked_score)
        eps = 1e-5 * jnp.maximum(jnp.abs(best_s), 1.0)
        near = has_slot & (masked_score >= best_s - eps)
        m = jnp.maximum(jnp.sum(near), 1)
        target = order[jnp.mod(jnp.maximum(pos, 0), m)]
    else:
        cum = jnp.cumsum(slots_o)
        idx = jnp.searchsorted(cum, pos.astype(jnp.float32), side="right")
        target = order[jnp.clip(idx, 0, N - 1)]
    return target.astype(jnp.int32)


def _admission_round(eligible, feas, score, fit_req, acct_req, avail,
                     rank, thr, scalar_mask, npods, max_pods,
                     per_node_cap: int = 0, herd_mode: str = "pack"):
    """One parallel round: choose best node per task (waterfall-corrected),
    admit by priority prefix within each node, return (new_assign[T]
    node/-1, debit[N,R], pod_inc[N])."""
    pods_ok = (npods < max_pods)[None, :]
    feas = feas & pods_ok & eligible[:, None]
    masked = jnp.where(feas, score, NEG)
    personal = jnp.argmax(masked, axis=1).astype(jnp.int32)        # [T]
    if herd_mode in ("pack", "spread") and per_node_cap == 0:
        node_score = jnp.max(masked, axis=0)                       # [N]
        target = _waterfall_choice(eligible, node_score, fit_req, avail,
                                   npods, max_pods, thr, scalar_mask,
                                   herd_mode)
        t_ok = jnp.take_along_axis(feas, target[:, None], axis=1)[:, 0]
        choice = jnp.where(t_ok, target, personal)
    else:
        choice = personal
    has = jnp.take_along_axis(feas, choice[:, None], axis=1)[:, 0]
    choice = jnp.where(has, choice, -1)
    return _admit_prefix(choice, fit_req, acct_req, avail, rank, thr,
                         scalar_mask, npods, max_pods, per_node_cap)


def _admission_round_fused(eligible, a, avail, used_now, sig_feas, sig_i8,
                           inv_alloc, node_static, pars, acct_req, rank,
                           thr, scalar_mask, npods, herd_mode: str,
                           score_families):
    """The fused-kernel form of _admission_round: the [T,N] feasibility/
    score/argmax/node-max pass runs in ONE pallas kernel (HBM traffic per
    round drops from several [T,N] float32 matrices to the int8 signature
    mask + [T]/[N] vectors); the feasibility of the two *chosen* nodes is
    re-derived pointwise. Only the waterfall herd modes take this path
    (per_node_cap fidelity mode stays dense)."""
    from .pallas_kernels import fused_choice

    fit_req = a["task_init_req"]
    max_pods = a["node_max_pods"]
    pods_ok = npods < max_pods
    best_s, best_i, node_score = fused_choice(
        fit_req, avail, used_now, inv_alloc, node_static,
        eligible.astype(jnp.float32), pods_ok.astype(jnp.float32),
        sig_i8, pars, score_families)
    has_any = best_s > NEG * 0.5
    personal = best_i

    def feas_point(node_idx):
        """feasibility of (task, node_idx[task]) — identical rule to the
        dense feas matrix, evaluated at one node per task."""
        av = avail[node_idx]                                   # [T,R]
        fit = le_fits(fit_req, av, thr, scalar_mask)
        sig = jnp.take_along_axis(sig_feas, node_idx[:, None],
                                  axis=1)[:, 0]
        return fit & sig & pods_ok[node_idx] & eligible

    target = _waterfall_choice(eligible, node_score, fit_req, avail,
                               npods, max_pods, thr, scalar_mask,
                               herd_mode)
    t_ok = feas_point(target)
    choice = jnp.where(t_ok, target,
                       jnp.where(has_any, personal, -1))
    return _admit_prefix(choice, fit_req, acct_req, avail, rank, thr,
                         scalar_mask, npods, max_pods, 0)


def _admit_prefix(choice, fit_req, acct_req, avail, rank, thr,
                  scalar_mask, npods, max_pods, per_node_cap: int):
    """Priority-prefix admission for a round's per-task node choices
    (shared by the dense and fused choice paths)."""
    T = choice.shape[0]
    N = avail.shape[0]
    # sort by (node, rank); inactive last
    key = jnp.where(choice >= 0, choice * (T + 1) + rank, BIG_KEY)
    perm = jnp.argsort(key)
    s_choice = choice[perm]
    s_active = s_choice >= 0
    s_fit = fit_req[perm] * s_active[:, None]
    seg_start = jnp.concatenate(
        [jnp.array([True]), s_choice[1:] != s_choice[:-1]])
    prefix = _segment_prefix(s_fit, seg_start)                     # [T,R]

    s_avail = avail[jnp.maximum(s_choice, 0)]                      # [T,R]
    fits = le_fits(prefix + s_fit, s_avail, thr, scalar_mask,
                   ignore_req=s_fit) & s_active
    # pod-count prefix: position within segment
    ones = jnp.ones_like(s_choice)
    pos = _segment_prefix(ones[:, None].astype(jnp.float32), seg_start)[:, 0]
    pods_fit = (npods[jnp.maximum(s_choice, 0)] + pos) < max_pods[jnp.maximum(s_choice, 0)]
    admit_sorted = fits & pods_fit
    if per_node_cap > 0:
        # fidelity mode: at most cap admissions per node per round, so
        # scoring sees updated node state between admissions (closer to the
        # reference's sequential greedy)
        admit_sorted = admit_sorted & (pos < per_node_cap)

    # NOTE: prefix admission is conservative: a blocked task simply waits for
    # the next round, after the node's idle has been debited for real.
    admit = jnp.zeros(T, dtype=bool).at[perm].set(admit_sorted)
    new_assign = jnp.where(admit, choice, -1)

    debit = jax.ops.segment_sum(
        acct_req * admit[:, None], jnp.maximum(choice, 0), num_segments=N)
    pod_inc = jax.ops.segment_sum(
        admit.astype(jnp.int32), jnp.maximum(choice, 0), num_segments=N)
    return new_assign, debit, pod_inc


# ---------------------------------------------------------------------------
# fast round-based solver
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("max_rounds", "max_gang_iters",
                                             "per_node_cap", "herd_mode",
                                             "score_families",
                                             "use_queue_cap",
                                             "use_drf_order",
                                             "use_hdrf_order",
                                             "work_conserving",
                                             "fused"))
def solve_allocate(arrays: Dict[str, jnp.ndarray],
                   score_params: Dict[str, jnp.ndarray],
                   max_rounds: int = 64,
                   max_gang_iters: int = 12,
                   per_node_cap: int = 0,
                   herd_mode: str = "pack",
                   score_families: Tuple[str, ...] = ("binpack", "kube"),
                   use_queue_cap: bool = False,
                   use_drf_order: bool = False,
                   use_hdrf_order: bool = False,
                   work_conserving: bool = True,
                   fused: str = "auto") -> SolveResult:
    """Round-based allocate+pipeline solve with in-kernel gang semantics.

    With ``use_queue_cap`` (proportion plugin active) per-queue deserved is
    water-filled on device from queue_weight/capability/request and each
    round's admissions are capped at deserved per queue, so a 3:1 weight
    split of a saturated cluster yields a 3:1 allocation split.

    With ``use_drf_order`` (drf plugin active) the admission priority is
    recomputed every round from live dominant shares (SURVEY §7 stage 4:
    DRF shares as on-device reductions for ordering): each job's share is
    max_r(allocated_r / total_r) including this solve's placements, jobs
    sort ascending by share, and tasks inherit their job's position — so a
    saturated cluster splits between equal competitors instead of the
    static snapshot order handing everything to the first job.
    """
    a = arrays
    T = a["task_init_req"].shape[0]
    N = a["node_idle"].shape[0]
    J = a["job_min"].shape[0]
    thr = a["thresholds"]
    scalar_mask = a["scalar_dim_mask"]
    sig_feas = a["sig_masks"][a["task_sig"]] & a["node_valid"][None, :]  # [T,N]
    rank = a["task_rank"]
    counts_ready = a["task_counts_ready"].astype(jnp.int32)

    # fused pallas choice kernel (TPU): the per-round [T,N] feasibility/
    # score/argmax pass in one VMEM-resident kernel. "auto" = on-device
    # when the shape tiles cleanly and the round uses the waterfall herd
    # modes; "on"/"off" force (tests exercise the kernel in interpret
    # mode on CPU via "on").
    from .pallas_kernels import fused_choice_auto
    use_fused = fused == "on" or (
        fused == "auto" and jax.default_backend() == "tpu"
        and fused_choice_auto(T, N)
        and herd_mode in ("pack", "spread") and per_node_cap == 0)
    if use_fused and (herd_mode not in ("pack", "spread")
                      or per_node_cap != 0):
        use_fused = False  # fused path implements only the herd modes
    if use_fused:
        from .pallas_kernels import fused_choice, fused_setup
        sig_i8, inv_alloc, fused_pars, node_static = fused_setup(
            {"sig_feas": sig_feas, "node_alloc": a["node_alloc"]},
            score_params, a["task_init_req"].shape[1])

    if use_queue_cap:
        total = jnp.sum(
            a["node_alloc"] * a["node_valid"][:, None].astype(jnp.float32),
            axis=0)
        Q, deserved, task_queue, q_perm, q_seg_start = queue_cap_state(
            a, rank, thr, total, ease_unrequested=work_conserving)
        qalloc0 = a["queue_allocated"]
        # static-sort gathers hoisted out of the round loop (the live-DRF
        # re-sorted path recomputes them per round inside the mask)
        qs_q = task_queue[q_perm]
        qs_req = a["task_req"][q_perm]
    else:
        task_queue = None
        deserved = None
        q_perm = q_seg_start = None
        qs_q = qs_req = None
        qalloc0 = jnp.zeros((1, a["node_idle"].shape[1]), jnp.float32)

    if use_drf_order:
        jobres0, drf_rank, drf_cap = drf_state(a, rank)
        if use_hdrf_order:
            # hierarchical mode: the comparator AND the progressive cap
            # both come from the weighted tree (ops.hdrf.hdrf_state) —
            # one tree recursion per round feeds the re-rank and the
            # per-ancestor-level growth gate, so weighted hierarchies
            # converge to the reference's weighted split
            from .hdrf import hdrf_state
            hdrf_rank_cap = hdrf_state(a, rank)
    else:
        jobres0 = jnp.zeros((1, a["node_idle"].shape[1]), jnp.float32)
        drf_rank = drf_cap = None

    def phase_rounds(st, use_future: bool, capped: bool = True, gate=None):
        """Run admission rounds to fixpoint against idle (allocate) or
        future-idle (pipeline). st: 9-tuple carry (idle, pipe, npods,
        qalloc, jobres, assigned, kind, excluded, rounds). capped=False is
        the work-conserving overflow pass: fair-share deserved caps are
        relaxed (hard capability quotas still bind) so capacity no
        competing queue wants is not stranded. This deliberately improves
        on the reference, whose any-dim overused check
        (proportion.go:245 `!allocated.LessEqual(deserved)`) strands the
        same capacity — the host path reproduces that faithfully."""

        def cond(s):
            changed, rounds = s[-1], s[-2]
            return changed & (rounds < max_rounds)

        def body(s):
            (idle, pipe, npods, qalloc, jobres, assigned, kind, excluded,
             rounds, _) = s
            avail = (idle + a["node_extra_future"] - pipe) if use_future else idle
            eligible = (a["task_valid"] & (assigned < 0)
                        & ~excluded[a["task_job"]])
            # per-round admission priority: live DRF shares when active
            used_now = a["node_used"] + (a["node_idle"] - idle)
            feas0 = None
            if use_drf_order:
                if use_hdrf_order:
                    # placeability prefilter: a task no node can take this
                    # round must not hold its sibling group's min key or
                    # pin its subtree's budget (the reference's queue loop
                    # skips a queue whose job can't place and pops the
                    # next — hard cap-blocking against an unplaceable
                    # sibling would strand capacity instead). The dense
                    # path reuses this round's feasibility matrix; the
                    # fused path pays one extra kernel pass (hdrf only).
                    pods_ok_v = npods < a["node_max_pods"]
                    if use_fused:
                        best_s0, _, _ = fused_choice(
                            a["task_init_req"], avail, used_now,
                            inv_alloc, node_static,
                            eligible.astype(jnp.float32),
                            pods_ok_v.astype(jnp.float32),
                            sig_i8, fused_pars, score_families)
                        placeable = best_s0 > NEG * 0.5
                    else:
                        feas0 = fits_matrix(a["task_init_req"], avail,
                                            thr, scalar_mask) & sig_feas
                        placeable = jnp.any(
                            feas0 & pods_ok_v[None, :], axis=1)
                    r_rank, eligible = hdrf_rank_cap(
                        eligible & placeable, jobres)
                else:
                    r_rank = drf_rank(jobres)
                    eligible = drf_cap(eligible, jobres)
            else:
                r_rank = rank
            if use_queue_cap:
                # capped phases enforce fair-share deserved; the overflow
                # pass relaxes deserved but NEVER the hard capability
                # quota (a queue must not exceed its capability just
                # because capacity is otherwise idle)
                bound = deserved if capped else a["queue_capability"]
                qrem = jnp.maximum(bound - qalloc, 0.0)
                if use_drf_order:
                    qp = jnp.lexsort((r_rank, task_queue))
                    eligible = eligible & _queue_cap_mask(
                        eligible, task_queue, a["task_req"], qrem, thr,
                        scalar_mask, qp, q_seg_start)
                else:
                    eligible = eligible & _queue_cap_mask(
                        eligible, task_queue, a["task_req"], qrem, thr,
                        scalar_mask, q_perm, q_seg_start, qs_q, qs_req)
            if use_fused:
                new_assign, debit, pod_inc = _admission_round_fused(
                    eligible, a, avail, used_now, sig_feas, sig_i8,
                    inv_alloc, node_static, fused_pars, a["task_req"],
                    r_rank, thr, scalar_mask, npods, herd_mode,
                    score_families)
            else:
                feas = feas0 if feas0 is not None else (
                    fits_matrix(a["task_init_req"], avail, thr,
                                scalar_mask) & sig_feas)
                score = score_matrix(a["task_init_req"], avail, used_now,
                                     a["node_alloc"], score_params,
                                     score_families)
                new_assign, debit, pod_inc = _admission_round(
                    eligible, feas, score, a["task_init_req"],
                    a["task_req"], avail, r_rank, thr, scalar_mask, npods,
                    a["node_max_pods"], per_node_cap, herd_mode)
            got = new_assign >= 0
            assigned = jnp.where(got, new_assign, assigned)
            kind = jnp.where(got, jnp.int32(1 if use_future else 0), kind)
            if use_queue_cap:
                # pipelined tasks count toward queue allocated too (the
                # reference fires AllocateFunc handlers on ssn.Pipeline)
                qalloc = qalloc + jax.ops.segment_sum(
                    a["task_req"] * got[:, None], task_queue,
                    num_segments=Q)
            if use_drf_order:
                jobres = jobres + jax.ops.segment_sum(
                    a["task_req"] * got[:, None], a["task_job"],
                    num_segments=J)
            if use_future:
                pipe = pipe + debit
            else:
                idle = idle - debit
                npods = npods + pod_inc
            return (idle, pipe, npods, qalloc, jobres, assigned, kind,
                    excluded, rounds + 1, jnp.any(got))

        # skip the phase outright when no task is still eligible (e.g. the
        # pipeline phase after everything allocated): one [T] reduction
        # instead of a full wasted [T,N] round. `gate` adds a caller-side
        # cheap impossibility check (no future capacity / no capped task).
        _, _, _, _, _, assigned0, _, excluded0, _ = st
        any_eligible = jnp.any(a["task_valid"] & (assigned0 < 0)
                               & ~excluded0[a["task_job"]])
        if gate is not None:
            any_eligible = any_eligible & gate
        out = jax.lax.while_loop(cond, body, st + (any_eligible,))
        return out[:-1]

    # job order position for the gang-exclusion tie-break: first valid
    # task's rank (static snapshot order)
    job_first_rank = jnp.full((J,), T, jnp.int32).at[a["task_job"]].min(
        jnp.where(a["task_valid"], rank, T))
    # loop-invariant: pipeline phases only matter when some node's
    # FutureIdle can exceed its Idle (releasing > pipelined somewhere)
    has_future = jnp.any(a["node_extra_future"] > 0.0)

    def gang_body(s):
        (idle, pipe, npods, qalloc, jobres, assigned, kind, excluded,
         rounds, _, it, revert_count, deferred, processed) = s
        # deferred-retry queue: jobs that reverted twice in the parallel
        # phases sit out while the best-ranked of them retries ALONE —
        # the batched equivalent of the sequential reference, where the
        # earliest discarded gang gets first claim on capacity later
        # discards free. One deferred job resolves per iteration.
        unproc = deferred & ~processed & ~excluded
        cur = jnp.argmin(jnp.where(unproc, job_first_rank, BIG_KEY))
        solo = unproc & (jnp.arange(J) == cur)
        barred = deferred & ~solo
        st = (idle, pipe, npods, qalloc, jobres, assigned, kind,
              excluded | barred, rounds)
        st = phase_rounds(st, use_future=False)
        st = phase_rounds(st, use_future=True, gate=has_future)
        if use_queue_cap and work_conserving:
            # work-conserving overflow: leftovers no competing queue could
            # take under its cap go to whoever still wants them — run only
            # when some leftover task is BLOCKED by the capped eligibility
            # mask. The mask is monotone in the queue bound, so if every
            # leftover already passes it under `deserved`, the overflow
            # phases would see the exact eligibility the capped phases
            # converged on and admit nothing: two full-width [T,N] rounds
            # skipped for one [T,R] mask evaluation. (Under live DRF
            # ordering the mask is rank-dependent; keep the phases then.)
            if use_drf_order:
                # rank-dependent mask: no cheap exactness argument, keep
                # the phases (their own any-eligible check still applies)
                st = phase_rounds(st, use_future=False, capped=False)
                st = phase_rounds(st, use_future=True, capped=False,
                                  gate=has_future)
            else:
                (_i, _p, _n, qalloc_c, _j, assigned_c, _k, excl_c,
                 _r) = st
                rem = (a["task_valid"] & (assigned_c < 0)
                       & ~excl_c[a["task_job"]])
                qrem_now = jnp.maximum(deserved - qalloc_c, 0.0)
                elig_capped = _queue_cap_mask(
                    rem, task_queue, a["task_req"], qrem_now, thr,
                    scalar_mask, q_perm, q_seg_start, qs_q, qs_req)
                capped_out = jnp.any(rem & ~elig_capped)
                st = phase_rounds(st, use_future=False, capped=False,
                                  gate=capped_out)
                st = phase_rounds(st, use_future=True, capped=False,
                                  gate=capped_out & has_future)
        (idle, pipe, npods, qalloc, jobres, assigned, kind, _masked,
         rounds) = st

        # gang check: allocated (kind 0, counts_ready) per job
        alloc_counts = jax.ops.segment_sum(
            ((assigned >= 0) & (kind == 0)).astype(jnp.int32) * counts_ready,
            a["task_job"], num_segments=J)
        ready = (a["job_ready_base"] + alloc_counts) >= a["job_min"]
        ready = ready & a["job_valid"]
        # revert unready jobs that DID get allocations (Statement.Discard);
        # pipelined tasks are NOT statement ops in the reference
        # (allocate.go pipelines via ssn.Pipeline) so they survive discard
        # and keep holding FutureIdle. Unready jobs with nothing allocated
        # stay eligible — resources a revert frees may let them place in the
        # next gang iteration.
        has_alloc = jax.ops.segment_sum(
            ((assigned >= 0) & (kind == 0)).astype(jnp.int32), a["task_job"],
            num_segments=J) > 0
        revert_job = ~ready & a["job_valid"] & ~excluded & ~barred \
            & has_alloc
        revert_task = (revert_job[a["task_job"]] & (assigned >= 0)
                       & (kind == 0))
        credit = jax.ops.segment_sum(
            a["task_req"] * revert_task[:, None],
            jnp.maximum(assigned, 0), num_segments=N)
        pod_credit = jax.ops.segment_sum(
            revert_task.astype(jnp.int32),
            jnp.maximum(assigned, 0), num_segments=N)
        idle = idle + credit
        npods = npods - pod_credit
        if use_queue_cap:
            qalloc = qalloc - jax.ops.segment_sum(
                a["task_req"] * revert_task[:, None], task_queue,
                num_segments=Q)
        if use_drf_order:
            jobres = jobres - jax.ops.segment_sum(
                a["task_req"] * revert_task[:, None], a["task_job"],
                num_segments=J)
        assigned = jnp.where(revert_task, -1, assigned)
        kind = jnp.where(revert_task, -1, kind)
        # retry policy: a first revert leaves the job eligible for the
        # next parallel iteration (another job's revert — often the cause
        # of its failure — may have freed room); a second revert defers
        # the job to the one-at-a-time queue above. A solo retry that
        # reverts again is excluded for good; either way the job counts
        # as processed, so the queue drains one job per iteration and the
        # fixpoint stays bounded.
        revert_count = revert_count + revert_job.astype(jnp.int32)
        excluded = excluded | (solo & revert_job)
        processed = processed | (solo & jnp.any(unproc))
        deferred = deferred | (revert_job & (revert_count >= 2))
        any_more = jnp.any(revert_job) | jnp.any(
            deferred & ~processed & ~excluded)
        return (idle, pipe, npods, qalloc, jobres, assigned, kind, excluded,
                rounds, any_more, it + 1, revert_count, deferred, processed)

    init = (a["node_idle"], jnp.zeros_like(a["node_idle"]), a["node_npods"],
            qalloc0, jobres0,
            jnp.full((T,), -1, jnp.int32), jnp.full((T,), -1, jnp.int32),
            ~a["job_valid"], jnp.int32(0), jnp.bool_(True), jnp.int32(0),
            jnp.zeros(J, jnp.int32), jnp.zeros(J, dtype=bool),
            jnp.zeros(J, dtype=bool))
    # bounded gang fixpoint: rerun phases while any job got reverted (its
    # freed resources may admit other jobs) or deferred jobs await their
    # solo retry
    s = jax.lax.while_loop(
        lambda s: s[-5] & (s[-4] < max_gang_iters), gang_body, init)

    (idle, pipe, npods, _, _, assigned, kind, excluded, rounds,
     _, _, _, _, _) = s
    alloc_counts = jax.ops.segment_sum(
        ((assigned >= 0) & (kind == 0)).astype(jnp.int32) * counts_ready,
        a["task_job"], num_segments=J)
    job_ready = ((a["job_ready_base"] + alloc_counts) >= a["job_min"]) \
        & a["job_valid"]
    return SolveResult(assigned=assigned, kind=kind, job_ready=job_ready,
                       rounds=rounds, compact=_compact(assigned, kind, N))


# ---------------------------------------------------------------------------
# sequential parity solver (reference greedy semantics)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("score_families",
                                             "use_queue_cap",
                                             "overflow_pass",
                                             "work_conserving"))
def solve_allocate_sequential(arrays: Dict[str, jnp.ndarray],
                              score_params: Dict[str, jnp.ndarray],
                              score_families: Tuple[str, ...] = ("binpack", "kube"),
                              use_queue_cap: bool = False,
                              overflow_pass: bool = False,
                              work_conserving: bool = True) -> SolveResult:
    """lax.scan over tasks in rank order: task k's allocation is visible to
    task k+1 and job-boundary gang revert mirrors Statement.Discard.

    Requires tasks grouped by job in rank order (flatten_snapshot guarantees
    this). O(T) sequential steps — use for parity tests and small problems.

    overflow_pass (with use_queue_cap): after the strict deserved-capped
    scan, run a SECOND scan over the leftover tasks with the caps relaxed
    to hard capability — the sequential oracle for the round solver's
    work-conserving overflow phases (capacity no competing queue could
    take under its cap goes to whoever still wants it).
    """
    a = arrays
    T = a["task_init_req"].shape[0]
    N = a["node_idle"].shape[0]
    J = a["job_min"].shape[0]
    thr = a["thresholds"]
    scalar_mask = a["scalar_dim_mask"]
    sig_feas_all = a["sig_masks"][a["task_sig"]] & a["node_valid"][None, :]

    if use_queue_cap:
        total = jnp.sum(
            a["node_alloc"] * a["node_valid"][:, None].astype(jnp.float32),
            axis=0)
        Q, deserved, _, _, _ = queue_cap_state(
            a, a["task_rank"], thr, total,
            ease_unrequested=work_conserving)
        qalloc0 = a["queue_allocated"]
    else:
        deserved = None
        qalloc0 = jnp.zeros((1, a["node_idle"].shape[1]), jnp.float32)

    def fits_one(req, avail):
        return le_fits(req[None, :], avail, thr, scalar_mask)

    def make_pass(bound, base_alloc):
        """One sequential scan over the tasks. bound: per-queue cap table
        (deserved for the strict pass, hard capability for the overflow
        pass); base_alloc [J]: allocations a prior pass already committed
        — ready checks include them, reverts never touch them."""

        def finalize_job(carry, jidx):
            (idle, pipe, npods, qalloc, assigned, kind, jalloc,
             snap_idle, snap_pipe, snap_npods, snap_assigned) = carry
            ready = (a["job_ready_base"][jidx] + base_alloc[jidx]
                     + jalloc) >= a["job_min"][jidx]
            is_job = (a["task_job"] == jidx)
            # only THIS pass's allocations revert (a prior pass's are
            # already dispatched): exactly the entries assigned since the
            # job-boundary snapshot. Pipelined tasks survive discard,
            # mirroring ssn.Pipeline being outside the Statement.
            revert = (is_job & (assigned >= 0) & (kind == 0) & ~ready
                      & (snap_assigned < 0))
            idle = jnp.where(ready, idle, snap_idle)
            npods = jnp.where(ready, npods, snap_npods)
            if use_queue_cap:
                amt = jnp.sum(a["task_req"] * revert[:, None], axis=0)
                jq = a["job_queue"][jidx]
                qalloc = qalloc - (jnp.arange(Q) == jq)[:, None] \
                    * amt[None, :]
            assigned = jnp.where(revert, -1, assigned)
            kind = jnp.where(revert, -1, kind)
            return (idle, pipe, npods, qalloc, assigned, kind)

        def step(carry, i):
            (idle, pipe, npods, qalloc, assigned, kind, cur_job, jalloc,
             snap_idle, snap_pipe, snap_npods, snap_assigned) = carry
            jidx = a["task_job"][i]
            boundary = (jidx != cur_job)

            def at_boundary(args):
                (idle, pipe, npods, qalloc, assigned, kind, jalloc,
                 snap_idle, snap_pipe, snap_npods, snap_assigned) = args
                idle, pipe, npods, qalloc, assigned, kind = \
                    finalize_job(args, cur_job)
                return (idle, pipe, npods, qalloc, assigned, kind,
                        jnp.int32(0), idle, pipe, npods, assigned)

            (idle, pipe, npods, qalloc, assigned, kind, jalloc,
             snap_idle, snap_pipe, snap_npods, snap_assigned) = jax.lax.cond(
                boundary, at_boundary, lambda args: args,
                (idle, pipe, npods, qalloc, assigned, kind, jalloc,
                 snap_idle, snap_pipe, snap_npods, snap_assigned))
            cur_job = jidx

            # the overflow pass only visits leftovers
            valid = a["task_valid"][i] & (assigned[i] < 0)
            req_fit = a["task_init_req"][i]
            req_acct = a["task_req"][i]
            sig_feas = sig_feas_all[i]
            pods_ok = npods < a["node_max_pods"]
            if use_queue_cap:
                jq = a["job_queue"][jidx]
                valid = valid & le_fits(qalloc[jq] + req_acct, bound[jq],
                                        thr, scalar_mask,
                                        ignore_req=req_acct)

            feas_idle = fits_one(req_fit, idle) & sig_feas & pods_ok & valid
            future = idle + a["node_extra_future"] - pipe
            feas_fut = fits_one(req_fit, future) & sig_feas & pods_ok & valid

            used_now = a["node_used"] + (a["node_idle"] - idle)
            score = score_matrix(req_fit[None, :], idle, used_now,
                                 a["node_alloc"], score_params,
                                 score_families)[0]

            pick_idle = jnp.any(feas_idle)
            pick_fut = ~pick_idle & jnp.any(feas_fut)
            feas = jnp.where(pick_idle, feas_idle, feas_fut)
            node = jnp.argmax(jnp.where(feas, score, NEG)).astype(jnp.int32)
            got = pick_idle | pick_fut
            node = jnp.where(got, node, -1)

            debit = jnp.where(got, req_acct, 0.0)
            onehot = (jnp.arange(N) == node)[:, None]
            idle = idle - jnp.where(pick_idle, debit[None, :] * onehot, 0.0)
            pipe = pipe + jnp.where(pick_fut, debit[None, :] * onehot, 0.0)
            npods = npods + jnp.where(pick_idle,
                                      onehot[:, 0].astype(jnp.int32), 0)
            if use_queue_cap:
                q_onehot = (jnp.arange(Q) == a["job_queue"][jidx])[:, None]
                qalloc = qalloc + q_onehot * debit[None, :]
            # never clobber a prior pass's assignment
            prev_a, prev_k = assigned[i], kind[i]
            assigned = assigned.at[i].set(
                jnp.where(prev_a >= 0, prev_a, node))
            kind = kind.at[i].set(jnp.where(
                prev_a >= 0, prev_k,
                jnp.where(pick_idle, 0, jnp.where(pick_fut, 1, -1))))
            jalloc = jalloc + jnp.where(
                pick_idle & a["task_counts_ready"][i], 1, 0)
            return (idle, pipe, npods, qalloc, assigned, kind, cur_job,
                    jalloc, snap_idle, snap_pipe, snap_npods,
                    snap_assigned), None

        return finalize_job, step

    def run_pass(bound, base_alloc, state):
        idle, pipe, npods, qalloc, assigned, kind = state
        finalize_job, step = make_pass(bound, base_alloc)
        init = (idle, pipe, npods, qalloc, assigned, kind,
                a["task_job"][0], jnp.int32(0),
                idle, pipe, npods, assigned)
        carry, _ = jax.lax.scan(step, init, jnp.arange(T))
        (idle, pipe, npods, qalloc, assigned, kind, cur_job, jalloc,
         snap_idle, snap_pipe, snap_npods, snap_assigned) = carry
        return finalize_job(
            (idle, pipe, npods, qalloc, assigned, kind, jalloc,
             snap_idle, snap_pipe, snap_npods, snap_assigned), cur_job)

    counts_ready = a["task_counts_ready"].astype(jnp.int32)
    state = (a["node_idle"], jnp.zeros_like(a["node_idle"]),
             a["node_npods"], qalloc0,
             jnp.full((T,), -1, jnp.int32), jnp.full((T,), -1, jnp.int32))
    state = run_pass(deserved, jnp.zeros(J, jnp.int32), state)
    if overflow_pass and use_queue_cap:
        idle, pipe, npods, qalloc, assigned, kind = state
        base1 = jax.ops.segment_sum(
            ((assigned >= 0) & (kind == 0)).astype(jnp.int32)
            * counts_ready, a["task_job"], num_segments=J)
        state = run_pass(a["queue_capability"], base1,
                         (idle, pipe, npods, qalloc, assigned, kind))
    idle, pipe, npods, qalloc, assigned, kind = state
    alloc_counts = jax.ops.segment_sum(
        ((assigned >= 0) & (kind == 0)).astype(jnp.int32) * counts_ready,
        a["task_job"], num_segments=J)
    job_ready = ((a["job_ready_base"] + alloc_counts) >= a["job_min"]) \
        & a["job_valid"]
    return SolveResult(assigned=assigned, kind=kind, job_ready=job_ready,
                       rounds=jnp.int32(T), compact=_compact(assigned, kind, N))


# ---------------------------------------------------------------------------
# packed-transfer entry point
# ---------------------------------------------------------------------------

def _unpack(fbuf, ibuf, layout):
    d = {}
    for k, kind, off, size, shape in layout:
        if kind == "f":
            d[k] = jax.lax.dynamic_slice(fbuf, (off,), (size,)).reshape(shape)
        else:
            v = jax.lax.dynamic_slice(ibuf, (off,), (size,)).reshape(shape)
            d[k] = v.astype(bool) if kind == "b" else v
    return d


@functools.partial(jax.jit, static_argnames=(
    "layout", "max_rounds", "max_gang_iters", "per_node_cap", "herd_mode",
    "score_families", "use_queue_cap", "use_drf_order", "use_hdrf_order",
    "work_conserving"))
def solve_allocate_packed2d(f2d, i2d, layout,
                            score_params: Dict[str, jnp.ndarray],
                            max_rounds: int = 64,
                            max_gang_iters: int = 12,
                            per_node_cap: int = 0,
                            herd_mode: str = "pack",
                            score_families: Tuple[str, ...] = ("binpack",),
                            use_queue_cap: bool = False,
                            use_drf_order: bool = False,
                            use_hdrf_order: bool = False,
                            work_conserving: bool = True) -> SolveResult:
    """solve_allocate over the chunked device-resident buffers kept by
    ops.device_cache.PackedDeviceCache: per-session upload is only the
    dirty chunks; the flatten+slice here fuses away on device."""
    nf = max(off + size for k, kind, off, size, shape in layout
             if kind == "f")
    ni = max(off + size for k, kind, off, size, shape in layout
             if kind != "f")
    fbuf = f2d.reshape(-1)[:nf]
    ibuf = i2d.reshape(-1)[:ni]
    arrays = _unpack(fbuf, ibuf, layout)
    return solve_allocate(arrays, score_params, max_rounds, max_gang_iters,
                          per_node_cap, herd_mode, score_families,
                          use_queue_cap, use_drf_order, use_hdrf_order,
                          work_conserving)


@functools.partial(jax.jit, static_argnames=(
    "layout", "max_rounds", "max_gang_iters", "per_node_cap", "herd_mode",
    "score_families", "use_queue_cap", "use_drf_order", "use_hdrf_order",
    "work_conserving"), donate_argnums=(0, 1))
def solve_allocate_delta(f2d, i2d, f_idx, f_vals, i_idx, i_vals, layout,
                         score_params: Dict[str, jnp.ndarray],
                         max_rounds: int = 64,
                         max_gang_iters: int = 12,
                         per_node_cap: int = 0,
                         herd_mode: str = "pack",
                         score_families: Tuple[str, ...] = ("binpack",),
                         use_queue_cap: bool = False,
                         use_drf_order: bool = False,
                         use_hdrf_order: bool = False,
                         work_conserving: bool = True):
    """Fused dirty-chunk scatter + solve: the whole session is ONE device
    dispatch (this call) plus ONE readback (res.compact) — on a
    latency-expensive tunnel the dispatch count IS the latency, so the
    delta upload (ops.device_cache) rides the solve's argument transfer
    instead of paying its own two scatter dispatches.

    f2d/i2d are the donated device-resident chunked buffers; f_idx/f_vals
    (and i_idx/i_vals) are the dirty chunk indices and replacement chunk
    contents (duplicate indices write identical values, so power-of-two
    padding is a no-op). Returns (result, new_f2d, new_i2d) — the caller
    must retain the returned buffers (donation invalidates the inputs).
    """
    f2d = f2d.at[f_idx].set(f_vals)
    i2d = i2d.at[i_idx].set(i_vals)
    nf = max(off + size for k, kind, off, size, shape in layout
             if kind == "f")
    ni = max(off + size for k, kind, off, size, shape in layout
             if kind != "f")
    arrays = _unpack(f2d.reshape(-1)[:nf], i2d.reshape(-1)[:ni], layout)
    res = solve_allocate(arrays, score_params, max_rounds, max_gang_iters,
                         per_node_cap, herd_mode, score_families,
                         use_queue_cap, use_drf_order, use_hdrf_order,
                         work_conserving)
    return res, f2d, i2d


@functools.partial(jax.jit, static_argnames=(
    "layout", "max_rounds", "max_gang_iters", "per_node_cap", "herd_mode",
    "score_families", "use_queue_cap", "use_drf_order", "use_hdrf_order",
    "work_conserving"))
def solve_allocate_packed(fbuf, ibuf, layout,
                          score_params: Dict[str, jnp.ndarray],
                          max_rounds: int = 64,
                          max_gang_iters: int = 12,
                          per_node_cap: int = 0,
                          herd_mode: str = "pack",
                          score_families: Tuple[str, ...] = ("binpack",),
                          use_queue_cap: bool = False,
                          use_drf_order: bool = False,
                          use_hdrf_order: bool = False,
                          work_conserving: bool = True) -> SolveResult:
    """solve_allocate over buffers produced by SnapshotArrays.packed():
    the unpack is free on device (slices fuse), the transfer is 2 puts."""
    arrays = _unpack(fbuf, ibuf, layout)
    return solve_allocate(arrays, score_params, max_rounds, max_gang_iters,
                          per_node_cap, herd_mode, score_families,
                          use_queue_cap, use_drf_order, use_hdrf_order,
                          work_conserving)
