"""Fused choice kernel (Pallas/TPU): feasibility + scoring + argmax in one
VMEM pass.

The round solver's per-round cost is HBM bandwidth: the XLA path
materializes several [T,N] float32/bool matrices per round (feasibility,
score, masked score, argmax input, per-node max — XLA's cost analysis
reports ~3.6 GB accessed per round body at 10k x 2k). This kernel fuses
the whole (task, node) pass: each (bt, bn) tile computes feasibility and
the plugin score families on the fly from the [R]-vector inputs, and only
[T]-sized argmax results and an [N]-sized per-node max ever touch HBM.

Semantics vs the dense path in ops.solver:
- feasibility == le_fits(req, avail) & sig_feas & pods_ok & eligible
  with the positional threshold rule (cpu=10 milli, mem=1 byte, scalars
  10 milli ignored when the request is <= 10);
- score mirrors score_matrix(...) term for term in the same operation
  order. On the REAL TPU backend the results are bitwise identical
  (verified across a 40-seed corpus: identical assignments); under the
  CPU interpret path XLA's FMA contraction can differ by 1 ulp, which
  may flip argmax TIES — the CPU parity tests therefore assert
  outcome equivalence (equal scores at divergent choices) rather than
  bit equality. The kernel only runs for real on TPU (the solver's
  auto gate checks the backend).
- best_idx == argmax semantics of jnp.argmax (first max wins: in-tile
  the min index among max-achievers, cross-tile strictly-greater);
- node_max == max over tasks of the masked score.

Layout: the [R]-indexed inputs arrive TRANSPOSED ([R,T] / [R,N]) so the
long axis sits on lanes; the round-invariant signature mask is an int8
[T,N] (one read per round instead of several float32 matrices). Grid is
(T/bt, N/bn) with the node axis fastest: per-task running (best, idx)
accumulate in a revisited VMEM output block; the per-node max block is
revisited across the slow axis (HBM round trip, [N]-sized).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e30

#: positional thresholds (api.resource): cpu millicores, memory bytes,
#: scalar milli-units. Scalar dims (r >= 2) are ignored when the request
#: itself is <= 10 milli.
_THR_CPU = 10.0
_THR_MEM = 1.0
_THR_SCALAR = 10.0
_REL_FIT_TOL = 5e-7  # mirrors ops.solver.REL_FIT_TOL (see its rationale)


def _pick_tile(n: int, full_cap: int = 2048) -> int:
    """Mosaic requires block dims divisible by (8, 128) or spanning the
    whole axis; small axes take the whole-axis block."""
    for p in (512, 256, 128):
        if n % p == 0 and n >= p:
            return p
    return n if n <= full_cap else 0


def fused_choice_supported(T: int, N: int) -> bool:
    """Shapes the kernel tiles cleanly; anything else uses the dense path."""
    return _pick_tile(T) > 0 and _pick_tile(N) > 0


def fused_choice_auto(T: int, N: int) -> bool:
    """The solver's auto gate: take the kernel only at the scale where it
    pays AND where the tiles are the well-trodden 128-multiples — small
    odd shapes exercise Mosaic relayout corners (observed: i1 relayout
    failures on 40-row tiles) for no measurable win."""
    return (T >= 1024 and N >= 256 and T % 128 == 0 and N % 128 == 0
            and fused_choice_supported(T, N))


def _kernel(reqT_ref, elig_ref, sig_ref, availT_ref, usedT_ref, invT_ref,
            nstat_ref, podsok_ref, pars_ref,
            best_s_ref, best_i_ref, node_max_ref,
            *, R: int, bn: int, families: Tuple[str, ...]):
    i = pl.program_id(0)
    j = pl.program_id(1)

    sig = sig_ref[:] != 0                                     # [bt,bn]
    # reshape the 32-bit values BEFORE comparing: Mosaic can't insert a
    # minor dim on 1-bit vectors
    elig = elig_ref[0, :][:, None] != 0.0                     # [bt,1]
    podsok = podsok_ref[0, :][None, :] != 0.0                 # [1,bn]

    feas = sig & elig & podsok
    for r in range(R):
        req_r = reqT_ref[r, :][:, None]                       # [bt,1]
        av_r = availT_ref[r, :][None, :]                      # [1,bn]
        thr = _THR_CPU if r == 0 else (_THR_MEM if r == 1 else _THR_SCALAR)
        # same expression order as ops.solver.le_fits (incl. the float32
        # scale-aware REL_FIT_TOL term) so the fused path stays bitwise
        # identical to the dense one
        ok = (req_r < av_r + (thr + _REL_FIT_TOL * jnp.abs(av_r))) \
            | (req_r <= av_r)
        if r >= 2:
            ok = ok | (req_r <= 10.0)
        feas = feas & ok

    bt = sig.shape[0]
    score = jnp.zeros((bt, bn), jnp.float32)
    # pars layout: [0]=binpack_weight, [1]=least, [2]=most, [3]=balanced,
    # [4]=100/sum(w), [5:5+R]=binpack_res_weights.
    # The float operation ORDER below mirrors ops.solver.score_matrix
    # term for term (task/node sums accumulated separately, kube terms
    # summed before joining score) so the result is bitwise identical —
    # a different grouping flips argmax tie-breaks.
    if "binpack" in families:
        bp_task = jnp.zeros((bt, bn), jnp.float32)
        bp_node = jnp.zeros((1, bn), jnp.float32)
        for r in range(R):
            inv_r = invT_ref[r, :][None, :]
            w_r = pars_ref[0, 5 + r]
            # task term multiplies req by (w*inv), node term multiplies
            # (used*w) by inv — the dense path's exact groupings
            bp_task = bp_task + reqT_ref[r, :][:, None] * (w_r * inv_r)
            bp_node = bp_node + (usedT_ref[r, :][None, :] * w_r) * inv_r
        score = score + (pars_ref[0, 0]
                         * (bp_task + bp_node) * pars_ref[0, 4])
    if "kube" in families:
        f0 = ((usedT_ref[0, :][None, :] + reqT_ref[0, :][:, None])
              * invT_ref[0, :][None, :])
        f1 = ((usedT_ref[1, :][None, :] + reqT_ref[1, :][:, None])
              * invT_ref[1, :][None, :])
        least = ((jnp.clip(1.0 - f0, 0.0, 1.0)
                  + jnp.clip(1.0 - f1, 0.0, 1.0)) / 2.0) * 100.0
        most = ((jnp.clip(f0, 0.0, 1.0)
                 + jnp.clip(f1, 0.0, 1.0)) / 2.0) * 100.0
        balanced = (1.0 - jnp.abs(f0 - f1)) * 100.0
        score = score + (pars_ref[0, 1] * least + pars_ref[0, 2] * most
                         + pars_ref[0, 3] * balanced)
    score = score + nstat_ref[0, :][None, :]

    masked = jnp.where(feas, score, NEG)

    loc_best = jnp.max(masked, axis=1)                        # [bt]
    # explicit first-index tie rule: Mosaic's argmax lowering does not
    # guarantee the lowest index on ties (XLA's does), so take min over
    # the max-achieving columns
    col = jax.lax.broadcasted_iota(jnp.int32, masked.shape, 1)
    cand = jnp.where(masked == loc_best[:, None], col,
                     jnp.int32(2 ** 30))
    loc_idx = jnp.min(cand, axis=1) + j * bn

    @pl.when(j == 0)
    def _():
        best_s_ref[0, :] = loc_best
        best_i_ref[0, :] = loc_idx

    @pl.when(j > 0)
    def _():
        prev = best_s_ref[0, :]
        better = loc_best > prev                  # strict: first max wins
        best_s_ref[0, :] = jnp.where(better, loc_best, prev)
        best_i_ref[0, :] = jnp.where(better, loc_idx, best_i_ref[0, :])

    colmax = jnp.max(masked, axis=0)                          # [bn]

    @pl.when(i == 0)
    def _():
        node_max_ref[0, :] = colmax

    @pl.when(i > 0)
    def _():
        node_max_ref[0, :] = jnp.maximum(node_max_ref[0, :], colmax)


@functools.partial(jax.jit, static_argnames=("families",))
def fused_choice(init_req, avail, used_now, inv_alloc, node_static,
                 eligible, pods_ok, sig_feas_i8, pars,
                 families: Tuple[str, ...]):
    """Fused (feasibility & score & argmax & node-max) over [T,N].

    init_req [T,R] f32; avail/used_now/inv_alloc [N,R] f32; node_static
    [N] f32; eligible [T] f32 (0/1); pods_ok [N] f32 (0/1); sig_feas_i8
    [T,N] int8 (round-invariant predicate mask); pars [5+R] f32 (see
    kernel). Returns (best_score [T], best_idx [T], node_max [N]).
    """
    T, R = init_req.shape
    N = avail.shape[0]
    bt = _pick_tile(T)
    bn = _pick_tile(N)
    if not bt or not bn:
        raise ValueError(f"unsupported fused-choice shape T={T} N={N}")

    reqT = init_req.T                     # [R,T]
    availT = avail.T                      # [R,N]
    usedT = used_now.T
    invT = inv_alloc.T
    grid = (T // bt, N // bn)

    kernel = functools.partial(_kernel, R=R, bn=bn, families=families)
    vm = pltpu.VMEM
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((R, bt), lambda i, j: (0, i), memory_space=vm),
            pl.BlockSpec((1, bt), lambda i, j: (0, i), memory_space=vm),
            pl.BlockSpec((bt, bn), lambda i, j: (i, j), memory_space=vm),
            pl.BlockSpec((R, bn), lambda i, j: (0, j), memory_space=vm),
            pl.BlockSpec((R, bn), lambda i, j: (0, j), memory_space=vm),
            pl.BlockSpec((R, bn), lambda i, j: (0, j), memory_space=vm),
            pl.BlockSpec((1, bn), lambda i, j: (0, j), memory_space=vm),
            pl.BlockSpec((1, bn), lambda i, j: (0, j), memory_space=vm),
            pl.BlockSpec((1, 5 + R), lambda i, j: (0, 0), memory_space=vm),
        ],
        out_specs=[
            pl.BlockSpec((1, bt), lambda i, j: (0, i), memory_space=vm),
            pl.BlockSpec((1, bt), lambda i, j: (0, i), memory_space=vm),
            pl.BlockSpec((1, bn), lambda i, j: (0, j), memory_space=vm),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, T), jnp.float32),
            jax.ShapeDtypeStruct((1, T), jnp.int32),
            jax.ShapeDtypeStruct((1, N), jnp.float32),
        ],
        # interpret off-TPU (tests run the same code path on CPU); the
        # axon plugin reports its own platform name, so gate on cpu
        interpret=jax.default_backend() == "cpu",
    )(reqT, eligible[None, :], sig_feas_i8, availT, usedT, invT,
      node_static[None, :], pods_ok[None, :], pars[None, :])
    best_s, best_i, node_max = out
    return best_s[0], best_i[0], node_max[0]


def fused_setup(a, score_params, R: int):
    """The fused path's per-solve prelude, shared by the single-device and
    sharded solvers so their parity-critical inputs cannot diverge:
    (sig_i8, inv_alloc, fused_pars, node_static). `a` needs sig_feas
    pre-composed ([T,N] bool) and node_alloc."""
    import jax.numpy as jnp

    sig_i8 = a["sig_feas"].astype(jnp.int8)
    inv_alloc = 1.0 / a["node_alloc"]
    fused_pars = pack_pars(score_params, R)
    node_static = jnp.asarray(score_params["node_static"], jnp.float32)
    return sig_i8, inv_alloc, fused_pars, node_static


def pack_pars(params, R: int):
    """Build the kernel's flat parameter vector from the solver's score
    params dict (device-friendly: one tiny array instead of many
    scalars)."""
    w = jnp.asarray(params["binpack_res_weights"], jnp.float32)
    wsum = jnp.maximum(jnp.sum(w), 1e-9)
    head = jnp.stack([
        jnp.asarray(params["binpack_weight"], jnp.float32),
        jnp.asarray(params["least_req_weight"], jnp.float32),
        jnp.asarray(params["most_req_weight"], jnp.float32),
        jnp.asarray(params["balanced_weight"], jnp.float32),
        100.0 / wsum,
    ])
    return jnp.concatenate([head, w[:R]])
